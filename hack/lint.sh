#!/usr/bin/env bash
# The repo lint gate: tpudra-lint (always), ruff + mypy (when installed).
#
# Exit nonzero on ANY finding, so this is usable as a CI gate outside make
# (`make lint` is a thin wrapper).  tpudra-lint is stdlib-only and therefore
# unconditional; ruff/mypy are optional in the hermetic image, so their
# absence is a loud skip, never a silent pass-pretender: the tpudra-lint
# rules and tests/test_lint.py::test_repo_is_clean still gate.
set -u
cd "$(dirname "$0")/.."

fail=0

echo "== tpudra-lint + tpudra-lockgraph + tpudra-effectgraph + tpudra-racegraph (python -m tpudra.analysis)"
# One invocation, one shared parse pass, one shared call graph: the
# per-module lint rules AND all whole-program rule families — the lock
# rules (LOCK-CYCLE / BLOCK-UNDER-LOCK-IP / FLOCK-INVERSION,
# docs/lock-order.md), the WAL rules (WAL-INTENT-BEFORE-EFFECT /
# WAL-RECOVERY-EXHAUSTIVE / FENCE-DOMINATES-COMMIT / STRIPE-ORDER,
# docs/effect-graph.md), and the race rules (RACE / GUARD-CONSISTENCY /
# THREAD-CONFINED-ESCAPE, docs/race-model.md) — run over the same parsed
# modules, so no graph costs a second walk of the tree.
python -m tpudra.analysis || fail=1

if python -m ruff --version >/dev/null 2>&1; then
    echo "== ruff check"
    python -m ruff check . || fail=1
elif command -v ruff >/dev/null 2>&1; then
    echo "== ruff check"
    ruff check . || fail=1
else
    echo "== ruff not installed; skipping (pip install ruff to enable)"
fi

if python -m mypy --version >/dev/null 2>&1; then
    echo "== mypy (scoped per pyproject.toml)"
    python -m mypy || fail=1
elif command -v mypy >/dev/null 2>&1; then
    echo "== mypy (scoped per pyproject.toml)"
    mypy || fail=1
else
    echo "== mypy not installed; skipping (pip install mypy to enable)"
fi

exit $fail

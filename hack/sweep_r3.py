"""Round-3 perf sweep on the real chip.  Each experiment runs in its OWN
subprocess: a failed remote compile (HTTP 500 = compile-time HBM OOM) leaks
device memory in the owning process, poisoning every later experiment, so
isolation is correctness here, not hygiene.

Run: python hack/sweep_r3.py [tag ...]       (default: all)
     python hack/sweep_r3.py --one <tag>     (internal: run one experiment)
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import BENCH_BATCH, BENCH_MODEL, PEAK_BF16_TFLOPS, _time_train_step  # noqa: E402


def model_flops(cfg, n_params, tokens):
    return tokens * (6 * n_params + 12 * cfg.n_layers * cfg.max_seq * cfg.d_model)


def measure(cfg, batch, iters=10):
    import jax

    n_params, dt, compile_s = _time_train_step(cfg, batch, iters)
    kind = jax.devices()[0].device_kind.lower()
    peak = next(p for k, p in PEAK_BF16_TFLOPS if k in kind)
    tokens = batch * (cfg.max_seq - 1)
    flops = model_flops(cfg, n_params, tokens)
    return {
        "batch": batch,
        "step_ms": round(dt * 1000, 1),
        "mfu_pct": round(flops / dt / (peak * 1e12) * 100.0, 2),
        "tokens_per_s": round(tokens / dt),
        "compile_s": round(compile_s, 1),
    }


def _decomp(which):
    """One decomposition leg per process (a shared process OOMs: three
    resident compiled programs + undonated states exceed HBM)."""
    import jax

    from tpudra.workload import model as m

    cfg = m.ModelConfig(**{**BENCH_MODEL, "attention": "splash"})
    params = m.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (BENCH_BATCH, cfg.max_seq), 0, cfg.vocab
    )
    if which == "fwd":
        fn = jax.jit(lambda p, t: m.loss_fn(p, t, cfg))
        scalar = lambda r: r  # noqa: E731
        args = (params, tokens)
    elif which == "fwdbwd":
        # Grads must be OUTPUTS or XLA DCEs the whole backward (observed:
        # [0]-indexing made fwdbwd time == fwd time exactly).
        fn = jax.jit(lambda p, t: jax.value_and_grad(m.loss_fn)(p, t, cfg))
        scalar = lambda r: r[0]  # noqa: E731
        args = (params, tokens)
    else:
        init_opt, train_step = m.make_train_step(cfg)
        opt_state = init_opt(params)
        fn = jax.jit(train_step, donate_argnums=(0, 1))
        scalar = lambda r: r[2]  # noqa: E731
        args = (params, opt_state, tokens)

    r = fn(*args)
    if which == "full":
        # donated: thread the state
        params, opt_state, _ = r
        float(scalar(r))
        t0 = time.perf_counter()
        for _ in range(10):
            params, opt_state, loss = fn(params, opt_state, args[2])
        float(loss)
    else:
        float(scalar(r))
        t0 = time.perf_counter()
        for _ in range(10):
            r = fn(*args)
        float(scalar(r))
    return {"ms": round((time.perf_counter() - t0) / 10 * 1000, 1)}


def _cfg_exp(tag, batch=BENCH_BATCH, iters=10, **kw):
    def run():
        from tpudra.workload import model as m

        cfg = m.ModelConfig(**{**BENCH_MODEL, "attention": "splash", **kw})
        return measure(cfg, batch, iters)

    return run


def _remat_policy_exp(policy_name, batch=BENCH_BATCH):
    """Flagship step with an alternative jax.checkpoint policy grafted in."""
    import jax
    from functools import partial as _partial

    from tpudra.workload import model as m

    policy = getattr(jax.checkpoint_policies, policy_name)
    orig = m.remat_layer_body

    def patched(cfg, attn_fn=None):
        return jax.checkpoint(
            _partial(m._layer, cfg, attn_fn=attn_fn), policy=policy
        )

    m.remat_layer_body = patched
    try:
        cfg = m.ModelConfig(**{**BENCH_MODEL, "attention": "splash"})
        return measure(cfg, batch, iters=10)
    finally:
        m.remat_layer_body = orig


def exp_cache():
    import jax

    jax.config.update("jax_compilation_cache_dir", "/tmp/tpudra-jaxcache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    from tpudra.workload import model as m

    cfg = m.ModelConfig(**dict(BENCH_MODEL, attention="splash"))
    cold = measure(cfg, BENCH_BATCH, iters=3)
    jax.clear_caches()
    warm = measure(cfg, BENCH_BATCH, iters=3)
    return {"cold_compile_s": cold["compile_s"], "warm_compile_s": warm["compile_s"]}


EXPERIMENTS = {
    "decomp-fwd": lambda: _decomp("fwd"),
    "decomp-fwdbwd": lambda: _decomp("fwdbwd"),
    "decomp-full": lambda: _decomp("full"),
    "remat-none-b16": _cfg_exp("remat-none-b16", remat="none"),
    "remat-none-b8": _cfg_exp("remat-none-b8", batch=8, remat="none"),
    "remat-full-b16": _cfg_exp("remat-full-b16", remat="full"),
    "attention-naive": _cfg_exp("attention-naive", attention="naive"),
    "remat-dotsbatch-b16": lambda: _remat_policy_exp("checkpoint_dots"),
    "remat-dotsbatch-b12": lambda: _remat_policy_exp("checkpoint_dots", batch=12),
    "ce-fused-b16": _cfg_exp("ce-fused-b16", ce_impl="fused"),
    "ce-fused-b24": _cfg_exp("ce-fused-b24", batch=24, ce_impl="fused"),
    "ce-fused-b32": _cfg_exp("ce-fused-b32", batch=32, ce_impl="fused"),
    "ce-fused-none-b16": _cfg_exp("ce-fused-none-b16", ce_impl="fused", remat="none"),
    "long16k-fused-b2": _cfg_exp(
        "long16k-fused-b2", batch=2, iters=5, max_seq=16384, ce_impl="fused"
    ),
    "long16k-fused-b1": _cfg_exp(
        "long16k-fused-b1", batch=1, iters=5, max_seq=16384, ce_impl="fused"
    ),
    "long16k-chunked-b2": _cfg_exp(
        "long16k-chunked-b2", batch=2, iters=5, max_seq=16384
    ),
    "cache": exp_cache,
    "base": _cfg_exp("base"),
}


def main():
    args = sys.argv[1:]
    if args and args[0] == "--one":
        tag = args[1]
        try:
            print(json.dumps({"tag": tag, **EXPERIMENTS[tag]()}), flush=True)
        except Exception as e:  # noqa: BLE001
            print(
                json.dumps({"tag": tag, "error": f"{type(e).__name__}: {e}"[:250]}),
                flush=True,
            )
        return

    tags = args or list(EXPERIMENTS)
    for tag in tags:
        t0 = time.time()
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--one", tag],
            capture_output=True, text=True, timeout=1200,
        )
        lines = [ln for ln in proc.stdout.splitlines() if ln.startswith("{")]
        if lines:
            print(lines[-1], flush=True)
        else:
            tail = (proc.stderr or proc.stdout).strip().splitlines()[-3:]
            print(
                json.dumps({"tag": tag, "error": " | ".join(tail)[:250]}),
                flush=True,
            )
        print(
            json.dumps({"tag": f"{tag}-wall", "s": round(time.time() - t0, 1)}),
            flush=True,
        )


if __name__ == "__main__":
    main()

"""Regenerate the committed golden chart renders (tests/helm_goldens/).

Run after any intentional chart change:  python hack/regen_helm_goldens.py
tests/test_helm.py::TestGoldens asserts the live render matches these
byte-for-byte.  On a machine with real helm, cross-check helmlite itself:

    helm template tpudra deployments/helm/tpu-dra-driver [-f values-custom.yaml]

and diff against the same goldens (object-level: the goldens are canonical
sorted-key YAML of every rendered document, one file per template).
"""

from __future__ import annotations

import os
import sys

import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

from helmlite import Chart  # noqa: E402

GOLDEN_DIR = os.path.join(REPO, "tests", "helm_goldens")
CHART = os.path.join(REPO, "deployments", "helm", "tpu-dra-driver")


def canonical(docs: list[dict]) -> str:
    return "\n---\n".join(
        yaml.safe_dump(d, sort_keys=True, default_flow_style=False) for d in docs
    )


def write_set(name: str, values: dict | None) -> None:
    outdir = os.path.join(GOLDEN_DIR, name)
    os.makedirs(outdir, exist_ok=True)
    for f in os.listdir(outdir):
        if f.endswith(".yaml"):
            os.unlink(os.path.join(outdir, f))
    rendered = Chart(CHART).render(values)
    for template, docs in sorted(rendered.items()):
        if not docs:
            continue
        with open(os.path.join(outdir, template), "w") as fh:
            fh.write(canonical(docs) + "\n")
    print(f"{name}: {sum(len(d) for d in rendered.values())} docs")


def custom_values() -> dict:
    with open(os.path.join(GOLDEN_DIR, "values-custom.yaml")) as f:
        return yaml.safe_load(f)


if __name__ == "__main__":
    write_set("default", None)
    write_set("custom", custom_values())

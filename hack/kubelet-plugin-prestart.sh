#!/usr/bin/env bash
# Preflight for the kubelet-plugin pod (reference
# hack/kubelet-plugin-prestart.sh analog): when the TPU stack is not set up
# on the node, fail with an actionable message in the init container's log
# instead of letting the plugin crash-loop opaquely.  Kubernetes provides
# the retry-with-backoff; this provides the diagnosis.
set -u

BACKEND="${DEVICE_BACKEND:-native}"

if [ "${BACKEND}" = "mock" ]; then
    echo "preflight: mock device backend — no hardware expected, OK"
    exit 0
fi

fail() {
    printf '%b\n' "preflight FAILED: $*" \
        "Is this a TPU node? The native backend needs the accel devices" \
        "the Cloud TPU VM image provides. If TPUs live elsewhere, set the" \
        "chart's kubeletPlugin.nodeSelector to target TPU nodes only, or" \
        "switch kubeletPlugin.deviceBackend to 'mock' for CI clusters." >&2
    exit 1
}

# 1. Device nodes: /dev/accel* (or vfio groups for passthrough nodes).
if ! ls /dev/accel* >/dev/null 2>&1 && ! ls /dev/vfio/* >/dev/null 2>&1; then
    fail "no /dev/accel* or /dev/vfio/* device nodes visible"
fi

# 2. sysfs PCI: at least one Google TPU function (vendor 0x1ae0), unless the
# VM hides sysfs (then enumeration falls back to counting device nodes).
if [ -d /sys/bus/pci/devices ]; then
    found=0
    for dev in /sys/bus/pci/devices/*; do
        [ -r "${dev}/vendor" ] || continue
        if [ "$(cat "${dev}/vendor")" = "0x1ae0" ]; then
            found=1
            break
        fi
    done
    if [ "${found}" = 0 ] && ! ls /dev/accel* >/dev/null 2>&1; then
        fail "no PCI function with Google vendor id 0x1ae0 in sysfs"
    fi
fi

echo "preflight: TPU device surface present, OK"

"""Benchmark: ResourceClaim bind p50 latency through the full driver path.

The BASELINE.json headline metric.  The reference instruments this path
(t_prep/t_prep_lock_acq log lines, gpu-kubelet-plugin/driver.go:340-386) but
publishes no numbers; its only hard bound is the e2e suite's 8 s
pod-time-to-READY ceiling for a single-GPU claim
(tests/bats/test_gpu_basic.bats:33).  We therefore report
``vs_baseline = 8000 ms / p50_ms`` — how many times faster than the
reference's accepted worst case one full bind is.

What one iteration measures (the gpu-test1 single-chip claim analog, end to
end through every real layer of this driver):

  DRA gRPC over the unix socket (the real kubelet wire protocol) → claim
  reference resolution against the apiserver → node-global flock →
  checkpoint RMW (flock + dual version write) → overlap validation → device
  prepare → transient CDI spec write → checkpoint complete → RPC response
  … then the matching unprepare.

Run: ``python bench.py`` — prints exactly one JSON line.
"""

from __future__ import annotations

import json
import statistics
import sys
import tempfile
import time

ITERS = 200
WARMUP = 10
BASELINE_BIND_MS = 8000.0  # reference e2e bound, test_gpu_basic.bats:33


def main() -> None:
    from tests.test_device_state import mk_claim
    from tpudra.devicelib import MockTopologyConfig
    from tpudra.devicelib.mock import MockDeviceLib
    from tpudra.kube import gvr
    from tpudra.kube.fake import FakeKube
    from tpudra.plugin.driver import Driver, DriverConfig
    from tpudra.plugin.grpcserver import DRAClient

    with tempfile.TemporaryDirectory() as tmp:
        lib = MockDeviceLib(
            config=MockTopologyConfig(generation="v5p"),
            state_file=f"{tmp}/hw.json",
        )
        kube = FakeKube()
        driver = Driver(
            DriverConfig(
                node_name="bench-node",
                plugin_dir=f"{tmp}/plugin",
                registry_dir=f"{tmp}/registry",
                cdi_root=f"{tmp}/cdi",
            ),
            kube,
            lib,
        )
        driver.start()
        client = DRAClient(driver.sockets.dra_socket_path)
        try:
            samples_ms: list[float] = []
            for i in range(ITERS + WARMUP):
                uid = f"bench-{i}"
                claim = mk_claim(uid, [f"tpu-{i % 4}"], name=uid)
                kube.create(gvr.RESOURCE_CLAIMS, claim, "default")
                # Timed span = what kubelet experiences: the DRA gRPC call,
                # including the plugin's claim-reference resolution.
                t0 = time.perf_counter()
                resp = client.prepare([claim])
                dt = (time.perf_counter() - t0) * 1000.0
                result = resp["claims"][uid]
                if "error" in result:
                    raise RuntimeError(f"prepare failed: {result['error']}")
                client.unprepare([claim])
                if i >= WARMUP:
                    samples_ms.append(dt)
            p50 = statistics.median(samples_ms)
        finally:
            client.close()
            driver.stop()

    print(
        json.dumps(
            {
                "metric": "resourceclaim_bind_p50_latency",
                "value": round(p50, 3),
                "unit": "ms",
                "vs_baseline": round(BASELINE_BIND_MS / p50, 1),
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())

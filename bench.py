"""Benchmark: the BASELINE.json metrics, measured on the real stack.

Prints exactly ONE JSON line:

  metric/value/unit/vs_baseline — ResourceClaim bind p50 latency through the
  full driver path (the BASELINE.json headline; the reference instruments
  this path via t_prep log lines, gpu-kubelet-plugin/driver.go:340-386, and
  its only hard bound is the e2e suite's 8 s pod-time-to-READY ceiling,
  tests/bats/test_gpu_basic.bats:33 — vs_baseline = 8000 ms / p50_ms).

  extras.tpu — flagship-model train step on the real TPU chip this
  environment provides: step time, tokens/s, and MFU vs the chip's bf16
  peak (the perf number the reference never published; its analog is the
  bats assertion that NCCL bandwidth merely *exists*,
  tests/bats/test_cd_mnnvl_workload.bats:18-52).

  extras.collectives — JAX psum GB/s (the second BASELINE.json metric).
  Runs on the real device set when more than one chip is claimed;
  otherwise on the 8-device virtual CPU mesh so the measurement hook is
  always exercised (CPU numbers are labeled as such).

What one bind iteration measures (the gpu-test1 single-chip claim analog,
end to end through every real layer of this driver):

  DRA gRPC over the unix socket (the real kubelet wire protocol) → claim
  reference resolution against the apiserver → node-global flock →
  checkpoint RMW (flock + dual version write) → overlap validation → device
  prepare → transient CDI spec write → checkpoint complete → RPC response
  … then the matching unprepare.

Run: ``python bench.py`` — prints exactly one JSON line.

Knobs (for A/B runs on the bind path):

  --iters N / --warmup N   iteration counts for the bind sections, so an A/B
                           pair can trade precision for wall time and is not
                           dominated by first-iteration cache effects
  --bind-only              run ONLY the CPU-only bind sections (headline +
                           multi-claim batch) and print their line — the
                           before/after artifact for bind-path PRs
  --apiserver-latency-ms N with --bind-only: additionally run the
                           apiserver-RTT A/B — the batch bind measured at
                           an injected N ms per-request latency
                           (FakeKube.set_latency), interleaving a
                           watch-cached arm against a per-claim-GET arm
                           (DriverConfig.claim_cache off), so the cost the
                           claim cache removes is measured, not argued
                           (`make bench-apiserver`)
  --gang [--sizes 2,4,8]   gang-reservation A/B (`make bench-gang`,
                           docs/multi-host.md): all-or-nothing gang bind
                           p50/p99 by slice size, interleaved
                           bound-vs-rollback arms through real CD plugin
                           drivers
  --trace-ab               tracing-overhead A/B (`make bench-trace`,
                           docs/tracing.md): the single-claim bind with
                           TPUDRA_TRACE=1 interleaved against disabled,
                           plus the span critical path from the traced
                           arm's log — overhead measured, attribution
                           printed
"""

from __future__ import annotations

import contextlib
import json
import os
import statistics
import subprocess
import sys
import tempfile
import time

ITERS = 200
WARMUP = 10
BATCH_CLAIMS = 8  # claims per NodePrepareResources call in the batch bench
BASELINE_BIND_MS = 8000.0  # reference e2e bound, test_gpu_basic.bats:33

# bf16 peak TFLOP/s by TPU generation (public spec sheets), keyed by
# substrings of jax Device.device_kind.
PEAK_BF16_TFLOPS = [
    ("v5 lite", 197.0),  # v5e
    ("v5e", 197.0),
    ("v5p", 459.0),
    ("v6", 918.0),  # Trillium
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 46.0),
]

# Largest config that fits a single 16 GB v5e chip with selective remat;
# ~472M params, measured ~67% MFU with the tuned splash-attention path
# (see extras.tpu for the live number).
BENCH_MODEL = dict(
    vocab=32768, d_model=2048, n_heads=16, n_layers=8, d_ff=8192, max_seq=1024
)
BENCH_BATCH = 16
# Steps per timed chain: dispatches are queued asynchronously and synced
# once at the end.  A per-step sync costs ~80 ms of round-trip through the
# remote-execution tunnel — 13% of the step — which is measurement
# overhead, not device time.
STEP_ITERS = 10


@contextlib.contextmanager
def _bench_driver(
    generation: str = "v5p",
    num_chips: int = None,
    latency_ms: float = 0.0,
    claim_cache: bool = True,
):
    """One bind-bench harness: mock-device driver + kubelet-side DRA gRPC
    client on a scratch dir.  Yields (kube, client, driver) — shared by the
    single-claim headline and the multi-claim batch sections so both always
    benchmark the identical driver configuration.  ``latency_ms`` injects
    per-request apiserver RTT (FakeKube.set_latency); ``claim_cache=False``
    is the per-claim-GET arm of the apiserver A/B."""
    from tpudra.devicelib import MockTopologyConfig
    from tpudra.devicelib.mock import MockDeviceLib
    from tpudra.kube.fake import FakeKube
    from tpudra.plugin.driver import Driver, DriverConfig
    from tpudra.plugin.grpcserver import DRAClient

    with tempfile.TemporaryDirectory() as tmp:
        topo = (
            MockTopologyConfig(generation=generation)
            if num_chips is None
            else MockTopologyConfig(generation=generation, num_chips=num_chips)
        )
        lib = MockDeviceLib(config=topo, state_file=f"{tmp}/hw.json")
        kube = FakeKube()
        if latency_ms > 0:
            kube.set_latency(latency_ms / 1000.0)
        driver = Driver(
            DriverConfig(
                # "node-a": the node-scoped claim-cache filter matches the
                # pool mk_claim stamps on allocation results.
                node_name="node-a",
                plugin_dir=f"{tmp}/plugin",
                registry_dir=f"{tmp}/registry",
                cdi_root=f"{tmp}/cdi",
                claim_cache=claim_cache,
            ),
            kube,
            lib,
        )
        driver.start()
        client = DRAClient(driver.sockets.dra_socket_path)
        try:
            # Steady state is what the section measures: resolution from a
            # synced cache, not initial-LIST fallback noise.  A sync
            # failure must be loud — a silently-degraded cached arm would
            # print a false ~0 improvement as the canonical A/B artifact.
            # (Inside the try so the started driver is torn down before
            # the scratch dir is deleted.)
            if claim_cache and not driver.wait_for_claim_cache(10):
                raise RuntimeError("claim informer failed to sync in 10s")
            yield kube, client, driver
        finally:
            client.close()
            driver.stop()


def _pcts_ms(samples: list[float], nd: int = 3, include_max: bool = False) -> dict:
    """p50/p99 (+ optional max) over millisecond samples.  p99 is the
    nearest-rank sample (== max below ~100 samples)."""
    s = sorted(samples)
    out = {
        "p50_ms": round(statistics.median(s), nd),
        "p99_ms": round(s[max(0, int(len(s) * 0.99) - 1)], nd),
    }
    if include_max:
        out["max_ms"] = round(s[-1], nd)
    return out


def bench_bind_p50(iters: int = None, warmup: int = None) -> float:
    iters = ITERS if iters is None else iters
    warmup = WARMUP if warmup is None else warmup
    from tests.test_device_state import mk_claim
    from tpudra.kube import gvr

    with _bench_driver() as (kube, client, _driver):
        samples_ms: list[float] = []
        for i in range(iters + warmup):
            uid = f"bench-{i}"
            claim = mk_claim(uid, [f"tpu-{i % 4}"], name=uid)
            kube.create(gvr.RESOURCE_CLAIMS, claim, "default")
            # Timed span = what kubelet experiences: the DRA gRPC call,
            # including the plugin's claim-reference resolution.
            t0 = time.perf_counter()
            resp = client.prepare([claim])
            dt = (time.perf_counter() - t0) * 1000.0
            result = resp["claims"][uid]
            if "error" in result:
                raise RuntimeError(f"prepare failed: {result['error']}")
            client.unprepare([claim])
            if i >= warmup:
                samples_ms.append(dt)
        return statistics.median(samples_ms)


def bench_bind_batch(
    n_claims: int = BATCH_CLAIMS, iters: int = None, warmup: int = None
) -> dict:
    """Multi-claim batch bind: ONE NodePrepareResources call carrying
    ``n_claims`` disjoint-footprint claims (kubelet batches exactly like
    this when several pods land on a node at once).  This is the section
    the batched checkpoint RMW exists for: the pre-batch engine paid two
    checkpoint read-modify-write cycles PER CLAIM; the phased engine pays
    two per BATCH, with per-claim side effects overlapped."""
    iters = max(1, (ITERS if iters is None else iters) // 4)
    warmup = max(1, (WARMUP if warmup is None else warmup) // 2)
    from tests.test_device_state import mk_claim
    from tpudra.kube import gvr

    # v5e: 8 chips per host, so an 8-claim batch gets disjoint chips.
    with _bench_driver(generation="v5e", num_chips=n_claims) as (
        kube, client, _driver,
    ):
        samples_ms: list[float] = []
        for i in range(iters + warmup):
            claims = []
            for c in range(n_claims):
                uid = f"batch-{i}-{c}"
                claim = mk_claim(uid, [f"tpu-{c}"], name=uid)
                kube.create(gvr.RESOURCE_CLAIMS, claim, "default")
                claims.append(claim)
            t0 = time.perf_counter()
            resp = client.prepare(claims)
            dt = (time.perf_counter() - t0) * 1000.0
            for claim in claims:
                uid = claim["metadata"]["uid"]
                if "error" in resp["claims"][uid]:
                    raise RuntimeError(
                        f"prepare failed: {resp['claims'][uid]['error']}"
                    )
            client.unprepare(claims)
            for claim in claims:
                kube.delete(
                    gvr.RESOURCE_CLAIMS, claim["metadata"]["name"], "default"
                )
            if i >= warmup:
                samples_ms.append(dt)
        p50 = statistics.median(samples_ms)
        return {
            "n_claims": n_claims,
            # The batch section runs fewer iterations than the headline
            # (each iteration binds n_claims claims); record the actual
            # sample count so the A/B artifact is honest about precision.
            "iters": iters,
            "batch_bind_p50_ms": round(p50, 3),
            "per_claim_p50_ms": round(p50 / n_claims, 3),
        }


def bench_bind_apiserver_ab(
    latency_ms: float,
    iters: int = None,
    warmup: int = None,
    n_claims: int = BATCH_CLAIMS,
) -> dict:
    """Apiserver-RTT A/B for the batch bind: the same batch-of-N section
    run against a FakeKube that charges ``latency_ms`` per request, once
    with the watch-backed claim cache (the production path) and once with
    per-claim GETs (``claim_cache=False``, the pre-cache path).  The two
    arms run INTERLEAVED — arm A iteration i, then arm B iteration i — so
    host-side noise lands on both arms equally instead of becoming a fake
    delta.  The uncached arm pays ~N serialized GET RTTs per bind (the
    fake charges RTT per request under its table lock, which is what a
    QPS-limited production client effectively pays); the cached arm's
    resolution is apiserver-free, so the gap is the cost the cache
    removes."""
    iters = max(1, (ITERS if iters is None else iters) // 4)
    warmup = max(1, (WARMUP if warmup is None else warmup) // 2)
    from tests.test_device_state import mk_claim
    from tpudra.kube import gvr

    def one_batch(kube, client, driver, tag: str, i: int) -> float:
        claims = []
        for c in range(n_claims):
            uid = f"ab-{tag}-{i}-{c}"
            claim = mk_claim(uid, [f"tpu-{c}"], name=uid)
            kube.create(gvr.RESOURCE_CLAIMS, claim, "default")
            claims.append(claim)
        if driver.claim_informer is not None:
            # Measure steady-state resolution, not watch-delivery latency:
            # kubelet prepares long after the claim exists.
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and any(
                driver.claim_informer.get(c["metadata"]["name"], "default") is None
                for c in claims
            ):
                time.sleep(0.001)
        t0 = time.perf_counter()
        resp = client.prepare(claims)
        dt = (time.perf_counter() - t0) * 1000.0
        for claim in claims:
            uid = claim["metadata"]["uid"]
            if "error" in resp["claims"][uid]:
                raise RuntimeError(f"prepare failed: {resp['claims'][uid]['error']}")
        client.unprepare(claims)
        for claim in claims:
            kube.delete(gvr.RESOURCE_CLAIMS, claim["metadata"]["name"], "default")
        return dt

    samples: dict[str, list[float]] = {"cached": [], "uncached": []}
    with _bench_driver(
        "v5e", n_claims, latency_ms=latency_ms, claim_cache=True
    ) as cached_arm, _bench_driver(
        "v5e", n_claims, latency_ms=latency_ms, claim_cache=False
    ) as uncached_arm:
        arms = {"cached": cached_arm, "uncached": uncached_arm}
        for i in range(iters + warmup):
            for tag, (kube, client, driver) in arms.items():
                dt = one_batch(kube, client, driver, tag, i)
                if i >= warmup:
                    samples[tag].append(dt)
    cached_p50 = statistics.median(samples["cached"])
    uncached_p50 = statistics.median(samples["uncached"])
    return {
        "latency_ms": latency_ms,
        "n_claims": n_claims,
        "iters": iters,
        "cached_batch_p50_ms": round(cached_p50, 3),
        "uncached_batch_p50_ms": round(uncached_p50, 3),
        "improvement_ms": round(uncached_p50 - cached_p50, 3),
    }


def bench_trace_ab(iters: int = None, warmup: int = None) -> dict:
    """Traced-vs-disabled bind A/B plus the span critical path
    (docs/tracing.md): the single-claim headline run with arms
    INTERLEAVED — iteration i traced (TPUDRA_TRACE=1, spans appended to a
    scratch log), iteration i untraced — so the overhead number is the
    tracing layer's own cost, not box noise.  The traced arm's log is then
    fed through tools/trace_report's phase aggregation, so the artifact
    carries the ATTRIBUTION (mean ms per bind phase along the
    rpc.NodePrepareResources tree) next to the p50s — future perf PRs cite
    which phase moved, not just that the p50 did."""
    iters = ITERS if iters is None else iters
    warmup = WARMUP if warmup is None else warmup
    from tests.test_device_state import mk_claim
    from tpudra import trace
    from tpudra.kube import gvr

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "tools"))
    try:
        from trace_report import phase_means
    finally:
        sys.path.pop(0)

    samples: dict[str, list[float]] = {"traced": [], "disabled": []}
    prev = {
        k: os.environ.get(k) for k in (trace.ENV_TRACE, trace.ENV_TRACE_LOG)
    }
    with tempfile.TemporaryDirectory(prefix="tpudra-trace-ab-") as tmp:
        log = os.path.join(tmp, "trace.jsonl")
        try:
            with _bench_driver() as (kube, client, _driver):
                for i in range(iters + warmup):
                    for arm in ("disabled", "traced"):
                        if arm == "traced":
                            os.environ[trace.ENV_TRACE] = "1"
                            os.environ[trace.ENV_TRACE_LOG] = log
                        else:
                            os.environ.pop(trace.ENV_TRACE, None)
                        uid = f"trace-{arm}-{i}"
                        claim = mk_claim(uid, [f"tpu-{i % 4}"], name=uid)
                        kube.create(gvr.RESOURCE_CLAIMS, claim, "default")
                        t0 = time.perf_counter()
                        resp = client.prepare([claim])
                        dt = (time.perf_counter() - t0) * 1000.0
                        if "error" in resp["claims"][uid]:
                            raise RuntimeError(resp["claims"][uid]["error"])
                        client.unprepare([claim])
                        kube.delete(gvr.RESOURCE_CLAIMS, uid, "default")
                        if i >= warmup:
                            samples[arm].append(dt)
        finally:
            for k, v in prev.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
            trace.reset_for_tests()
        phases = phase_means(trace.read_log(log), "rpc.NodePrepareResources")
    traced_p50 = statistics.median(samples["traced"])
    disabled_p50 = statistics.median(samples["disabled"])
    return {
        "iters": iters,
        "bind_p50_traced_ms": round(traced_p50, 3),
        "bind_p50_disabled_ms": round(disabled_p50, 3),
        "overhead_pct": round(
            100.0 * (traced_p50 - disabled_p50) / disabled_p50, 1
        ),
        "critical_path": phases,
    }


def bench_checkpoint_churn(iters: int = None) -> dict:
    """Checkpoint-persistence churn A/B (ISSUE 5, `make bench-checkpoint`):
    N resident claims × M status-flip mutates through CheckpointManager,
    interleaved WAL-vs-snapshot arms (``journal=True`` vs the
    ``--no-journal`` behavior), plus the 8-way group-commit fsync count —
    medians of 3 waves.  The claims the journal makes measurable:

    - bytes written per mutate in the journal arm are independent of the
      resident-claim count (O(delta)); the snapshot arm re-encodes every
      resident claim per mutate (O(state));
    - 8 concurrent mutators cost ≤2 fsyncs end to end (group commit: the
      first leader commits its own entry; everyone who enqueued while it
      held the flock rides the SECOND leader's single batch) against 16
      for the snapshot arm (per mutate: temp-file fsync + the
      rename-durability directory fsync)."""
    import statistics as st
    import threading

    from prometheus_client import REGISTRY

    from tpudra.plugin.checkpoint import (
        PREPARE_COMPLETED,
        PREPARE_STARTED,
        Checkpoint,
        CheckpointManager,
        PreparedClaim,
        PreparedDevice,
        PreparedDeviceGroup,
    )

    M = 60 if iters is None else max(4, iters)

    def metric(name: str, labels: dict = None) -> float:
        return REGISTRY.get_sample_value(name, labels or {}) or 0.0

    def all_fsyncs() -> float:
        return sum(
            metric("tpudra_checkpoint_fsyncs_total", {"kind": k})
            for k in ("journal", "snapshot", "dir")
        )

    def mk_resident(n: int) -> Checkpoint:
        cp = Checkpoint()
        for i in range(n):
            uid = f"res-{i}"
            cp.prepared_claims[uid] = PreparedClaim(
                uid=uid, namespace="default", name=uid,
                status=PREPARE_COMPLETED,
                groups=[PreparedDeviceGroup(devices=[PreparedDevice(
                    canonical_name=f"tpu-{i % 8}", type="chip",
                    pool_name="node-a", request_names=["r0"],
                    cdi_device_ids=[f"tpu.google.com/tpu={uid}-tpu-{i % 8}"],
                    attributes={"uuid": f"uuid-{i}"},
                )])],
            )
        return cp

    def flip(cp: Checkpoint, uid: str) -> None:
        claim = cp.prepared_claims[uid]
        claim.status = (
            PREPARE_STARTED
            if claim.status == PREPARE_COMPLETED
            else PREPARE_COMPLETED
        )

    out: dict = {"mutates_per_arm": M, "resident": {}}
    bytes_kind = {"journal": "journal", "snapshot": "snapshot"}
    for n_resident in (8, 128):
        with tempfile.TemporaryDirectory() as tmp:
            mgrs = {
                "journal": CheckpointManager(f"{tmp}/wal", journal=True),
                "snapshot": CheckpointManager(f"{tmp}/snap", journal=False),
            }
            for mgr in mgrs.values():
                mgr.write(mk_resident(n_resident))
            samples = {arm: [] for arm in mgrs}
            bytes0 = {
                arm: metric(
                    "tpudra_checkpoint_bytes_written_total",
                    {"kind": bytes_kind[arm]},
                )
                for arm in mgrs
            }
            # Iteration-interleaved arms: host noise lands on both equally.
            for i in range(M):
                for arm, mgr in mgrs.items():
                    uid = f"res-{i % n_resident}"
                    t0 = time.perf_counter()
                    mgr.mutate(lambda cp, uid=uid: flip(cp, uid), touched=[uid])
                    samples[arm].append((time.perf_counter() - t0) * 1000.0)
            out["resident"][str(n_resident)] = {
                arm: {
                    "mutate_p50_ms": round(st.median(samples[arm]), 3),
                    "bytes_per_mutate": round(
                        (
                            metric(
                                "tpudra_checkpoint_bytes_written_total",
                                {"kind": bytes_kind[arm]},
                            )
                            - bytes0[arm]
                        )
                        / M
                    ),
                }
                for arm in mgrs
            }
    j8 = out["resident"]["8"]["journal"]["bytes_per_mutate"]
    j128 = out["resident"]["128"]["journal"]["bytes_per_mutate"]
    s8 = out["resident"]["8"]["snapshot"]["bytes_per_mutate"]
    s128 = out["resident"]["128"]["snapshot"]["bytes_per_mutate"]
    out["journal_bytes_ratio_128_vs_8"] = round(j128 / j8, 2) if j8 else None
    out["snapshot_bytes_ratio_128_vs_8"] = round(s128 / s8, 2) if s8 else None

    # 8-way group-commit fsync count, medians of 3 waves per arm: every
    # wave is 8 barrier-aligned threads each committing one status flip.
    def one_wave(mgr: CheckpointManager) -> float:
        barrier = threading.Barrier(8)
        errors: list = []

        def worker(i: int) -> None:
            try:
                barrier.wait(timeout=30)
                uid = f"res-{i}"
                mgr.mutate(lambda cp, uid=uid: flip(cp, uid), touched=[uid])
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append(e)

        f0 = all_fsyncs()
        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        if errors:
            raise RuntimeError(f"group-commit wave failed: {errors[0]}")
        return all_fsyncs() - f0

    group: dict = {}
    with tempfile.TemporaryDirectory() as tmp:
        for arm in ("journal", "snapshot"):
            mgr = CheckpointManager(f"{tmp}/{arm}", journal=(arm == "journal"))
            mgr.write(mk_resident(8))
            # Warmup: the first-ever append pays a one-time directory
            # fsync for the WAL file's creation; waves measure steady state.
            mgr.mutate(lambda cp: flip(cp, "res-0"), touched=["res-0"])
            mgr.mutate(lambda cp: flip(cp, "res-0"), touched=["res-0"])
            waves = sorted(one_wave(mgr) for _ in range(3))
            group[arm] = {
                "fsyncs_per_8claim_wave_median": waves[1],
                "fsyncs_per_8claim_wave_all": waves,
            }
    out["group_commit"] = group
    return out


def bench_storage_degraded(iters: int = None, warmup: int = None) -> dict:
    """Degraded-mode shed A/B (`make bench-storage`, docs/bind-path.md
    "Storage fault contract"): healthy bind p50 vs the fail-fast shed
    path with the checkpoint dir faulted ENOSPC through the storage seam.
    The acceptance bar is BOUNDED shed latency — the typed retryable
    error must come back without flock/checkpoint/disk work — plus proof
    the node converges back to healthy binds after heal."""
    import errno

    from tests.test_device_state import mk_claim
    from tpudra import storage
    from tpudra.kube import gvr

    iters = ITERS if iters is None else iters
    warmup = WARMUP if warmup is None else warmup
    with _bench_driver() as (kube, client, driver):
        healthy_ms: list[float] = []
        for i in range(iters + warmup):
            uid = f"sb-h-{i}"
            claim = mk_claim(uid, [f"tpu-{i % 4}"], name=uid)
            kube.create(gvr.RESOURCE_CLAIMS, claim, "default")
            t0 = time.perf_counter()
            resp = client.prepare([claim])
            dt = (time.perf_counter() - t0) * 1000.0
            if "error" in resp["claims"][uid]:
                raise RuntimeError(f"prepare failed: {resp['claims'][uid]}")
            client.unprepare([claim])
            kube.delete(gvr.RESOURCE_CLAIMS, uid, "default")
            if i >= warmup:
                healthy_ms.append(dt)
        # Fault the checkpoint dir and flip the driver degraded with one
        # full-cost failing bind; every later attempt is a shed.
        plan = storage.FaultPlan()
        plugin_dir = driver._config.plugin_dir  # noqa: SLF001 — bench introspection
        plan.add(op="write", path=plugin_dir, err=errno.ENOSPC, times=None)
        plan.add(op="fsync", path=plugin_dir, err=errno.ENOSPC, times=None)
        storage.install_fault_plan(plan)
        shed_ms: list[float] = []
        try:
            first = mk_claim("sb-flip", ["tpu-0"], name="sb-flip")
            kube.create(gvr.RESOURCE_CLAIMS, first, "default")
            resp = client.prepare([first])
            if "error" not in resp["claims"]["sb-flip"]:
                raise RuntimeError("faulted bind unexpectedly succeeded")
            if not driver.storage_degraded:
                raise RuntimeError("driver never entered degraded mode")
            for i in range(iters + warmup):
                uid = f"sb-d-{i}"
                claim = mk_claim(uid, [f"tpu-{i % 4}"], name=uid)
                kube.create(gvr.RESOURCE_CLAIMS, claim, "default")
                t0 = time.perf_counter()
                resp = client.prepare([claim])
                dt = (time.perf_counter() - t0) * 1000.0
                err = resp["claims"][uid].get("error", "")
                if storage.DEGRADED_ERROR_PREFIX not in err:
                    raise RuntimeError(f"expected typed shed error, got: {err!r}")
                kube.delete(gvr.RESOURCE_CLAIMS, uid, "default")
                if i >= warmup:
                    shed_ms.append(dt)
        finally:
            plan.heal()
            storage.clear_fault_plan()
        # Heal convergence: the supervisor's probe + compaction must bring
        # real binds back.
        deadline = time.monotonic() + 30
        while driver.storage_degraded and time.monotonic() < deadline:
            time.sleep(0.05)
        recovered = not driver.storage_degraded
        if recovered:
            post = mk_claim("sb-post", ["tpu-1"], name="sb-post")
            kube.create(gvr.RESOURCE_CLAIMS, post, "default")
            resp = client.prepare([post])
            recovered = "error" not in resp["claims"]["sb-post"]
            if recovered:
                client.unprepare([post])
        shed = _pcts_ms(shed_ms, include_max=True)
        return {
            "iters": iters,
            "healthy_bind_p50_ms": round(statistics.median(healthy_ms), 3),
            "shed_p50_ms": shed["p50_ms"],
            "shed_p99_ms": shed["p99_ms"],
            "shed_max_ms": shed["max_ms"],
            "recovered_after_heal": recovered,
        }


def bench_failover(iters: int = None, warmup: int = None) -> dict:
    """Controller-failover A/B (`make bench-failover`, docs/ha.md).

    Two measurements:

    - **time-to-new-leader**: lease pairs on a FakeKube; the leader dies
      (``crash()`` — SIGKILL-shaped, the standby must wait out the full
      expiry window) or hands off gracefully (``release()``); p50/p99 of
      the standby's acquisition latency, per arm.  Measured at a scaled
      lease geometry (duration/renew below) — production geometries scale
      linearly since expiry dominates the crash arm.

    - **bind under a 429 storm vs quiet, truly interleaved**: the same
      single-claim bind (per-claim-GET resolution, so every bind touches
      the apiserver) with the storm arm's resolve refused once with
      429-plus-Retry-After before succeeding; the measured time includes
      the kubelet-role retry paced by the shared Backoff.  The artifact
      is the within-run delta: what one shed round-trip costs a bind.
    """
    import threading as threading_mod

    from tests.test_device_state import mk_claim
    from tpudra.controller.lease import LeaseElector
    from tpudra.kube import gvr
    from tpudra.kube.fake import ApiErrorPlan, FakeKube

    iters = ITERS if iters is None else iters
    warmup = WARMUP if warmup is None else warmup
    lease_iters = min(iters, 10)
    dur_s, renew_s = 0.4, 0.08
    out: dict = {
        "iters": iters,
        "lease_iters": lease_iters,
        "lease_duration_ms": dur_s * 1000.0,
        "renew_interval_ms": renew_s * 1000.0,
    }

    def one_failover(i: int, graceful: bool) -> float:
        kube = FakeKube()
        stop_a, stop_b = threading_mod.Event(), threading_mod.Event()
        mk = lambda ident: LeaseElector(  # noqa: E731
            kube,
            identity=ident,
            name="bench-controller",
            namespace="default",
            lease_duration_s=dur_s,
            renew_interval_s=renew_s,
        )
        a, b = mk(f"a-{i}"), mk(f"b-{i}")
        try:
            a.start(stop_a)
            deadline = time.monotonic() + 10
            while not a.is_leader and time.monotonic() < deadline:
                time.sleep(0.005)
            b.start(stop_b)
            time.sleep(renew_s * 3)  # b observes the live lease
            t0 = time.perf_counter()
            if graceful:
                stop_a.set()  # run()'s finally releases the lease
            else:
                a.crash()  # lease left held: b waits out expiry
            deadline = time.monotonic() + 10
            while not b.is_leader and time.monotonic() < deadline:
                time.sleep(0.002)
            if not b.is_leader:
                raise RuntimeError("standby never acquired the lease")
            return (time.perf_counter() - t0) * 1000.0
        finally:
            stop_a.set()
            stop_b.set()

    crash_ms = [one_failover(i, graceful=False) for i in range(lease_iters)]
    handoff_ms = [one_failover(i, graceful=True) for i in range(lease_iters)]

    out["time_to_new_leader"] = {
        "crash": _pcts_ms(crash_ms, nd=1, include_max=True),
        "graceful": _pcts_ms(handoff_ms, nd=1, include_max=True),
    }

    # -- bind under a 429 storm vs quiet, interleaved -----------------------
    retry_after_s = 0.02
    out["storm_retry_after_ms"] = retry_after_s * 1000.0
    with _bench_driver(claim_cache=False) as (kube, client, driver):
        from tpudra.backoff import Backoff

        quiet_ms: list[float] = []
        storm_ms: list[float] = []

        def one_bind(i: int, storm: bool) -> float:
            uid = f"fo-{'s' if storm else 'q'}-{i}"
            claim = mk_claim(uid, [f"tpu-{i % 4}"], name=uid)
            kube.create(gvr.RESOURCE_CLAIMS, claim, "default")
            if storm:
                # Deterministic storm: this bind's first resolve GET is
                # shed with 429 + Retry-After, the retry lands.
                kube.set_error_plan(
                    ApiErrorPlan().fail(
                        verb="get", code=429, times=1,
                        retry_after_s=retry_after_s,
                    )
                )
            backoff = Backoff(retry_after_s, 0.5)
            t0 = time.perf_counter()
            try:
                for _ in range(20):
                    resp = client.prepare([claim])
                    if "error" not in resp["claims"][uid]:
                        break
                    # The kubelet role: a retryable error re-prepares on
                    # the shared jittered backoff (the Retry-After floor
                    # travels typed in-process; over gRPC the hint is in
                    # the error string and the backoff base covers it).
                    time.sleep(max(backoff.next_delay(), retry_after_s))
                else:
                    raise RuntimeError(f"bind never granted: {resp}")
                return (time.perf_counter() - t0) * 1000.0
            finally:
                kube.set_error_plan(None)
                client.unprepare([claim])
                kube.delete(gvr.RESOURCE_CLAIMS, uid, "default")

        for i in range(iters + warmup):
            q = one_bind(i, storm=False)
            s = one_bind(i, storm=True)
            if i >= warmup:
                quiet_ms.append(q)
                storm_ms.append(s)
        out["bind_quiet"] = _pcts_ms(quiet_ms, nd=1, include_max=True)
        out["bind_429_storm"] = _pcts_ms(storm_ms, nd=1, include_max=True)
        out["storm_overhead_p50_ms"] = round(
            statistics.median(storm_ms) - statistics.median(quiet_ms), 1
        )
    return out


def bench_partition_ab(iters: int = None, warmup: int = None) -> dict:
    """Fractional-chip A/B (`make bench-partition`, docs/partitioning.md):

    (1) INTERLEAVED bind latency — whole-chip claims vs dynamic-partition
    claims (partition create + per-partition WAL records on the bind
    path) through the same DRA gRPC → flock → checkpoint → CDI path, p50
    and p99 per arm.  The acceptance bar: partitioned bind within 2× the
    whole-chip p50.

    (2) PACKING — fill the node to saturation with whole-chip claims,
    then with small (half-chip) partition claims: resident claims per
    chip is the packing-efficiency ratio (the "millions of users" shape —
    many small inference claims per chip), and a timed churn window
    yields claims placed per chip-hour for each arm."""
    from tests.test_device_state import mk_claim, opaque
    from tpudra import featuregates as fg
    from tpudra.kube import gvr

    fg.feature_gates().set_from_map({fg.DYNAMIC_PARTITIONING: True})
    iters = ITERS if iters is None else iters
    warmup = WARMUP if warmup is None else warmup
    api_v = "resource.tpu.google.com/v1beta1"
    part_cfg = [opaque({"apiVersion": api_v, "kind": "TpuPartitionConfig"})]
    chips = 4

    def part_name(chip: int, placement: int) -> str:
        return f"tpu-{chip}-part-1c.4hbm-{placement}-{placement * 4}"

    with _bench_driver(num_chips=chips) as (kube, client, driver):
        def one(uid: str, devices: list[str], configs) -> float:
            claim = mk_claim(uid, devices, configs=configs, name=uid)
            kube.create(gvr.RESOURCE_CLAIMS, claim, "default")
            t0 = time.perf_counter()
            resp = client.prepare([claim])
            dt = (time.perf_counter() - t0) * 1000.0
            if "error" in resp["claims"][uid]:
                raise RuntimeError(f"prepare failed: {resp['claims'][uid]}")
            client.unprepare([claim])
            kube.delete(gvr.RESOURCE_CLAIMS, uid, "default")
            return dt

        chip_ms: list[float] = []
        part_ms: list[float] = []
        for i in range(iters + warmup):
            # Interleaved arms: box drift hits both equally.
            dt_c = one(f"bp-c-{i}", [f"tpu-{i % chips}"], None)
            dt_p = one(f"bp-p-{i}", [part_name(i % chips, 0)], part_cfg)
            if i >= warmup:
                chip_ms.append(dt_c)
                part_ms.append(dt_p)

        # -- packing: saturation residency, then churn throughput --------
        def fill(mk_devices, configs, prefix: str) -> list[dict]:
            resident = []
            for k in range(chips * 8):  # far past any real capacity
                uid = f"{prefix}-{k}"
                devices = mk_devices(k)
                if devices is None:
                    break
                claim = mk_claim(uid, devices, configs=configs, name=uid)
                kube.create(gvr.RESOURCE_CLAIMS, claim, "default")
                resp = client.prepare([claim])
                if "error" in resp["claims"][uid]:
                    kube.delete(gvr.RESOURCE_CLAIMS, uid, "default")
                    break
                resident.append(claim)
            return resident

        def drain(resident: list[dict]) -> None:
            for claim in resident:
                uid = claim["metadata"]["uid"]
                client.unprepare([claim])
                kube.delete(gvr.RESOURCE_CLAIMS, uid, "default")

        whole = fill(
            lambda k: [f"tpu-{k}"] if k < chips else None, None, "pk-c"
        )
        chip_resident = len(whole)
        drain(whole)
        placements = [
            part_name(c, p) for c in range(chips) for p in (0, 1)
        ]
        small = fill(
            lambda k: [placements[k]] if k < len(placements) else None,
            part_cfg, "pk-p",
        )
        part_resident = len(small)
        drain(small)

        def churn(mk_devices, configs, prefix: str, window_s: float = 2.0) -> int:
            """Bind+release small claims for a fixed wall window; the
            count normalizes to claims placed per chip-hour."""
            placed = 0
            deadline = time.perf_counter() + window_s
            while time.perf_counter() < deadline:
                one(f"{prefix}-{placed}", mk_devices(placed), configs)
                placed += 1
            return placed

        window_s = 2.0
        chip_placed = churn(
            lambda k: [f"tpu-{k % chips}"], None, "ch-c", window_s
        )
        part_placed = churn(
            lambda k: [placements[k % len(placements)]], part_cfg, "ch-p",
            window_s,
        )
        per_hour = 3600.0 / window_s / chips
        return {
            "iters": iters,
            "whole_chip": _pcts_ms(chip_ms),
            "partition": _pcts_ms(part_ms),
            "bind_ratio_p50": round(
                statistics.median(part_ms) / max(1e-9, statistics.median(chip_ms)), 2
            ),
            "packing": {
                "chips": chips,
                "whole_chip_resident": chip_resident,
                "partition_resident": part_resident,
                "efficiency": round(part_resident / max(1, chip_resident), 2),
                "whole_chip_claims_per_chip_hour": round(chip_placed * per_hour),
                "partition_claims_per_chip_hour": round(part_placed * per_hour),
            },
        }


def bench_bind_partition_p50() -> dict:
    """Dynamic-partition bind p50 through the NATIVE C++ library.

    The reference's hot prepare op is MIG GI+CI creation on silicon
    (device_state.go:763, O(seconds)); our analog is TensorCore partition
    create/rollback in libtpuinfo.  This measures the same DRA gRPC →
    flock → checkpoint → partition-create → CDI path as the headline
    metric, but every iteration crosses the ctypes→C ABI boundary and
    mutates the library's crash-consistent partition state.
    """
    import tempfile

    from tpudra.devicelib.native import DEFAULT_LIB_PATH

    if not os.path.exists(
        os.environ.get("TPUINFO_LIBRARY_PATH", DEFAULT_LIB_PATH)
    ):
        return {"skipped": "libtpuinfo.so not built (make -C native)"}
    try:
        from tests.test_e2e import Scheduler, find, load_spec
        from tpudra import featuregates as fg
        from tpudra.devicelib.native import NativeDeviceLib
        from tpudra.kube import gvr
        from tpudra.kube.fake import FakeKube
        from tpudra.plugin.driver import Driver, DriverConfig
        from tpudra.plugin.grpcserver import DRAClient

        fg.feature_gates().set_from_map({fg.DYNAMIC_PARTITIONING: True})
        with tempfile.TemporaryDirectory() as tmp:
            cfg_path = os.path.join(tmp, "tpuinfo.cfg")
            with open(cfg_path, "w") as f:
                f.write(
                    "generation=v5p\nnum_chips=4\nhost_index=0\nnum_hosts=1\n"
                    f"slice_uuid=bench\nstate_file={tmp}/tpuinfo-state\n"
                )
            lib = NativeDeviceLib(config_path=cfg_path)
            kube = FakeKube()
            driver = Driver(
                DriverConfig(
                    node_name="bench-node",
                    plugin_dir=f"{tmp}/plugin",
                    registry_dir=f"{tmp}/registry",
                    cdi_root=f"{tmp}/cdi",
                ),
                kube,
                lib,
            )
            driver.start()
            driver.publish_resources()
            client = DRAClient(driver.sockets.dra_socket_path)
            try:
                rct = find(load_spec("tpu-test-partition.yaml"), "ResourceClaimTemplate")[0]
                samples_ms: list[float] = []
                iters = ITERS // 2
                for i in range(iters + WARMUP):
                    uid = f"part-{i}"
                    claim = Scheduler(kube).allocate(rct, uid, "default", uid)
                    t0 = time.perf_counter()
                    resp = client.prepare([claim])
                    dt = (time.perf_counter() - t0) * 1000.0
                    if "error" in resp["claims"][uid]:
                        raise RuntimeError(resp["claims"][uid]["error"])
                    client.unprepare([claim])
                    kube.delete(gvr.RESOURCE_CLAIMS, uid, "default")
                    if i >= WARMUP:
                        samples_ms.append(dt)
                return {
                    "bind_p50_ms": round(statistics.median(samples_ms), 3),
                    "path": "DRA gRPC -> flock -> checkpoint -> "
                    "libtpuinfo partition create (C ABI) -> CDI",
                }
            finally:
                client.close()
                driver.stop()
    except Exception as e:  # noqa: BLE001 — bench must always print its line
        return {"error": f"{type(e).__name__}: {e}"[:300]}



def _enable_compile_cache() -> str:
    """Wire the persistent XLA compilation cache (a 45 s cold compile on the
    flagship is real money on a driver whose pitch is claim→training in
    seconds).  Returns the cache dir."""
    import jax

    cache_dir = os.environ.get("TPUDRA_JAX_CACHE_DIR", "/tmp/tpudra-jaxcache")
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    return cache_dir


def _time_train_step(cfg, batch: int, iters: int, chains: int = 2):
    """Shared timing harness for the train-step benches: init, one
    compile+sync step, then ``chains`` independent chains of ``iters``
    queued dispatches, each synced once (a per-step sync costs ~80 ms of
    round-trip through the remote-execution tunnel), keeping the BEST
    chain.  Best-of-N exists because these numbers become the round
    artifact: a host-side stall (another process on the bench box, tunnel
    hiccup) inflates a single chain and then reads as a model regression —
    exactly what happened to the r3 seq-8192 figure, measured during a
    concurrent full-suite soak (BASELINE.md "measurement noise").
    Returns (n_params, seconds_per_step, compile_seconds)."""
    import jax

    from tpudra.workload import model as m

    params = m.init_params(jax.random.PRNGKey(0), cfg)
    n_params = sum(x.size for x in jax.tree.leaves(params))
    init_opt, train_step = m.make_train_step(cfg)
    opt_state = init_opt(params)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (batch, cfg.max_seq), 0, cfg.vocab
    )
    step = jax.jit(train_step, donate_argnums=(0, 1))
    t0 = time.perf_counter()
    params, opt_state, loss = step(params, opt_state, tokens)
    float(loss)  # forces device sync (block_until_ready is not enough
    # through the axon remote-execution tunnel)
    compile_s = time.perf_counter() - t0
    best = float("inf")
    for _ in range(max(1, chains)):
        t0 = time.perf_counter()
        for _ in range(iters):
            params, opt_state, loss = step(params, opt_state, tokens)
        float(loss)
        best = min(best, (time.perf_counter() - t0) / iters)
    return n_params, best, compile_s


def _peak_for(kind: str) -> float | None:
    """bf16 peak for a jax device_kind, or None when the generation is not
    in the table (one matching rule for every section's MFU)."""
    for key, peak in PEAK_BF16_TFLOPS:
        if key in kind.lower():
            return peak
    return None


def _model_metrics(cfg, batch: int, n_params: int, dt: float, kind: str) -> dict:
    """MFU accounting shared by every train-step section.  Counts model
    FLOPs as 6N per token plus the quadratic attention term (12·L·S·D) —
    comparisons against plain-6N numbers are apples-to-oranges."""
    tokens_per_step = batch * (cfg.max_seq - 1)
    flops = tokens_per_step * (
        6 * n_params + 12 * cfg.n_layers * cfg.max_seq * cfg.d_model
    )
    out = {
        "step_ms": round(dt * 1000.0, 1),
        "tokens_per_s": round(tokens_per_step / dt),
        "model_tflops_per_s": round(flops / dt / 1e12, 1),
    }
    peak = _peak_for(kind)
    if peak is not None:
        out["peak_bf16_tflops"] = peak
        out["mfu_pct"] = round(flops / dt / (peak * 1e12) * 100.0, 1)
    return out


def bench_tpu_step() -> dict:
    """Flagship train step on whatever accelerator jax provides."""
    try:
        import jax

        from tpudra.workload import model as m

        dev = jax.devices()[0]
        kind = dev.device_kind
        if dev.platform == "cpu":
            # A ~472M-param train step on a host CPU takes minutes-to-hours;
            # this section only means anything on an accelerator.
            return {"skipped": "no accelerator (jax platform is cpu)"}
        cache_dir = _enable_compile_cache()
        # Explicit splash: this is a deliberately single-device program,
        # and "auto" conservatively declines the pallas path when the host
        # exposes multiple chips (model.py use_flash_attention).
        cfg = m.ModelConfig(**BENCH_MODEL, attention="splash")
        n_params, dt, compile_s = _time_train_step(cfg, BENCH_BATCH, STEP_ITERS)
        out = {
            "device_kind": kind,
            "platform": dev.platform,
            "model": dict(BENCH_MODEL, batch=BENCH_BATCH, params_m=round(n_params / 1e6, 1)),
            "compile_s": round(compile_s, 1),
            "compile_cache_dir": cache_dir,
            **_model_metrics(cfg, BENCH_BATCH, n_params, dt, kind),
        }
        return out
    except Exception as e:  # noqa: BLE001 — bench must always print its line
        return {"error": f"{type(e).__name__}: {e}"[:300]}


def bench_long_context(seq: int, batch: int) -> dict:
    """Long-context train step on the real chip.

    Past ~seq 2048 the naive attention's f32 score tensor cannot fit HBM —
    the model's pallas splash path is what makes the step exist at all.
    The reference has no analog; the closest is its MNNVL claim that the
    fabric extends the memory domain — this is the single-chip version of
    "long context actually trains".
    """
    try:
        import jax

        from tpudra.workload import model as m

        if jax.devices()[0].platform == "cpu":
            return {"skipped": "no accelerator"}
        _enable_compile_cache()
        cfg = m.ModelConfig(
            vocab=32768, d_model=2048, n_heads=16, n_layers=8, d_ff=8192,
            max_seq=seq, attention="splash",
        )
        n_params, dt, _ = _time_train_step(cfg, batch, iters=5)
        return {
            "seq": cfg.max_seq,
            "batch": batch,
            "attention": "pallas splash, fused bwd (naive cannot compile at this length)",
            **_model_metrics(cfg, batch, n_params, dt, jax.devices()[0].device_kind),
        }
    except Exception as e:  # noqa: BLE001
        return {"error": f"{type(e).__name__}: {e}"[:300]}


def bench_ab(
    remat: str = None,
    attention: str = None,
    ce_impl: str = None,
    opt_impl: str = None,
) -> dict:
    """A/B leg at the flagship config: one knob changed from the tuned
    default, so every tuning claim in model.py's docstring is backed by a
    driver-captured artifact (remat=dots / splash attention are the
    defaults the headline number uses)."""
    try:
        import jax

        from tpudra.workload import model as m

        if jax.devices()[0].platform == "cpu":
            return {"skipped": "no accelerator"}
        _enable_compile_cache()
        kw = dict(BENCH_MODEL, attention=attention or "splash")
        if remat:
            kw["remat"] = remat
        if ce_impl:
            kw["ce_impl"] = ce_impl
        if opt_impl:
            kw["opt_impl"] = opt_impl
        cfg = m.ModelConfig(**kw)
        n_params, dt, _ = _time_train_step(cfg, BENCH_BATCH, iters=5)
        return {
            "remat": cfg.remat,
            "attention": cfg.attention,
            "ce_impl": cfg.ce_impl,
            "opt_impl": cfg.opt_impl,
            **_model_metrics(
                cfg, BENCH_BATCH, n_params, dt, jax.devices()[0].device_kind
            ),
        }
    except Exception as e:  # noqa: BLE001
        return {"error": f"{type(e).__name__}: {e}"[:300]}


def bench_moe() -> dict:
    """Sparse (Switch-MoE) flagship variant on the real chip: same layer
    count as the dense bench at half width with 8 experts — more params at
    a fraction of the per-token FLOPs (top-1 routing).  Single chip, so no
    expert parallelism here; the ep-sharded path is exercised on the
    virtual mesh by dryrun_multichip and the workload tests."""
    try:
        import jax

        from tpudra.workload import model as m

        if jax.devices()[0].platform == "cpu":
            return {"skipped": "no accelerator"}
        _enable_compile_cache()
        cfg = m.ModelConfig(
            vocab=32768, d_model=1024, n_heads=8, n_layers=8, d_ff=4096,
            max_seq=1024, attention="splash", num_experts=8,
        )
        batch = 8
        n_params, dt, _ = _time_train_step(cfg, batch, iters=5)
        tokens_per_step = batch * (cfg.max_seq - 1)

        # Expert-FLOP accounting (VERDICT r4 #6): 6·n_params over-counts a
        # top-1 Switch model by the (num_experts−1) expert FFNs each token
        # never touches.  The dense-comparable MFU numerator uses ACTIVE
        # params (one expert FFN per layer; router fully, every token
        # computes it); the capacity padding XLA really computes (dispatch
        # to E·C slots, capacity_factor 1.25, lane-aligned) is reported
        # separately as hardware throughput + overhead, so the
        # sparse-vs-dense comparison is normalized, not flattered.
        ffn_params_per_expert = 2 * cfg.d_model * cfg.d_ff  # w1 + w2 (moe.py:59)
        n_active = n_params - cfg.n_layers * (cfg.num_experts - 1) * ffn_params_per_expert
        model_flops = tokens_per_step * (
            6 * n_active + 12 * cfg.n_layers * cfg.max_seq * cfg.d_model
        )
        from tpudra.workload.moe import MoEConfig

        moe_cfg = MoEConfig(
            d_model=cfg.d_model, d_ff=cfg.d_ff, num_experts=cfg.num_experts
        )
        # Per-layer dispatch population: the train step feeds the model
        # tokens[:, :-1] (model.py loss_fn), so each layer routes
        # batch·(max_seq−1) tokens — same base as tokens_per_step above.
        routed_tokens = tokens_per_step
        capacity_slots = cfg.num_experts * moe_cfg.capacity(routed_tokens)
        padded_extra = max(0, capacity_slots - routed_tokens)
        computed_flops = model_flops + (
            6 * ffn_params_per_expert * padded_extra * cfg.n_layers
        )
        out = {
            "num_experts": cfg.num_experts,
            "params_m": round(n_params / 1e6, 1),
            "active_params_m": round(n_active / 1e6, 1),
            "batch": batch,
            "seq": cfg.max_seq,
            "step_ms": round(dt * 1000.0, 1),
            "tokens_per_s": round(tokens_per_step / dt),
            "model_tflops_per_s": round(model_flops / dt / 1e12, 1),
            "hw_tflops_per_s": round(computed_flops / dt / 1e12, 1),
            "capacity_padding_overhead_pct": round(
                100.0 * padded_extra / routed_tokens, 1
            ),
        }
        peak = _peak_for(jax.devices()[0].device_kind)
        if peak is not None:
            out["peak_bf16_tflops"] = peak
            out["mfu_pct"] = round(model_flops / dt / (peak * 1e12) * 100.0, 1)
        return out
    except Exception as e:  # noqa: BLE001
        return {"error": f"{type(e).__name__}: {e}"[:300]}


def bench_native_corroboration() -> dict:
    """Cross-check NativeDeviceLib/libtpuinfo against the live TPU runtime
    (VERDICT r2 #3): whatever jax attests about the chip — kind, count,
    coordinates, HBM when exposed — must agree with what the C++ library
    enumerates.  On hosts whose sysfs carries the TPU PCI functions the
    library enumerates natively; behind the remote-execution tunnel the
    accelerator type comes from the probe and the check then exercises the
    generation table's derived attributes against the runtime's."""
    from tpudra.devicelib.native import DEFAULT_LIB_PATH, NativeDeviceLib
    from tpudra.devicelib.runtimeprobe import probe_runtime

    if not os.path.exists(
        os.environ.get("TPUINFO_LIBRARY_PATH", DEFAULT_LIB_PATH)
    ):
        return {"skipped": "libtpuinfo.so not built (make -C native)"}
    probe = probe_runtime()
    if probe is None:
        return {"available": False, "reason": "no live TPU runtime"}
    try:
        with tempfile.TemporaryDirectory() as tmp:
            config_source = "host sysfs/metadata"
            try:
                lib = NativeDeviceLib(runtime_probe=probe)
                if not lib.enumerate_chips():
                    lib.close()
                    raise RuntimeError("no chips via host enumeration")
            except Exception:  # noqa: BLE001 — remote tunnel: no local TPU functions
                config_source = (
                    "runtime probe (host sysfs has no TPU functions; "
                    "the TPU is behind a remote-execution tunnel)"
                )
                cfg = os.path.join(tmp, "tpuinfo.cfg")
                with open(cfg, "w") as f:
                    f.write(
                        f"generation={probe.generation}\n"
                        f"num_chips={probe.num_devices}\n"
                        "host_index=0\nnum_hosts=1\nslice_uuid=bench-live\n"
                        f"state_file={tmp}/tpuinfo-state\n"
                    )
                lib = NativeDeviceLib(config_path=cfg, runtime_probe=probe)
            try:
                out = lib.corroborate_runtime()
                # Platform attestation for multi-process sharing (VERDICT
                # r4 #5): can a second process open the chip while held?
                # Probed live on the device node when one is visible;
                # "unknown" behind the remote tunnel.
                out["multiprocess_mode"] = lib.multiprocess_mode()
            finally:
                lib.close()
            out["config_source"] = config_source
            return out
    except Exception as e:  # noqa: BLE001
        return {"error": f"{type(e).__name__}: {e}"[:300]}


def bench_scale() -> dict:
    """Production-scale machinery under load (VERDICT r3 #6), CPU-only:

    - **churn**: 100 nodes x 500 mixed claims (1-chip / 2-chip / dynamic
      partition) through the full prepare→unprepare path concurrently —
      bind p50/p99 under contention, aggregate prepares/s.  Claims are
      slotted (node = i%100, device by wave) so same-device conflicts are
      rare; a straggler CAN still make waves collide on one device (tasks
      200 apart), so overlap refusals retry briefly like kubelet would —
      counted in ``overlap_retries`` — and what's measured is machinery
      contention (flock, checkpoint RMW, CDI IO).
    - **controller**: 100 ComputeDomains reconciled by the real controller
      (informers + keyed queue + rate limiter) → reconciles/s to full
      DaemonSet+RCT fan-out.
    - **informer**: cache entries + approximate heap for the 100-slice
      watch (tracemalloc).
    - **qps**: the client-side token bucket under an 8-thread storm of 300
      LISTs against the HTTP fake apiserver — held == effective rate
      stayed at/under the configured 50 QPS (+burst amortized).
    """
    import concurrent.futures as cf
    import threading
    import tracemalloc

    N_NODES, N_CLAIMS, WORKERS = 100, 500, 16
    out: dict = {"nodes": N_NODES, "claims": N_CLAIMS, "workers": WORKERS}
    try:
        from tpudra import featuregates as fg
        from tpudra.devicelib.mock import MockDeviceLib
        from tpudra.devicelib.topology import MockTopologyConfig
        from tpudra.kube import gvr
        from tpudra.kube.fake import FakeKube
        from tpudra.kube.informer import Informer
        from tpudra.plugin.driver import Driver, DriverConfig

        fg.feature_gates().set_from_map({fg.DYNAMIC_PARTITIONING: True})
        kube = FakeKube()
        with tempfile.TemporaryDirectory() as tmp:
            drivers = []
            for n in range(N_NODES):
                lib = MockDeviceLib(
                    config=MockTopologyConfig(generation="v5p"),
                    state_file=f"{tmp}/hw{n}.json",
                )
                drivers.append(
                    Driver(
                        DriverConfig(
                            node_name=f"node-{n:03d}",
                            plugin_dir=f"{tmp}/p{n}",
                            registry_dir=f"{tmp}/r{n}",
                            cdi_root=f"{tmp}/c{n}",
                        ),
                        kube,
                        lib,
                    )
                )
            t0 = time.perf_counter()
            for d in drivers:
                d.publish_resources()
            out["publish_100_nodes_s"] = round(time.perf_counter() - t0, 2)

            # Informer watching the 100 published slices.
            tracemalloc.start()
            stop = threading.Event()
            inf = Informer(kube, gvr.RESOURCE_SLICES)
            inf.start(stop)
            inf.wait_for_sync()
            current, peak = tracemalloc.get_traced_memory()
            tracemalloc.stop()
            out["informer"] = {
                "cache_entries": len(inf.list()),
                "heap_mb": round(current / 1e6, 2),
                "heap_peak_mb": round(peak / 1e6, 2),
            }

            part_cfg = [{
                "source": "FromClass",
                "requests": [],
                "opaque": {
                    "driver": "tpu.google.com",
                    "parameters": {
                        "apiVersion": "resource.tpu.google.com/v1beta1",
                        "kind": "TpuPartitionConfig",
                    },
                },
            }]

            from tests.test_device_state import mk_claim

            overlap_retries = [0]
            retry_lock = threading.Lock()

            def one(i: int) -> float:
                d = drivers[i % N_NODES]
                wave = i // N_NODES
                uid = f"scale-{i}"
                if wave == 2:
                    claim = mk_claim(
                        uid, ["tpu-0-part-1c.4hbm-0-0"],
                        configs=part_cfg, name=uid,
                    )
                elif wave == 3:
                    claim = mk_claim(uid, ["tpu-2", "tpu-3"], name=uid)
                else:
                    claim = mk_claim(uid, [f"tpu-{wave % 4}"], name=uid)
                for _attempt in range(100):
                    t0 = time.perf_counter()
                    resp = d.prepare_resource_claims([claim])
                    dt = (time.perf_counter() - t0) * 1000.0
                    err = resp["claims"][uid].get("error", "")
                    if not err:
                        d.unprepare_resource_claims([{"uid": uid}])
                        return dt
                    if "overlaps" not in err:
                        raise RuntimeError(err)
                    # A straggler holding the colliding grant: retry the
                    # way kubelet would, without polluting the latency
                    # sample with the wait.
                    with retry_lock:
                        overlap_retries[0] += 1
                    time.sleep(0.02)
                raise RuntimeError(f"claim {uid} never cleared its overlap")

            t0 = time.perf_counter()
            with cf.ThreadPoolExecutor(max_workers=WORKERS) as pool:
                lat = sorted(pool.map(one, range(N_CLAIMS)))
            wall = time.perf_counter() - t0
            stop.set()
            out["churn"] = {
                "bind_p50_ms": round(lat[len(lat) // 2], 3),
                "bind_p90_ms": round(lat[int(len(lat) * 0.90)], 3),
                "bind_p99_ms": round(lat[int(len(lat) * 0.99)], 3),
                "bind_max_ms": round(lat[-1], 3),
                "prepares_per_s": round(N_CLAIMS / wall, 1),
                "wall_s": round(wall, 2),
                "overlap_retries": overlap_retries[0],
            }

        # Controller reconcile fan-out over 100 ComputeDomains.
        from tests.test_computedomain import mk_cd, mk_node
        from tpudra.controller.controller import Controller, ManagerConfig

        ckube = FakeKube()
        for n in range(N_NODES):
            mk_node(ckube, f"node-{n:03d}")
        c = Controller(ckube, ManagerConfig(driver_namespace="tpudra-system"))
        cstop = threading.Event()
        ct = threading.Thread(target=c.run, args=(cstop,), daemon=True)
        t0 = time.perf_counter()
        for i in range(N_NODES):
            mk_cd(ckube, name=f"cd-{i:03d}", num_nodes=2)
        ct.start()
        deadline = time.monotonic() + 120
        want = N_NODES
        while time.monotonic() < deadline:
            n_ds = len(ckube.list(gvr.DAEMONSETS, "tpudra-system").get("items", []))
            if n_ds >= want:
                break
            time.sleep(0.05)
        elapsed = time.perf_counter() - t0
        n_ds = len(ckube.list(gvr.DAEMONSETS, "tpudra-system").get("items", []))
        cstop.set()
        out["controller"] = {
            "compute_domains": N_NODES,
            "daemonsets_created": n_ds,
            "full_fanout_s": round(elapsed, 2),
            "reconciles_per_s": round(n_ds / elapsed, 1) if elapsed else 0,
        }

        # Sustained-QPS limiter under a storm, over the real HTTP client.
        from tpudra.kube.client import KubeClient
        from tpudra.kube.httpserver import FakeKubeServer

        qps_limit, burst, n_req = 50.0, 25, 300
        with FakeKubeServer() as server:
            qc = KubeClient(server.url, qps=qps_limit, burst=burst)
            t0 = time.perf_counter()
            with cf.ThreadPoolExecutor(max_workers=8) as pool:
                list(
                    pool.map(
                        lambda _: qc.list(gvr.NODES), range(n_req)
                    )
                )
            elapsed = time.perf_counter() - t0
        effective = (n_req - burst) / elapsed
        out["qps"] = {
            "limit": qps_limit,
            "burst": burst,
            "requests": n_req,
            "elapsed_s": round(elapsed, 2),
            "effective_qps": round(effective, 1),
            # 10% slack for scheduling jitter; the storm must not pierce
            # the bucket.
            "held": effective <= qps_limit * 1.1,
        }
        return out
    except Exception as e:  # noqa: BLE001 — bench must always print its line
        out["error"] = f"{type(e).__name__}: {e}"[:300]
        return out


def bench_gang(sizes: list[int] = None, iters: int = None) -> dict:
    """Gang-bind latency A/B (`make bench-gang`, docs/multi-host.md):
    all-or-nothing slice reservation (controller/gang.py) through REAL CD
    plugin drivers, for 2/4/8-node slices.

    Two arms per size, TRULY interleaved (bound iter i, then rollback
    iter i — same drivers, same checkpoint files, so filesystem-cache
    drift taxes both arms equally):

      bound     — every member binds; reserve() wall time measured, the
                  (untimed) release tears down between iters
      rollback  — the LAST member's bind fails (its ComputeDomain is
                  unknown on that node), so reserve() pays N-1 binds plus
                  the full unwind; the measured time is the price of the
                  all-or-nothing guarantee on the failure path

    Checkpoints live on the in-memory scratch base (the gang section
    measures control-plane work, not host fsync — bench-checkpoint owns
    that axis).
    """
    import shutil
    import tempfile

    from tpudra.controller.gang import (
        GangBindError,
        GangMember,
        GangReservationManager,
    )
    from tpudra.kube import gvr as gvr_mod
    from tpudra.kube.fake import FakeKube
    from tpudra.plugin.checkpoint import CheckpointManager
    from tpudra.sim.cluster import latency_summary, scratch_base
    from tpudra.sim.multihost import (
        DriverGangBinder,
        build_cd_stack,
        close_cd_stack,
        make_channel_claim,
        make_compute_domain,
    )

    sizes = sizes or [2, 4, 8]
    iters = iters if iters is not None else 15
    max_nodes = max(sizes)
    base = tempfile.mkdtemp(prefix="tpudra-gangbench-", dir=scratch_base())
    out: dict = {"sizes": sizes, "iters": iters}
    drivers: dict = {}
    gang_cp = None
    try:
        kube = FakeKube()
        nodes = [f"gb-node-{i}" for i in range(max_nodes)]
        for name in nodes:
            kube.create(gvr_mod.NODES, {"metadata": {"name": name}, "spec": {}})
        drivers = build_cd_stack(kube, nodes, base, num_hosts=max_nodes)
        gang_cp = CheckpointManager(os.path.join(base, "gangs"))
        mgr = GangReservationManager(gang_cp, DriverGangBinder(drivers))

        def mk_domain(uid: str, member_nodes: list[str]) -> None:
            kube.create(
                gvr_mod.COMPUTE_DOMAINS,
                make_compute_domain(uid, uid, member_nodes),
                "default",
            )

        seq = [0]

        def one_gang(k: int, rollback_arm: bool) -> float:
            seq[0] += 1
            gang_id = f"bench-{seq[0]}"
            uid = f"{gang_id}-uid"
            member_nodes = nodes[:k]
            mk_domain(uid, member_nodes)
            members = [
                GangMember(node=n, claim_uid=f"{gang_id}-m{j}")
                for j, n in enumerate(member_nodes)
            ]
            claims = {}
            for j, m in enumerate(members):
                # Rollback arm: the LAST member's claim names a domain
                # this cluster has never seen → its bind fails after the
                # first k-1 members are bound, forcing the full unwind.
                domain = (
                    "no-such-domain"
                    if rollback_arm and j == len(members) - 1
                    else uid
                )
                claims[m.claim_uid] = make_channel_claim(
                    m.claim_uid, m.node, domain
                )
            t0 = time.perf_counter()
            try:
                mgr.reserve(gang_id, members, claims)
                dt = (time.perf_counter() - t0) * 1000.0
                mgr.release(gang_id)
            except GangBindError:
                dt = (time.perf_counter() - t0) * 1000.0
            kube.delete(gvr_mod.COMPUTE_DOMAINS, uid, "default")
            return dt

        for k in sizes:
            bound_ms: list[float] = []
            rollback_ms: list[float] = []
            one_gang(k, False)  # warmup (checkpoint files, label paths)
            for _ in range(iters):
                bound_ms.append(one_gang(k, False))
                rollback_ms.append(one_gang(k, True))
            out[f"nodes_{k}"] = {
                "bound": latency_summary(bound_ms),
                "rollback": latency_summary(rollback_ms),
            }
    except Exception as e:  # noqa: BLE001 — bench must always print its line
        out["error"] = f"{type(e).__name__}: {e}"[:300]
    finally:
        close_cd_stack(drivers)
        if gang_cp is not None:
            try:
                gang_cp.close()
            except Exception:  # tpudra-lint: disable=EXC-SWALLOW the scratch dir is removed next line; a failed shutdown compaction has no one to report to
                pass
        shutil.rmtree(base, ignore_errors=True)
    return out


def bench_cluster_scale(
    nodes_list: list[int] = None,
    churn: int = None,
    seed: int = 0,
    waves: int = 2,
) -> dict:
    """Cluster-scale A/B (`make bench-cluster`, docs/cluster-scale.md): N
    simulated nodes (each a real in-process plugin driver with its own
    claim informer) + one real controller against one accounted FakeKube,
    under seeded claim churn and ComputeDomain spec flips.

    Two arms per node count, TRULY interleaved (arm A wave i, then arm B
    wave i, both harnesses alive throughout so idle-thread load taxes both
    arms equally):

      fixed  — serialize-once watch fan-out, priority lanes + per-key fair
               dispatch, bulk slice publication (this PR)
      legacy — per-watcher event deepcopy, single-heap FIFO queue,
               3-requests-per-node publication (the pre-PR control plane)

    Reported per arm: bind p50/p99 pooled across waves, controller
    reconcile p50/p99 (CD flip waves), apiserver requests by verb + QPS
    over the churn windows, informer event lag, watch fan-out stats,
    startup publication cost, and the flapping-CD injection (max quiet-key
    wait under one hot key — the starvation bound)."""
    from tpudra.sim.cluster import ClusterScaleConfig, ClusterScaleSim, latency_summary

    nodes_list = nodes_list or [8, 128, 256]
    out: dict = {"seed": seed, "waves": waves}
    for n_nodes in nodes_list:
        # Per-wave claim count scales DOWN with node count: the per-event
        # fan-out cost grows with N, and the wave exists to sample bind
        # latency under that load, not to saturate the host for an hour.
        n_churn = churn if churn is not None else max(12, min(32, 4096 // n_nodes))
        arm_cfg = {
            "fixed": dict(fair=True, share_watch_events=True, bulk_publish=True),
            "legacy": dict(fair=False, share_watch_events=False, bulk_publish=False),
        }
        sims = {}
        report: dict = {"churn_per_wave": n_churn}
        try:
            for tag, knobs in arm_cfg.items():
                sims[tag] = ClusterScaleSim(
                    ClusterScaleConfig(
                        nodes=n_nodes,
                        churn_claims=n_churn,
                        compute_domains=8,
                        seed=seed,
                        **knobs,
                    )
                ).start()
                sims[tag].seed_compute_domains()
            bind: dict[str, list] = {t: [] for t in sims}
            bind_errors: dict[str, int] = {t: 0 for t in sims}
            first_error: dict[str, str] = {}
            reconcile: dict[str, list] = {t: [] for t in sims}
            verbs: dict[str, dict] = {t: {} for t in sims}
            churn_wall: dict[str, float] = {t: 0.0 for t in sims}
            for wave in range(waves):
                for tag, sim in sims.items():
                    # Churn + CD flips in flight together: reconcile p99
                    # under live claim fan-out is the measured scenario.
                    def run(s=sim, t=tag, i=wave):
                        churn_out, cd_out = s.combined_wave(
                            f"{t}-{i}", flip_to=(i % 2) + 1
                        )
                        return {"churn": churn_out, "cd": cd_out}

                    w = sim.measured_window(run)
                    bind[tag].extend(w["churn"].pop("samples_ms"))
                    # Errored binds return early and FAST — pooling their
                    # samples without the error count would let a broken
                    # arm report a flattering p99.
                    bind_errors[tag] += w["churn"].get("bind_errors", 0)
                    if "first_error" in w["churn"]:
                        first_error.setdefault(tag, w["churn"]["first_error"])
                    reconcile[tag].extend(w["cd"].pop("samples_ms"))
                    for verb, count in w["apiserver"]["by_verb"].items():
                        verbs[tag][verb] = verbs[tag].get(verb, 0) + count
                    churn_wall[tag] += w["apiserver"]["wall_s"]
            for tag, sim in sims.items():
                flap = sim.flapping_injection(victims=16)
                total = sum(verbs[tag].values())
                bind_summary = latency_summary(bind[tag])
                bind_summary["errors"] = bind_errors[tag]
                if tag in first_error:
                    bind_summary["first_error"] = first_error[tag]
                report[tag] = {
                    "bind": bind_summary,
                    "reconcile": latency_summary(reconcile[tag]),
                    "apiserver": {
                        "by_verb": verbs[tag],
                        "qps": round(total / max(churn_wall[tag], 1e-9), 1),
                    },
                    "event_lag": sim.lag_report(),
                    "publish": sim.publish_stats,
                    "watch": sim.watch_report(),
                    "flap": flap,
                }
        except Exception as e:  # noqa: BLE001 — bench must always print its line
            report["error"] = f"{type(e).__name__}: {e}"[:300]
        finally:
            for sim in sims.values():
                try:
                    sim.close()
                except Exception as e:  # noqa: BLE001 — teardown must visit every arm
                    print(
                        f"cluster-scale: arm teardown failed: {e}",
                        file=sys.stderr,
                    )
        out[str(n_nodes)] = report
    return out


def bench_claim_to_jax() -> dict:
    """Close the north-star loop on the real chip (BASELINE.json's end
    state: "the pod sees exactly the chips granted by the ResourceClaim"):
    prepare a claim with the NATIVE backend on this host, spawn a process
    under the merged CDI environment exactly as containerd would build it,
    and assert the real libtpu sees the granted chip — count, generation,
    coordinates — and can execute a jitted op.  Records {granted, seen,
    matched} (reference analog: the README demo pod against the real host
    GPU + test_gpu_basic.bats:33's pod-READY assertion)."""
    from tpudra.devicelib.native import DEFAULT_LIB_PATH
    from tpudra.devicelib.runtimeprobe import probe_runtime

    if not os.path.exists(
        os.environ.get("TPUINFO_LIBRARY_PATH", DEFAULT_LIB_PATH)
    ):
        return {"skipped": "libtpuinfo.so not built (make -C native)"}
    probe = probe_runtime()
    if probe is None:
        return {"skipped": "no live TPU runtime on this host"}
    try:
        from tests.test_device_state import mk_claim
        from tpudra.sim.cdi import apply_cdi
        from tpudra.devicelib.native import NativeDeviceLib
        from tpudra.kube import gvr
        from tpudra.kube.fake import FakeKube
        from tpudra.plugin.driver import Driver, DriverConfig

        with tempfile.TemporaryDirectory() as tmp:
            try:
                lib = NativeDeviceLib(runtime_probe=probe)
                if not lib.enumerate_chips():
                    lib.close()
                    raise RuntimeError("no chips via host enumeration")
            except Exception:  # noqa: BLE001 — remote tunnel: no local TPU fns
                cfg = os.path.join(tmp, "tpuinfo.cfg")
                with open(cfg, "w") as f:
                    f.write(
                        f"generation={probe.generation}\n"
                        f"num_chips={probe.num_devices}\n"
                        "host_index=0\nnum_hosts=1\nslice_uuid=live\n"
                    )
                lib = NativeDeviceLib(config_path=cfg, runtime_probe=probe)
            all_chips = lib.enumerate_chips()
            # Grant exactly the chips the runtime can address: behind the
            # remote-execution tunnel the attested slice has more chips
            # than the session can reach, and the contract under test is
            # "the pod sees exactly the GRANTED chips" — a subset grant of
            # the addressable ones (one chip is enough, VERDICT r3 #2).
            n_addressable = max(1, min(probe.num_devices, len(all_chips)))
            if probe.coords:
                want = [list(c) for c in probe.coords if len(c) == 3]
                chips = [
                    c for c in all_chips if list(c.coords) in want
                ] or all_chips[:n_addressable]
            else:
                chips = all_chips[:n_addressable]
            chips = chips[:n_addressable]
            granted_names = [f"tpu-{c.index}" for c in chips]
            kube = FakeKube()
            driver = Driver(
                DriverConfig(
                    node_name="bench-node",
                    plugin_dir=f"{tmp}/plugin",
                    registry_dir=f"{tmp}/registry",
                    cdi_root=f"{tmp}/cdi",
                ),
                kube,
                lib,
            )
            driver.start()
            try:
                uid = "claim-to-jax"
                claim = mk_claim(uid, granted_names, name=uid)
                kube.create(gvr.RESOURCE_CLAIMS, claim, "default")
                resp = driver.prepare_resource_claims([claim])
                result = resp["claims"][uid]
                if "error" in result:
                    raise RuntimeError(result["error"])
                spec = driver.state._cdi.read_claim_spec(uid)
                ids = [
                    i for dev in result["devices"] for i in dev["cdiDeviceIDs"]
                ]
                cdi_env, nodes, _ = apply_cdi(spec, ids)

                # The workload process: the host env (the tunnel/runtime
                # pinning must survive — a constructed env would strand the
                # child on CPU jax) overlaid with exactly the edits the
                # container runtime would inject.
                code = (
                    "import json\n"
                    "from tpudra.workload.envspec import ClaimEnv\n"
                    "env = ClaimEnv.from_environ()\n"
                    "import jax, jax.numpy as jnp\n"
                    "devs = jax.devices()\n"
                    "x = jnp.ones((256, 256), jnp.bfloat16)\n"
                    "y = jax.jit(lambda a: a @ a)(x)\n"
                    "out = {\n"
                    " 'platform': devs[0].platform,\n"
                    " 'num_devices': len(devs),\n"
                    " 'device_kind': devs[0].device_kind,\n"
                    " 'runtime_coords': [list(getattr(d, 'coords', ()) or ()) for d in devs],\n"
                    " 'visible': env.visible_devices,\n"
                    " 'claim_coords': [list(c) for c in env.coords],\n"
                    " 'claim_generation': env.generation,\n"
                    " 'matmul_ok': bool(jnp.isfinite(y.astype(jnp.float32)).all()),\n"
                    "}\n"
                    "print('RESULT:' + json.dumps(out))\n"
                )
                from tpudra.devicelib.runtimeprobe import hardware_env

                child_env = hardware_env()  # strip pytest's CPU pinning
                child_env.update(cdi_env)
                child_env["PYTHONPATH"] = (
                    os.path.dirname(os.path.abspath(__file__))
                    + os.pathsep
                    + child_env.get("PYTHONPATH", "")
                )
                proc = subprocess.run(
                    [sys.executable, "-c", code],
                    env=child_env, capture_output=True, text=True, timeout=600,
                )
                seen = {}
                for line in (proc.stdout or "").splitlines():
                    if line.startswith("RESULT:"):
                        seen = json.loads(line[len("RESULT:"):])
                if not seen:
                    tail = (proc.stderr or proc.stdout).strip().splitlines()[-3:]
                    raise RuntimeError(
                        f"workload rc={proc.returncode}: " + " | ".join(tail)[:250]
                    )
                driver.unprepare_resource_claims([{"uid": uid}])

                granted = {
                    "devices": granted_names,
                    "generation": chips[0].generation,
                    "coords": [list(c.coords) for c in chips],
                    "device_nodes": nodes,
                }
                from tpudra.devicelib.runtimeprobe import RuntimeProbe

                # Generation via the canonical device_kind mapping (the
                # runtime spells one generation several ways: "TPU v5
                # lite" / "v5e"; "TPU v6 lite" / "Trillium").
                seen_gen = RuntimeProbe(
                    device_kind=seen.get("device_kind", "")
                ).generation
                # Chip count via DISTINCT coords where the runtime exposes
                # them: 2-core generations report one jax device per core,
                # so raw device count is cores, not chips.
                distinct = {
                    tuple(c) for c in seen.get("runtime_coords", []) if c
                }
                if distinct:
                    count_ok = distinct == {tuple(c) for c in granted["coords"]}
                else:
                    n = seen.get("num_devices", 0)
                    count_ok = n > 0 and n % len(chips) == 0
                matched = (
                    seen.get("platform") == "tpu"
                    and count_ok
                    and seen_gen == chips[0].generation
                    and seen.get("matmul_ok") is True
                    and seen.get("claim_coords") == granted["coords"]
                )
                return {"granted": granted, "seen": seen, "matched": matched}
            finally:
                driver.stop()
    except Exception as e:  # noqa: BLE001 — bench must always print its line
        return {"error": f"{type(e).__name__}: {e}"[:300]}


def bench_collectives_multichip() -> dict:
    """psum GB/s on a real multi-chip ICI set.  Runs as a --section
    subprocess (bounded timeout, no device state in the orchestrator — a
    hung relay or a chip held by the orchestrator would poison the later
    claim_to_jax/native sections) and only when the probe saw >1 device on
    a non-cpu backend: a CPU mesh with forced host devices must never
    publish a GB/s figure dressed as the BASELINE psum metric."""
    try:
        import jax

        if jax.default_backend() == "cpu":
            return {"skipped": "cpu backend — no ICI to measure"}
        n = len(jax.devices())
        if n < 2:
            return {"skipped": f"only {n} device(s) — psum GB/s needs a real ICI mesh"}
        from tpudra.workload.collectives import bench_psum
        from tpudra.workload.envspec import mesh_from_devices

        mesh = mesh_from_devices(("data",), (n,), devices=jax.devices())
        r = bench_psum(mesh, "data", mib_per_device=64, iters=10)
        return {
            "environment": f"{n}x {jax.devices()[0].device_kind} (ICI)",
            "psum_bus_gbps": round(r.bus_gbps, 2),
            "psum_algo_gbps": round(r.algo_gbps, 2),
        }
    except Exception as e:  # noqa: BLE001
        return {"error": f"{type(e).__name__}: {e}"[:300]}


def bench_collectives_hook() -> dict:
    """Single-chip fallback: exercise the psum measurement path on the
    8-device virtual CPU mesh in a bounded subprocess (proving the hook
    runs) without publishing a bandwidth number.  Touches jax only in the
    child, so a hung device relay cannot wedge the orchestrator."""
    code = (
        "import jax, json\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from tpudra.workload.collectives import bench_psum\n"
        "from tpudra.workload.envspec import mesh_from_devices\n"
        "mesh = mesh_from_devices(('data',), (8,), devices=jax.devices()[:8])\n"
        "r = bench_psum(mesh, 'data', mib_per_device=8, iters=5)\n"
        "print(json.dumps({'ok': r.bus_gbps > 0}))\n"
    )
    repo_dir = os.path.dirname(os.path.abspath(__file__))
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS=os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8",
        PYTHONPATH=repo_dir + os.pathsep + os.environ.get("PYTHONPATH", ""),
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            env=env,
            capture_output=True,
            text=True,
            timeout=300,
        )
        ok = False
        if proc.returncode == 0 and proc.stdout.strip():
            ok = json.loads(proc.stdout.strip().splitlines()[-1]).get("ok", False)
        out = {
            "skipped": "no multi-chip hardware (psum GB/s needs a real ICI mesh)",
            "hook_exercised": bool(ok),
        }
        if not ok:
            # A broken collectives path must stay distinguishable from the
            # expected single-chip skip in the round artifact.
            tail = (proc.stderr or "").strip().splitlines()[-3:]
            out["error"] = (
                f"hook run failed rc={proc.returncode}: " + " | ".join(tail)[:250]
            )
        return out
    except Exception as e:  # noqa: BLE001
        return {"error": f"{type(e).__name__}: {e}"[:300]}


# ---------------------------------------------------------------------------
# Section runner: every section that touches the accelerator runs in its own
# subprocess.  A failed remote compile (HTTP 500 = compile-time HBM OOM)
# leaks device memory in the owning process — isolation keeps one section's
# failure from poisoning the rest (observed: a single OOM turns every later
# in-process section into RESOURCE_EXHAUSTED).
# ---------------------------------------------------------------------------

SECTIONS = {
    "checkpoint": bench_checkpoint_churn,
    "tpu": bench_tpu_step,
    "long8192": lambda: bench_long_context(8192, 2),
    "long16384": lambda: bench_long_context(16384, 1),
    "moe": bench_moe,
    "ab_remat_full": lambda: bench_ab(remat="full"),
    "ab_naive": lambda: bench_ab(attention="naive"),
    "ab_ce_fused": lambda: bench_ab(ce_impl="fused"),
    "ab_opt_fused": lambda: bench_ab(opt_impl="fused"),
    "native": bench_native_corroboration,
    "claim_to_jax": bench_claim_to_jax,
    "scale": bench_scale,
    "collectives": bench_collectives_multichip,
}


def _probe_device_backend(timeout: float = 180.0) -> dict:
    """Bounded reachability probe for the configured jax backend.

    The probe initializes the backend in a SUBPROCESS with a hard timeout:
    on this environment the device relay (axon) can hang indefinitely
    during backend init, and any in-process jax.devices() would wedge the
    whole bench with zero output (the BENCH_r04 rc=124/empty-tail failure
    mode).  A timed-out probe yields a machine-readable diagnostic and the
    orchestrator then skips every device-touching section instead of
    burning their per-section timeouts one by one."""
    code = (
        "import json, jax\n"
        "ds = jax.devices()\n"
        "print(json.dumps({'backend': jax.default_backend(),"
        " 'device_kind': ds[0].device_kind, 'n_devices': len(ds)}))\n"
    )
    t0 = time.perf_counter()
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return {
            "reachable": False,
            "error": f"backend init timed out after {timeout:.0f}s "
            "(device relay hung?)",
        }
    for line in reversed(proc.stdout.strip().splitlines() or [""]):
        if line.startswith("{"):
            try:
                out = json.loads(line)
                out.update(reachable=True, probe_s=round(time.perf_counter() - t0, 1))
                return out
            except ValueError:
                break
    tail = (proc.stderr or proc.stdout).strip().splitlines()[-3:]
    return {
        "reachable": False,
        "error": f"probe rc={proc.returncode}: " + " | ".join(tail)[:200],
    }


def _run_section(name: str, timeout: float = 1200.0) -> dict:
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--section", name],
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        # One wedged section (a remote-compile retry loop, say) must not
        # take the whole bench line with it.
        return {"error": f"section {name} timed out after {timeout:.0f}s"}
    for line in reversed(proc.stdout.strip().splitlines() or [""]):
        if line.startswith("{"):
            try:
                return json.loads(line)
            except ValueError:
                break  # killed mid-print: report via the rc/tail path
    tail = (proc.stderr or proc.stdout).strip().splitlines()[-3:]
    return {"error": f"section {name} rc={proc.returncode}: " + " | ".join(tail)[:250]}


# ---------------------------------------------------------------------------
# Artifact shape.  The driver captures only a bounded tail of stdout
# (BENCH_r03.json arrived truncated mid-object, parsed=null — the headline
# numbers existed only in prose that round).  So the printed line carries a
# COMPACT summary (scalar per section), and the full per-section detail is
# written to BENCH_DETAILS_r{N}.json in the repo, committed alongside.
# ---------------------------------------------------------------------------

# Scalars worth carrying on the one-line summary, wherever they appear.
SUMMARY_KEYS = (
    "device_kind", "seq", "batch", "step_ms", "tokens_per_s",
    "model_tflops_per_s", "mfu_pct", "compile_s", "warm_compile_s",
    "bind_p50_ms", "bind_p90_ms", "bind_p99_ms", "available", "consistent",
    "n_claims", "batch_bind_p50_ms", "per_claim_p50_ms",
    "checked_count", "psum_bus_gbps", "hook_exercised", "num_experts",
    "matched", "prepares_per_s", "reconciles_per_s", "effective_qps",
    "held", "cache_entries", "heap_mb", "multiprocess_mode",
    "mutate_p50_ms", "bytes_per_mutate", "journal_bytes_ratio_128_vs_8",
    "snapshot_bytes_ratio_128_vs_8", "fsyncs_per_8claim_wave_median",
    # incremental-line payloads (probe + headline)
    "metric", "value", "unit", "vs_baseline",
    "reachable", "backend", "n_devices", "probe_s",
)


def _summarize(section) -> dict:
    """Compact view of one section: error/skip markers, whitelisted
    scalars, and recursively-summarized sub-dicts."""
    if not isinstance(section, dict):
        return section
    out = {}
    for k in ("error", "skipped"):
        if k in section:
            out[k] = str(section[k])[:80]
    for k in SUMMARY_KEYS:
        if k in section:
            out[k] = section[k]
    if isinstance(section.get("model"), dict) and "params_m" in section["model"]:
        out["params_m"] = section["model"]["params_m"]
    for k, v in section.items():
        if isinstance(v, dict) and k not in ("model",):
            s = _summarize(v)
            if s:
                out[k] = s
    return out


def _round_number() -> int:
    """Next round index: one past the newest BENCH_r{N}.json the driver has
    recorded (round 4 runs with BENCH_r03.json in the tree)."""
    import glob
    import re

    repo = os.path.dirname(os.path.abspath(__file__))
    ns = [
        int(m.group(1))
        for f in glob.glob(os.path.join(repo, "BENCH_r*.json"))
        for m in [re.search(r"BENCH_r(\d+)\.json$", f)]
        if m
    ]
    return (max(ns) + 1) if ns else 1


def _pop_str_flag(argv: list, flag: str) -> str | None:
    """Extract ``--flag VALUE`` from argv (mutating it); None when absent."""
    if flag not in argv:
        return None
    i = argv.index(flag)
    try:
        value = argv[i + 1]
    except IndexError:
        raise SystemExit(f"{flag} requires an argument")
    del argv[i : i + 2]
    return value


def _pop_int_flag(argv: list, flag: str, minimum: int = 0) -> int | None:
    """Extract ``--flag N`` from argv (mutating it); None when absent."""
    if flag not in argv:
        return None
    i = argv.index(flag)
    try:
        value = int(argv[i + 1])
    except (IndexError, ValueError):
        raise SystemExit(f"{flag} requires an integer argument")
    if value < minimum:
        raise SystemExit(f"{flag} must be >= {minimum}, got {value}")
    del argv[i : i + 2]
    return value


def main(argv=None) -> None:
    argv = list(sys.argv[1:] if argv is None else argv)
    # Pop the knobs BEFORE the --section check so `--section X --iters N`
    # still runs section X (sections ignore the knobs) instead of silently
    # falling through to the multi-minute full bench.
    iters = _pop_int_flag(argv, "--iters", minimum=1)
    warmup = _pop_int_flag(argv, "--warmup")
    apiserver_latency_ms = _pop_int_flag(argv, "--apiserver-latency-ms")
    if len(argv) == 2 and argv[0] == "--section":
        print(json.dumps(SECTIONS[argv[1]]()))
        return
    full = "--full" in argv

    if "--cluster-scale" in argv:
        # The control-plane A/B artifact (`make bench-cluster`): N-node
        # sweep, fixed-vs-legacy arms interleaved, CPU-only, no devices.
        # --nodes "8,128,256" overrides the sweep, --churn M the per-wave
        # claim count, --seed S the churn/backoff RNG.
        argv.remove("--cluster-scale")
        nodes_arg = _pop_str_flag(argv, "--nodes")
        churn_arg = _pop_int_flag(argv, "--churn", minimum=1)
        seed_arg = _pop_int_flag(argv, "--seed") or 0
        nodes_list = (
            [int(x) for x in nodes_arg.split(",") if x.strip()]
            if nodes_arg
            else None
        )
        line = {
            "metric": "cluster_scale",
            **bench_cluster_scale(
                nodes_list=nodes_list, churn=churn_arg, seed=seed_arg
            ),
        }
        print(json.dumps(line))
        return

    if "--gang" in argv:
        # The gang-reservation A/B artifact (`make bench-gang`): bind
        # p50/p99 for 2/4/8-node slices, interleaved bound-vs-rollback
        # arms through real CD plugin drivers; CPU-only.
        argv.remove("--gang")
        sizes_arg = _pop_str_flag(argv, "--sizes")
        sizes = (
            [int(x) for x in sizes_arg.split(",") if x.strip()]
            if sizes_arg
            else None
        )
        line = {"metric": "gang_bind", **bench_gang(sizes=sizes, iters=iters)}
        print(json.dumps(line))
        return

    if "--checkpoint-churn" in argv:
        # The A/B artifact for checkpoint-storage PRs (`make
        # bench-checkpoint`): WAL-vs-snapshot churn + group-commit fsyncs,
        # CPU-only, no driver stack.
        line = {
            "metric": "checkpoint_churn",
            **bench_checkpoint_churn(iters=iters),
        }
        print(json.dumps(line))
        return

    if "--storage-degraded" in argv:
        # The degraded-mode artifact (`make bench-storage`): healthy bind
        # p50 vs the fail-fast shed path under an ENOSPC-faulted
        # checkpoint dir, plus heal convergence; CPU-only.
        argv.remove("--storage-degraded")
        line = {
            "metric": "storage_degraded_shed",
            **bench_storage_degraded(iters=iters, warmup=warmup),
        }
        print(json.dumps(line))
        return

    if "--failover" in argv:
        # The controller-failover artifact (`make bench-failover`,
        # docs/ha.md): time-to-new-leader p50/p99 across crash-shaped and
        # graceful lease handoffs, plus bind p99 during a 429 storm vs
        # quiet, interleaved; CPU-only.
        argv.remove("--failover")
        line = {
            "metric": "controller_failover",
            **bench_failover(iters=iters, warmup=warmup),
        }
        print(json.dumps(line))
        return

    if "--partition" in argv:
        # The fractional-chip artifact (`make bench-partition`,
        # docs/partitioning.md): interleaved partitioned-vs-whole-chip
        # bind p50/p99 plus the packing-efficiency scenario; CPU-only.
        argv.remove("--partition")
        line = {
            "metric": "partition_bind",
            **bench_partition_ab(iters=iters, warmup=warmup),
        }
        print(json.dumps(line))
        return

    if "--trace-ab" in argv:
        # The tracing-overhead artifact (`make bench-trace`,
        # docs/tracing.md): traced-vs-disabled bind p50 interleaved, plus
        # the span critical path — the ≤5% overhead gate and the phase
        # attribution future perf PRs cite.
        line = {
            "metric": "trace_overhead",
            **bench_trace_ab(iters=iters, warmup=warmup),
        }
        print(json.dumps(line))
        return

    if "--bind-only" in argv:
        # The A/B artifact for bind-path PRs: headline single-claim p50 +
        # the multi-claim batch section, nothing that needs a device.
        # --apiserver-latency-ms adds the remote-half A/B: batch bind at an
        # injected RTT, watch-cached vs per-claim-GET resolution.
        p50 = bench_bind_p50(iters=iters, warmup=warmup)
        line = {
            "metric": "resourceclaim_bind_p50_latency",
            "value": round(p50, 3),
            "unit": "ms",
            "vs_baseline": round(BASELINE_BIND_MS / p50, 1),
            "iters": iters if iters is not None else ITERS,
            "batch": bench_bind_batch(iters=iters, warmup=warmup),
        }
        if apiserver_latency_ms is not None:
            line["apiserver"] = bench_bind_apiserver_ab(
                float(apiserver_latency_ms), iters=iters, warmup=warmup
            )
        print(json.dumps(line))
        return

    # Wall budget (VERDICT r4 #1): the driver's capture has a finite
    # timeout and a run that exceeds it yields rc=124 with an empty tail.
    # The default run targets a conservative budget; the exhaustive A/B
    # legs and the scale sweep (slowest, least round-to-round variant)
    # run only under --full.  Each section's subprocess timeout is capped
    # by the remaining budget, and once it is spent remaining sections are
    # skipped with an explicit marker rather than silently overrunning.
    t_start = time.perf_counter()
    wall_budget = float(
        os.environ.get("TPUDRA_BENCH_WALL_S", "3600" if full else "1500")
    )

    def remaining() -> float:
        return wall_budget - (time.perf_counter() - t_start)

    def emit(section: str, payload: dict) -> None:
        # Incremental evidence: one JSON line per completed section, so a
        # capture truncated mid-run still carries the headline and every
        # section finished so far.  The final (non-"partial") line remains
        # the complete artifact.
        line = {"partial": True, "section": section, **_summarize(payload)}
        print(json.dumps(line)[:1900], flush=True)

    def run_section(name: str, *, needs_device: bool = False) -> dict:
        if needs_device and not probe.get("reachable"):
            return {"skipped": "device backend unreachable (see probe)"}
        if remaining() < 90.0:
            return {"skipped": f"wall budget exhausted ({wall_budget:.0f}s)"}
        out = _run_section(name, timeout=min(1200.0, remaining()))
        emit(name, out)
        return out

    # Bounded backend-reachability probe BEFORE anything touches jax: a
    # hung relay becomes a diagnostic plus CPU-only degraded run instead
    # of an empty-tail timeout.
    probe = _probe_device_backend()
    emit("probe", probe)

    p50 = bench_bind_p50(iters=iters, warmup=warmup)
    headline = {
        "metric": "resourceclaim_bind_p50_latency",
        "value": round(p50, 3),
        "unit": "ms",
        "vs_baseline": round(BASELINE_BIND_MS / p50, 1),
    }
    emit("bind", headline)
    bind_batch = bench_bind_batch(iters=iters, warmup=warmup)
    emit("bind_batch", bind_batch)
    checkpoint = run_section("checkpoint")
    partition = bench_bind_partition_p50()
    emit("dynamic_partition", partition)

    # Collectives first among the device sections: the multi-chip measure
    # runs in its own bounded subprocess, the single-chip hook pins cpu in
    # its child — either way the result is emitted as soon as it exists.
    if (
        probe.get("reachable")
        and probe.get("backend") != "cpu"
        and probe.get("n_devices", 0) > 1
    ):
        collectives = run_section("collectives", needs_device=True)
    else:
        collectives = bench_collectives_hook()
        emit("collectives", collectives)

    tpu = run_section("tpu", needs_device=True)
    # Second run in a fresh process: compiles served from the persistent
    # cache — the "claim → training in seconds" number after a pod restart.
    warm = run_section("tpu", needs_device=True)
    if "compile_s" in warm and "compile_s" in tpu:
        tpu["warm_compile_s"] = warm["compile_s"]
        if warm.get("step_ms", 1e9) < tpu.get("step_ms", 0):
            tpu.update({k: warm[k] for k in warm if k != "compile_s"})
    extras = {
        "probe": probe,
        "bind_batch": bind_batch,
        "tpu": tpu,
        "long_context": run_section("long8192", needs_device=True),
        "long_context_16k": run_section("long16384", needs_device=True),
        "moe": run_section("moe", needs_device=True),
        "collectives": collectives,
        "checkpoint": checkpoint,
        "dynamic_partition": partition,
        "native_corroboration": run_section("native", needs_device=True),
        # North-star loop: native claim prepare → merged CDI env → the
        # real libtpu sees exactly the granted chip and runs a jitted op.
        "claim_to_jax": run_section("claim_to_jax", needs_device=True),
    }
    if full:
        # A/B legs backing the tuning claims in workload/model.py: the
        # headline config is remat=dots + splash attention.
        extras["ab"] = {
            "remat_full": run_section("ab_remat_full", needs_device=True),
            "attention_naive": run_section("ab_naive", needs_device=True),
            "ce_fused": run_section("ab_ce_fused", needs_device=True),
            "opt_fused": run_section("ab_opt_fused", needs_device=True),
        }
        # 100-node/500-claim churn, controller fan-out, informer memory,
        # QPS limiter under storm (CPU-only).
        extras["scale"] = run_section("scale")
    extras["wall_s"] = round(time.perf_counter() - t_start, 1)

    details_name = f"BENCH_DETAILS_r{_round_number():02d}.json"
    details_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), details_name
    )
    try:
        with open(details_path, "w") as f:
            json.dump({**headline, "extras": extras}, f, indent=1)
    except OSError as e:
        extras["details_write_error"] = str(e)[:120]
    line = {
        **headline,
        "extras": {k: _summarize(v) for k, v in extras.items()},
        "details_file": details_name,
    }
    text = json.dumps(line)
    if len(text) > 1900:
        # Defensive: the driver's capture truncates around 2000 chars.
        # Shed the heaviest nested summaries before the headline is at
        # risk (the full detail is in the committed details file).
        for victim in ("ab", "native_corroboration", "collectives"):
            line["extras"].pop(victim, None)
            text = json.dumps(line)
            if len(text) <= 1900:
                break
    if len(text) > 1900:
        # Last resort: the headline + details pointer ALWAYS fits — a
        # truncated-mid-object line (r3's parsed:null artifact) is the one
        # outcome this pipeline exists to prevent.
        text = json.dumps({**headline, "details_file": details_name})
    print(text)


if __name__ == "__main__":
    sys.exit(main())

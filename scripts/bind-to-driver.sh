#!/usr/bin/env bash
# Usage: ./bind-to-driver.sh <ssss:bb:dd.f> <driver>
# Bind the TPU PCI function to the given driver via sysfs driver_override —
# the manual form of what the plugin's VFIO passthrough path does during
# Prepare (reference scripts/bind_to_driver.sh; tpudra/plugin/vfio.py).
set -u

dev="${1:?usage: $0 <ssss:bb:dd.f> <driver>}"
driver="${2:?usage: $0 <ssss:bb:dd.f> <driver>}"
override="/sys/bus/pci/devices/${dev}/driver_override"
bind="/sys/bus/pci/drivers/${driver}/bind"

[ -e "${override}" ] || { echo "${override} does not exist" >&2; exit 1; }
# Verify the target driver exists BEFORE touching anything: discovering it
# after the unbind would leave the device driverless with a stale override.
[ -e "${bind}" ] || { echo "${bind} does not exist (driver loaded?)" >&2; exit 1; }
echo "${driver}" > "${override}" || { echo "writing ${override} failed" >&2; exit 1; }

# Unbind from the current driver, if any.
current="/sys/bus/pci/devices/${dev}/driver"
if [ -e "${current}" ]; then
    if ! echo "${dev}" > "${current}/unbind"; then
        echo "" > "${override}"
        echo "unbind failed" >&2
        exit 1
    fi
fi

if ! echo "${dev}" > "${bind}"; then
    echo "" > "${override}"
    # Best effort back to default matching so the device is not left
    # driverless (the kernel re-matches only on a probe event).
    echo "${dev}" > /sys/bus/pci/drivers_probe 2>/dev/null
    echo "binding ${dev} to ${driver} failed" >&2
    exit 1
fi
echo "bound ${dev} to ${driver}"

#!/usr/bin/env bash
# Usage: ./bind-to-driver.sh <ssss:bb:dd.f> <driver>
# Bind the TPU PCI function to the given driver via sysfs driver_override —
# the manual form of what the plugin's VFIO passthrough path does during
# Prepare (reference scripts/bind_to_driver.sh; tpudra/plugin/vfio.py).
set -u

dev="${1:?usage: $0 <ssss:bb:dd.f> <driver>}"
driver="${2:?usage: $0 <ssss:bb:dd.f> <driver>}"
override="/sys/bus/pci/devices/${dev}/driver_override"
bind="/sys/bus/pci/drivers/${driver}/bind"

[ -e "${override}" ] || { echo "${override} does not exist" >&2; exit 1; }
echo "${driver}" > "${override}" || { echo "writing ${override} failed" >&2; exit 1; }

# Unbind from the current driver first, if any.
current="/sys/bus/pci/devices/${dev}/driver"
if [ -e "${current}" ]; then
    echo "${dev}" > "${current}/unbind" || { echo "unbind failed" >&2; exit 1; }
fi

[ -e "${bind}" ] || { echo "${bind} does not exist (driver loaded?)" >&2; exit 1; }
if ! echo "${dev}" > "${bind}"; then
    echo "" > "${override}"
    echo "binding ${dev} to ${driver} failed" >&2
    exit 1
fi
echo "bound ${dev} to ${driver}"

#!/usr/bin/env bash
# Usage: ./unbind-from-driver.sh <ssss:bb:dd.f>
# Release the TPU PCI function from its current driver and clear the
# driver_override, returning it to default matching (reference
# scripts/unbind_from_driver.sh).
set -u

dev="${1:?usage: $0 <ssss:bb:dd.f>}"
current="/sys/bus/pci/devices/${dev}/driver"
override="/sys/bus/pci/devices/${dev}/driver_override"

if [ -e "${current}" ]; then
    echo "${dev}" > "${current}/unbind" || { echo "unbind failed" >&2; exit 1; }
fi
[ -e "${override}" ] && echo "" > "${override}"
# The kernel re-matches drivers only on a probe event; without this the
# device would stay driverless (tpudra/plugin/vfio.py rebinds explicitly
# for the same reason).
echo "${dev}" > /sys/bus/pci/drivers_probe 2>/dev/null
echo "unbound ${dev}; reprobed for default driver matching"

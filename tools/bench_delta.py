"""Print the bind-p50 delta between a fresh bench run and the newest prior
round artifact.

Usage: ``python tools/bench_delta.py <file-with-bench-stdout>``

The file is whatever ``python bench.py`` just printed (``make bench`` tees
it); the prior number comes from the newest ``BENCH_r*.json`` in the repo
whose driver-recorded capture parsed (``parsed.value``, falling back to the
first JSON line of ``tail``).  With no usable prior round the script says so
and exits 0 — the delta is a convenience, not a gate.
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def current_headline(path: str) -> dict | None:
    """Last line of the bench output that carries the headline metric."""
    try:
        lines = open(path).read().splitlines()
    except OSError as e:
        print(f"bench-delta: cannot read {path}: {e}")
        return None
    for line in reversed(lines):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if obj.get("metric") == "resourceclaim_bind_p50_latency":
            return obj
    return None


def prior_headline() -> tuple[int, dict] | None:
    """(round, headline) from the newest BENCH_r*.json that parsed."""
    rounds = sorted(
        (
            (int(m.group(1)), f)
            for f in glob.glob(os.path.join(REPO, "BENCH_r*.json"))
            for m in [re.search(r"BENCH_r(\d+)\.json$", f)]
            if m
        ),
        reverse=True,
    )
    for n, f in rounds:
        try:
            rec = json.load(open(f))
        except (OSError, ValueError):
            continue
        parsed = rec.get("parsed")
        if isinstance(parsed, dict) and "value" in parsed:
            return n, parsed
        for line in (rec.get("tail") or "").splitlines():
            line = line.strip()
            if line.startswith("{"):
                try:
                    obj = json.loads(line)
                except ValueError:
                    continue
                if "value" in obj:
                    return n, obj
    return None


def main() -> int:
    if len(sys.argv) != 2:
        print(__doc__.strip().splitlines()[0])
        print("usage: python tools/bench_delta.py <bench-stdout-file>")
        return 2
    now = current_headline(sys.argv[1])
    if now is None:
        print("bench-delta: no headline line in this run's output")
        return 0
    prior = prior_headline()
    if prior is None:
        print(
            f"bench-delta: bind p50 {now['value']} ms "
            "(no prior BENCH_r*.json with a parsed headline to compare)"
        )
        # The apiserver A/B is within-run by design — it must print even
        # with no round history to compare the headline against.
        print_apiserver_section(now)
        return 0
    n, before = prior
    delta_pct = (now["value"] - before["value"]) / before["value"] * 100.0
    arrow = "faster" if delta_pct < 0 else "slower"
    print(
        f"bench-delta: bind p50 {before['value']} ms (round {n}) -> "
        f"{now['value']} ms now  ({abs(delta_pct):.1f}% {arrow})"
    )
    print_apiserver_section(now)
    return 0


def print_apiserver_section(now: dict) -> None:
    """The --apiserver-latency-ms A/B, when this run carried it: the batch
    bind at an injected RTT, watch-cached resolution vs per-claim GETs.
    The interesting delta is within the run (the two interleaved arms),
    not across rounds — RTT injection makes absolute numbers incomparable
    with the headline history."""
    ab = now.get("apiserver")
    if not isinstance(ab, dict) or "cached_batch_p50_ms" not in ab:
        return
    cached = ab["cached_batch_p50_ms"]
    uncached = ab["uncached_batch_p50_ms"]
    rtt = ab.get("latency_ms", 0)
    n = ab.get("n_claims", 0)
    print(
        f"bench-delta: apiserver A/B at {rtt:g} ms RTT "
        f"(batch of {n}): cached {cached} ms vs per-claim-GET {uncached} ms "
        f"({ab.get('improvement_ms', round(uncached - cached, 3))} ms "
        f"left the hot path; ~{n} serialized GET RTTs = {n * rtt:g} ms)"
    )


if __name__ == "__main__":
    sys.exit(main())

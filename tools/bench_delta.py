"""Print the bind-p50 delta between a fresh bench run and the newest prior
round artifact.

Usage: ``python tools/bench_delta.py <file-with-bench-stdout>``

The file is whatever ``python bench.py`` just printed (``make bench`` tees
it); the prior number comes from the newest ``BENCH_r*.json`` in the repo
whose driver-recorded capture parsed (``parsed.value``, falling back to the
first JSON line of ``tail``).  With no usable prior round the script says so
and exits 0 — the delta is a convenience, not a gate.
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def current_headline(path: str, metric: str = "resourceclaim_bind_p50_latency") -> dict | None:
    """Last line of the bench output that carries ``metric``."""
    try:
        lines = open(path).read().splitlines()
    except OSError as e:
        print(f"bench-delta: cannot read {path}: {e}")
        return None
    for line in reversed(lines):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if obj.get("metric") == metric:
            return obj
    return None


def prior_headline() -> tuple[int, dict] | None:
    """(round, headline) from the newest BENCH_r*.json that parsed."""
    rounds = sorted(
        (
            (int(m.group(1)), f)
            for f in glob.glob(os.path.join(REPO, "BENCH_r*.json"))
            for m in [re.search(r"BENCH_r(\d+)\.json$", f)]
            if m
        ),
        reverse=True,
    )
    for n, f in rounds:
        try:
            rec = json.load(open(f))
        except (OSError, ValueError):
            continue
        parsed = rec.get("parsed")
        if isinstance(parsed, dict) and "value" in parsed:
            return n, parsed
        for line in (rec.get("tail") or "").splitlines():
            line = line.strip()
            if line.startswith("{"):
                try:
                    obj = json.loads(line)
                except ValueError:
                    continue
                if "value" in obj:
                    return n, obj
    return None


def main() -> int:
    if len(sys.argv) != 2:
        print(__doc__.strip().splitlines()[0])
        print("usage: python tools/bench_delta.py <bench-stdout-file>")
        return 2
    now = current_headline(sys.argv[1])
    churn = current_headline(sys.argv[1], metric="checkpoint_churn")
    if churn is not None:
        print_checkpoint_section(churn)
    cluster = current_headline(sys.argv[1], metric="cluster_scale")
    if cluster is not None:
        print_cluster_section(cluster)
    gang = current_headline(sys.argv[1], metric="gang_bind")
    if gang is not None:
        print_gang_section(gang)
    partition = current_headline(sys.argv[1], metric="partition_bind")
    if partition:
        print_partition_section(partition)
    storage = current_headline(sys.argv[1], metric="storage_degraded_shed")
    if storage is not None:
        print_storage_section(storage)
    failover = current_headline(sys.argv[1], metric="controller_failover")
    if failover is not None:
        print_failover_section(failover)
    trace_ab = current_headline(sys.argv[1], metric="trace_overhead")
    if trace_ab is not None:
        print_trace_section(trace_ab)
    if now is None:
        if churn is None and cluster is None and gang is None and trace_ab is None:
            print("bench-delta: no headline line in this run's output")
        return 0
    prior = prior_headline()
    if prior is None:
        print(
            f"bench-delta: bind p50 {now['value']} ms "
            "(no prior BENCH_r*.json with a parsed headline to compare)"
        )
        # The apiserver A/B is within-run by design — it must print even
        # with no round history to compare the headline against.
        print_apiserver_section(now)
        return 0
    n, before = prior
    delta_pct = (now["value"] - before["value"]) / before["value"] * 100.0
    arrow = "faster" if delta_pct < 0 else "slower"
    print(
        f"bench-delta: bind p50 {before['value']} ms (round {n}) -> "
        f"{now['value']} ms now  ({abs(delta_pct):.1f}% {arrow})"
    )
    print_apiserver_section(now)
    return 0


def print_apiserver_section(now: dict) -> None:
    """The --apiserver-latency-ms A/B, when this run carried it: the batch
    bind at an injected RTT, watch-cached resolution vs per-claim GETs.
    The interesting delta is within the run (the two interleaved arms),
    not across rounds — RTT injection makes absolute numbers incomparable
    with the headline history."""
    ab = now.get("apiserver")
    if not isinstance(ab, dict) or "cached_batch_p50_ms" not in ab:
        return
    cached = ab["cached_batch_p50_ms"]
    uncached = ab["uncached_batch_p50_ms"]
    rtt = ab.get("latency_ms", 0)
    n = ab.get("n_claims", 0)
    print(
        f"bench-delta: apiserver A/B at {rtt:g} ms RTT "
        f"(batch of {n}): cached {cached} ms vs per-claim-GET {uncached} ms "
        f"({ab.get('improvement_ms', round(uncached - cached, 3))} ms "
        f"left the hot path; ~{n} serialized GET RTTs = {n * rtt:g} ms)"
    )


def print_partition_section(ab: dict) -> None:
    """Fractional-chip A/B (docs/partitioning.md): partitioned vs
    whole-chip bind latency plus the packing-efficiency scenario."""
    if "error" in ab:
        print(f"bench-delta: partition section errored: {ab['error']}")
        return
    whole, part = ab.get("whole_chip", {}), ab.get("partition", {})
    pk = ab.get("packing", {})
    print(
        "bench-delta: partition bind p50 "
        f"{part.get('p50_ms')}ms vs whole-chip {whole.get('p50_ms')}ms "
        f"(ratio {ab.get('bind_ratio_p50')}x, budget ≤2x), p99 "
        f"{part.get('p99_ms')} vs {whole.get('p99_ms')}ms"
    )
    print(
        "bench-delta: packing "
        f"{pk.get('partition_resident')} partition claims vs "
        f"{pk.get('whole_chip_resident')} whole-chip on {pk.get('chips')} "
        f"chips (efficiency {pk.get('efficiency')}x, budget ≥2x); "
        f"claims/chip-hour {pk.get('partition_claims_per_chip_hour')} vs "
        f"{pk.get('whole_chip_claims_per_chip_hour')}"
    )


def print_storage_section(shed: dict) -> None:
    """The `--storage-degraded` A/B (make bench-storage, docs/bind-path.md
    "Storage fault contract"): fail-fast shed latency under a faulted
    checkpoint dir vs the healthy bind, plus the heal-convergence bit."""
    if "error" in shed:
        print(f"bench-delta: storage section errored: {shed['error']}")
        return
    print(
        "bench-delta: storage-degraded shed: "
        f"p50 {shed.get('shed_p50_ms')} ms / p99 {shed.get('shed_p99_ms')} ms "
        f"/ max {shed.get('shed_max_ms')} ms (typed retryable error) vs "
        f"healthy bind p50 {shed.get('healthy_bind_p50_ms')} ms; "
        f"recovered after heal: {shed.get('recovered_after_heal')}"
    )


def print_failover_section(fo: dict) -> None:
    """The `--failover` artifact (make bench-failover, docs/ha.md):
    time-to-new-leader across crash vs graceful lease handoffs, plus what
    one 429 shed round-trip costs a bind (within-run interleaved arms)."""
    if "error" in fo:
        print(f"bench-delta: failover section errored: {fo['error']}")
        return
    ttl = fo.get("time_to_new_leader", {})
    crash, graceful = ttl.get("crash", {}), ttl.get("graceful", {})
    print(
        "bench-delta: time-to-new-leader (lease "
        f"{fo.get('lease_duration_ms'):g} ms / renew "
        f"{fo.get('renew_interval_ms'):g} ms): crash p50 "
        f"{crash.get('p50_ms')} ms / p99 {crash.get('p99_ms')} ms, "
        f"graceful handoff p50 {graceful.get('p50_ms')} ms / p99 "
        f"{graceful.get('p99_ms')} ms"
    )
    quiet, storm = fo.get("bind_quiet", {}), fo.get("bind_429_storm", {})
    print(
        "bench-delta: bind under 429 storm: p50 "
        f"{storm.get('p50_ms')} ms / p99 {storm.get('p99_ms')} ms vs quiet "
        f"{quiet.get('p50_ms')} / {quiet.get('p99_ms')} ms "
        f"(+{fo.get('storm_overhead_p50_ms')} ms p50 per shed round-trip, "
        f"Retry-After {fo.get('storm_retry_after_ms'):g} ms)"
    )


def print_trace_section(ab: dict) -> None:
    """The `--trace-ab` artifact (make bench-trace, docs/tracing.md):
    tracing overhead (interleaved arms, within-run by design) plus the
    span CRITICAL PATH — so a bind-path PR cites which phase moved, not
    just that the p50 did."""
    traced = ab.get("bind_p50_traced_ms")
    disabled = ab.get("bind_p50_disabled_ms")
    if traced is None or disabled is None:
        return
    print(
        f"bench-delta: tracing overhead: bind p50 {disabled} ms disabled "
        f"vs {traced} ms traced ({ab.get('overhead_pct')}% — budget ≤5%)"
    )
    phases = ab.get("critical_path")
    if isinstance(phases, dict) and phases:
        print("bench-delta: traced-bind phase attribution (mean ms/span):")
        for name, entry in sorted(
            phases.items(), key=lambda kv: -kv[1].get("mean_ms", 0.0)
        ):
            print(
                f"bench-delta:   {name:<28} {entry.get('mean_ms'):>8} ms "
                f"(n={entry.get('n')})"
            )


def print_gang_section(gang: dict) -> None:
    """The `--gang` A/B (make bench-gang): all-or-nothing gang bind
    p50/p99 by slice size, interleaved bound-vs-rollback arms — within-run
    by design (the rollback arm's price relative to the bound arm is the
    artifact, not the absolute ms of either)."""
    if gang.get("error"):
        print(f"bench-delta: gang section errored: {gang['error']}")
        return
    for k in gang.get("sizes", []):
        arms = gang.get(f"nodes_{k}")
        if not isinstance(arms, dict):
            continue
        bound = arms.get("bound", {})
        rb = arms.get("rollback", {})
        print(
            f"bench-delta: gang {k}-node bind p50 {bound.get('p50_ms')} ms "
            f"/ p99 {bound.get('p99_ms')} ms; rollback arm p50 "
            f"{rb.get('p50_ms')} ms (the all-or-nothing failure price)"
        )


def print_checkpoint_section(churn: dict) -> None:
    """The `--checkpoint-churn` A/B (make bench-checkpoint): WAL vs
    snapshot arms, within-run by design — the bytes/fsync ratios ARE the
    artifact, absolute latencies bounce with the box's fsync cost."""
    group = churn.get("group_commit", {})
    j = group.get("journal", {}).get("fsyncs_per_8claim_wave_median")
    s = group.get("snapshot", {}).get("fsyncs_per_8claim_wave_median")
    if j is not None and s is not None:
        print(
            f"bench-delta: checkpoint group commit: {j:g} fsync(s) per "
            f"8-claim churn wave (WAL) vs {s:g} (snapshot-per-mutate)"
        )
    for n, arms in sorted(
        churn.get("resident", {}).items(), key=lambda kv: int(kv[0])
    ):
        ja, sa = arms.get("journal", {}), arms.get("snapshot", {})
        print(
            f"bench-delta: checkpoint churn @{n} resident: WAL "
            f"{ja.get('bytes_per_mutate')} B/mutate p50 "
            f"{ja.get('mutate_p50_ms')} ms vs snapshot "
            f"{sa.get('bytes_per_mutate')} B/mutate p50 "
            f"{sa.get('mutate_p50_ms')} ms"
        )
    ratio_j = churn.get("journal_bytes_ratio_128_vs_8")
    ratio_s = churn.get("snapshot_bytes_ratio_128_vs_8")
    if ratio_j is not None:
        print(
            f"bench-delta: checkpoint bytes/mutate at 128 vs 8 resident: "
            f"WAL x{ratio_j:g} (delta-sized), snapshot x{ratio_s:g} "
            "(state-sized)"
        )


def print_cluster_section(cluster: dict) -> None:
    """The `--cluster-scale` A/B (make bench-cluster): fixed-vs-legacy
    control-plane arms, within-run by design — the interleaved arms ARE
    the artifact; absolute latencies bounce with the box's thread/syscall
    cost."""
    for key, report in sorted(
        ((k, v) for k, v in cluster.items() if k.isdigit()),
        key=lambda kv: int(kv[0]),
    ):
        fixed, legacy = report.get("fixed"), report.get("legacy")
        if not isinstance(fixed, dict) or not isinstance(legacy, dict):
            if report.get("error"):
                print(f"bench-delta: cluster @{key} nodes: {report['error']}")
            continue
        print(
            f"bench-delta: cluster @{key} nodes: reconcile p99 "
            f"{fixed['reconcile']['p99_ms']:g} ms (fixed) vs "
            f"{legacy['reconcile']['p99_ms']:g} ms (legacy); bind p99 "
            f"{fixed['bind']['p99_ms']:g} vs {legacy['bind']['p99_ms']:g} ms; "
            f"apiserver {fixed['apiserver']['qps']:g} vs "
            f"{legacy['apiserver']['qps']:g} qps over the churn windows"
        )
        for tag, arm in (("fixed", fixed), ("legacy", legacy)):
            if arm["bind"].get("errors"):
                # A broken arm's fast error-returns flatter its p99; say so
                # louder than the headline.
                print(
                    f"bench-delta: cluster @{key} nodes: WARNING {tag} arm "
                    f"had {arm['bind']['errors']} bind errors "
                    f"(first: {arm['bind'].get('first_error', '?')}) — its "
                    "latency numbers are not trustworthy"
                )
        print(
            f"bench-delta: cluster @{key} nodes: flap victims' max wait "
            f"{fixed['flap']['victim_wait_max_ms']:g} ms (fixed) vs "
            f"{legacy['flap']['victim_wait_max_ms']:g} ms (legacy); "
            f"event materializations {fixed['watch']['materializations']} "
            f"vs {legacy['watch']['materializations']}; startup publish "
            f"{fixed['publish']['requests']} vs "
            f"{legacy['publish']['requests']} requests"
        )


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python3
"""Render a chaos-soak JSON report; ``--assert-slo`` is the make-soak gate.

Reads the report ``tpudra.sim.chaos`` writes and prints the human view:
fault timeline with recovery times, per-window bind latency, invariant
check/violation counts, and the SLO verdict.  With ``--assert-slo`` the
exit code is the gate (0 = every budget met), checking:

- zero invariant violations;
- bind p99 within budget and max claim-stuck < T (the report's own
  ``slo`` section);
- the run actually covered ground: ≥ ``--min-sim-hours`` of simulated
  churn, at least ``--min-faults`` faults with every enabled kind
  injected at least once, and a nonzero check count for every
  continuously-monitored invariant (a soak that never checked anything
  passes no SLO);
- when the lock witness was armed, its merge ran.

Violations embed their seed + fault timeline; re-run with
``python -m tpudra.sim.chaos --replay <report.json>`` to reproduce.
"""

from __future__ import annotations

import argparse
import json
import sys

#: Invariants the monitor must have evaluated at least once per run
#: (lock-witness is only required when the report says it was armed;
#: slice-convergence/slice-health/grant-health only assert in quiet
#: windows, so a fault-saturated short run may legitimately end with zero
#: passes of those).  acknowledged-mutation-durability is asserted at
#: every crash-shaped recovery (plugin_crash / torn_wal / disk_fault's
#: composed SIGKILL) and storage-degraded-convergence on every monitor
#: pass — a run that skipped either proves nothing about the disk.
REQUIRED_CHECKED = (
    "claim-stuck",
    "cdi-leak",
    "flock-leak",
    "gang-degraded",
    "acknowledged-mutation-durability",
    "storage-degraded-convergence",
    "partition-leak",
    "single-writer",
    "leadership-liveness",
)

#: Fault kinds every soak run must have injected at least once — checked
#: against the INJECTED set, not just the configured one, so a run whose
#: config silently dropped chip_fault, daemon_crash, or disk_fault (the
#: health/daemon/storage blast radii) cannot pass the gate.
REQUIRED_KINDS = (
    "apiserver_latency",
    "watch_close",
    "kubelet_restart",
    "plugin_crash",
    "torn_wal",
    "clock_skew",
    "cd_wave",
    "chip_fault",
    "daemon_crash",
    "disk_fault",
    "partition_fault",
    "apiserver_outage",
    "controller_failover",
)


def render(report: dict) -> str:
    cfg = report["config"]
    lines = [
        f"chaos soak — seed {cfg['seed']}, {cfg['nodes']} nodes × "
        f"{cfg['chips_per_node']} chips, {cfg['wall_s']:.0f}s wall × "
        f"{cfg['compression']:.0f}x = {report['sim_hours']:.2f} simulated hours",
        "",
        f"faults injected: {report['faults']['injected_total']}",
    ]
    for kind, n in sorted(report["faults"]["by_kind"].items()):
        lines.append(f"  {kind:<20} {n}")
    lines.append("")
    lines.append("bind latency by fault window (ms):")
    lines.append(f"  {'window':<40} {'n':>6} {'p50':>9} {'p99':>9} {'max':>9}")
    windows = dict(report["bind"]["by_window"])
    for tag in sorted(windows, key=lambda t: (t != "quiet", t)):
        s = windows[tag]
        lines.append(
            f"  {tag:<40} {s['n']:>6} {s['p50_ms']:>9.2f} "
            f"{s['p99_ms']:>9.2f} {s['max_ms']:>9.2f}"
        )
    errs = report["bind"]["errors"]
    lines.append(
        f"  bind errors: {errs['total']} "
        f"({', '.join(f'{k}={v}' for k, v in sorted(errs['by_window'].items())) or 'none'})"
    )
    lines.append("")
    lines.append("invariants (continuous checks):")
    for inv, counts in sorted(report["invariants"].items()):
        flag = "OK " if counts["violations"] == 0 else "FAIL"
        lines.append(
            f"  [{flag}] {inv:<20} checks={counts['checks']:<6} "
            f"violations={counts['violations']}"
        )
    rec = report["recovery"]
    lines.append("")
    lines.append(
        f"recovery: {len(rec['samples_sim_s'])} fault recoveries, max "
        f"{rec['max_sim_s']:.0f} sim-s (budget {rec['budget_sim_s']:.0f})"
    )
    fo = report.get("failover")
    if fo:
        ttl = fo.get("time_to_new_leader_sim_s") or []
        lines.append(
            f"failover: {fo.get('leader_terms_started', 0)} leader term(s), "
            f"stale-leader rejections "
            f"{fo.get('stale_leader_rejections_observed', fo.get('tpudra_gang_stale_leader_rejections_total', 0)):.0f}"
            + (
                f", time-to-new-leader max {max(ttl):.0f} sim-s"
                if ttl
                else ""
            )
        )
    if report.get("anomalies"):
        lines.append("")
        lines.append("anomalies (non-failing):")
        for a in report["anomalies"]:
            lines.append(f"  - {a}")
    lines.append("")
    lines.append("SLO:")
    for name, entry in sorted(report["slo"].items()):
        flag = "OK " if entry["ok"] else "FAIL"
        lines.append(
            f"  [{flag}] {name:<24} value={entry['value']} "
            f"budget={entry['budget']}"
        )
    for v in report.get("violations", []):
        lines.append("")
        lines.append(
            f"VIOLATION [{v['invariant']}] at t_sim={v['t_sim']}: "
            f"{v['detail']}"
        )
        lines.append(
            f"  replay: python -m tpudra.sim.chaos --replay <this report> "
            f"(seed {v['replay']['seed']}, "
            f"{len(v['replay']['timeline'])} fault(s) in timeline)"
        )
    return "\n".join(lines)


def assert_slo(
    report: dict, min_sim_hours: float, min_faults: int
) -> list[str]:
    """Every reason the report fails the gate (empty = pass)."""
    failures = []
    for name, entry in report["slo"].items():
        if not entry["ok"]:
            failures.append(
                f"SLO {name}: value {entry['value']} vs budget {entry['budget']}"
            )
    if report["sim_hours"] < min_sim_hours:
        failures.append(
            f"covered only {report['sim_hours']:.2f} simulated hours "
            f"(need ≥ {min_sim_hours})"
        )
    if report["faults"]["injected_total"] < min_faults:
        failures.append(
            f"only {report['faults']['injected_total']} faults injected "
            f"(need ≥ {min_faults})"
        )
    for kind in dict.fromkeys(
        tuple(report["config"]["fault_kinds"]) + REQUIRED_KINDS
    ):
        if report["faults"]["by_kind"].get(kind, 0) < 1:
            failures.append(f"fault kind {kind!r} was never injected")
    for inv in REQUIRED_CHECKED:
        if report["invariants"].get(inv, {}).get("checks", 0) < 1:
            failures.append(f"invariant {inv!r} was never checked")
    if report["config"].get("witness") and (
        report["invariants"].get("lock-witness", {}).get("checks", 0) < 1
    ):
        failures.append("witness was armed but the merge never ran")
    if report["bind"]["overall"]["n"] < 1:
        failures.append("no successful binds recorded — the churn never ran")
    if report["faults"]["by_kind"].get("controller_failover", 0) >= 1:
        # The failover acceptance (docs/ha.md): every run that injected a
        # failover must have FENCED at least one revived stale leader at
        # the checkpoint layer — a failover whose stale-commit probe never
        # hit the WAL refusal proved nothing about split-brain.  The
        # RUN-LOCAL observation is what counts: the process-global metric
        # carries residue across in-process soaks and could fake the gate.
        fo = report.get("failover", {})
        observed = fo.get(
            "stale_leader_rejections_observed",
            fo.get("tpudra_gang_stale_leader_rejections_total", 0),
        )
        if observed < 1:
            probes = fo.get("stale_probes_run", 0)
            failures.append(
                "controller_failover injected but no stale-leader commit "
                "was fenced this run ("
                + (
                    f"{probes} probe(s) ran without a refusal"
                    if probes
                    else "every stale probe was skipped — see anomalies"
                )
                + ")"
            )
    return failures


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("report", help="path to the soak's JSON report")
    parser.add_argument("--assert-slo", action="store_true")
    parser.add_argument("--min-sim-hours", type=float, default=1.0)
    parser.add_argument("--min-faults", type=int, default=13)
    args = parser.parse_args(argv)
    with open(args.report) as f:
        report = json.load(f)
    print(render(report))
    if not args.assert_slo:
        return 0
    failures = assert_slo(report, args.min_sim_hours, args.min_faults)
    if failures:
        print("\nSLO GATE: FAILED", file=sys.stderr)
        for reason in failures:
            print(f"  - {reason}", file=sys.stderr)
        return 1
    print("\nSLO GATE: PASSED")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

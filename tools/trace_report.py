"""Reconstruct claim/gang timelines and critical paths from a tpudra trace log.

Usage:
    python tools/trace_report.py <trace.jsonl> [--trace ID] [--limit N] [--json]
    python tools/trace_report.py --self-check

The log is what ``TPUDRA_TRACE=1`` runs append (tpudra/trace.py): one JSON
span per line, possibly from several processes (controller, plugin
threads, worker ranks) sharing one file.  The report groups spans into
traces, renders each trace as an indented timeline (start offset,
duration, name, pid, key attrs), and prints the CRITICAL PATH — at each
node, the child whose end determines the parent's completion — so a 67 ms
gang bind decomposes into "which phase of which member on which node"
instead of a p50 delta.

``--self-check`` is the ``make trace-check`` body: it runs a traced
mini-bench — a 2-node gang reservation through REAL CD plugin drivers,
plus one subprocess per member standing in for a worker rank (it emits a
``rank.worker`` span parented ONLY on the grant env's
``TPUDRA_TRACEPARENT``) — then asserts this module parses the log into a
complete root→rank span tree: ``gang.reserve`` root, one
``gang.bind-member`` per member, checkpoint + CDI child phases under each
bind, and a rank span that chains to its member across the process
boundary.  It exercises every propagation edge we own except gRPC
metadata (covered by tests/test_trace.py) in a few seconds, with no jax.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tpudra import trace  # noqa: E402


# ------------------------------------------------------------------- model


def build_traces(spans: list) -> dict:
    """Group span records by trace id: {trace_id: {"spans": {span_id:
    rec}, "children": {span_id: [rec]}, "roots": [rec]}}.  A span whose
    parent is absent from the log (a torn line, a foreign parent) is
    treated as a root — the report degrades, never crashes."""
    traces: dict = {}
    for rec in spans:
        t = traces.setdefault(
            rec["trace"], {"spans": {}, "children": {}, "roots": []}
        )
        t["spans"][rec["span"]] = rec
    for t in traces.values():
        for rec in t["spans"].values():
            parent = rec.get("parent") or ""
            if parent and parent in t["spans"]:
                t["children"].setdefault(parent, []).append(rec)
            else:
                t["roots"].append(rec)
        for kids in t["children"].values():
            kids.sort(key=lambda r: r.get("start", 0.0))
        t["roots"].sort(key=lambda r: r.get("start", 0.0))
    return traces


def _end(rec: dict) -> float:
    return rec.get("start", 0.0) + rec.get("dur_ms", 0.0) / 1000.0


def critical_path(root: dict, children: dict) -> list:
    """Root-to-leaf chain where each hop is the child whose END time
    determines its parent's completion — the span sequence a perf PR must
    shorten to move the parent's latency."""
    path = [root]
    node = root
    while True:
        kids = children.get(node["span"], [])
        if not kids:
            return path
        node = max(kids, key=_end)
        path.append(node)


def critical_path_summary(root: dict, children: dict) -> list:
    """[{name, dur_ms, pct, pid, attrs}] along the critical path, pct
    relative to the root's duration."""
    total = max(root.get("dur_ms", 0.0), 1e-9)
    out = []
    for rec in critical_path(root, children):
        out.append(
            {
                "name": rec["name"],
                "dur_ms": rec.get("dur_ms", 0.0),
                "pct": round(100.0 * rec.get("dur_ms", 0.0) / total, 1),
                "pid": rec.get("pid"),
                "attrs": rec.get("attrs", {}),
            }
        )
    return out


def descendants(rec: dict, children: dict) -> list:
    out = []
    stack = [rec]
    while stack:
        node = stack.pop()
        for kid in children.get(node["span"], []):
            out.append(kid)
            stack.append(kid)
    return out


def _ancestor_chain(rec: dict, spans: dict) -> list:
    """Parent chain from ``rec`` to its root (names), following parent
    span ids within one trace."""
    chain = []
    node = rec
    seen = set()
    while True:
        parent = node.get("parent") or ""
        if not parent or parent not in spans or parent in seen:
            return chain
        seen.add(parent)
        node = spans[parent]
        chain.append(node["name"])


def phase_means(spans: list, root_name: str) -> dict:
    """Mean duration (ms) per span name across every trace rooted at
    ``root_name`` — the attribution table bench prints next to its p50s
    (how the bind p50 decomposes into phases, not just that it moved)."""
    traces = build_traces(spans)
    sums: dict = {}
    counts: dict = {}
    for t in traces.values():
        for root in t["roots"]:
            if root["name"] != root_name:
                continue
            for rec in [root] + descendants(root, t["children"]):
                sums[rec["name"]] = sums.get(rec["name"], 0.0) + rec.get(
                    "dur_ms", 0.0
                )
                counts[rec["name"]] = counts.get(rec["name"], 0) + 1
    return {
        name: {"mean_ms": round(sums[name] / counts[name], 3), "n": counts[name]}
        for name in sums
    }


# ------------------------------------------------------------------ render


def _render_span(rec: dict, t0: float, depth: int) -> str:
    offset_ms = (rec.get("start", 0.0) - t0) * 1000.0
    attrs = rec.get("attrs", {})
    attr_str = (
        " " + " ".join(f"{k}={attrs[k]}" for k in sorted(attrs)) if attrs else ""
    )
    err = f" ERROR({rec['error']})" if rec.get("error") else ""
    return (
        f"{offset_ms:9.1f}ms {'  ' * depth}{rec['name']} "
        f"[{rec.get('dur_ms', 0.0):.2f}ms pid={rec.get('pid')}]"
        f"{attr_str}{err}"
    )


def render_trace(trace_id: str, t: dict) -> str:
    lines = [f"trace {trace_id} ({len(t['spans'])} spans)"]
    if not t["roots"]:
        return lines[0] + "\n  (no roots)"
    t0 = t["roots"][0].get("start", 0.0)

    def walk(rec: dict, depth: int) -> None:
        lines.append(_render_span(rec, t0, depth))
        for kid in t["children"].get(rec["span"], []):
            walk(kid, depth + 1)

    for root in t["roots"]:
        walk(root, 0)
        lines.append("  critical path:")
        for hop in critical_path_summary(root, t["children"]):
            lines.append(
                f"    {hop['name']:<28} {hop['dur_ms']:9.2f}ms "
                f"{hop['pct']:5.1f}% pid={hop['pid']}"
            )
    return "\n".join(lines)


def report(path: str, trace_id: str = None, limit: int = 16) -> str:
    spans = trace.read_log(path)
    if not spans:
        return f"trace-report: no spans in {path}"
    traces = build_traces(spans)
    if trace_id is not None:
        traces = {k: v for k, v in traces.items() if k.startswith(trace_id)}
        if not traces:
            return f"trace-report: no trace matching {trace_id!r}"
    # Largest traces first: the gang/batch timelines an investigation
    # wants outrank single-mutate noise traces.
    ordered = sorted(
        traces.items(), key=lambda kv: len(kv[1]["spans"]), reverse=True
    )
    shown = ordered[: max(1, limit)]
    out = [render_trace(tid, t) for tid, t in shown]
    if len(ordered) > len(shown):
        out.append(
            f"... {len(ordered) - len(shown)} smaller trace(s) omitted "
            "(--limit raises the cap)"
        )
    return "\n\n".join(out)


# -------------------------------------------------------------- self-check

#: What a complete root→rank tree must contain (the make trace-check gate).
_RANK_SNIPPET = """\
import os
from tpudra import trace

with trace.start_span(
    "rank.worker",
    parent=os.environ.get(trace.TRACEPARENT_ENV) or None,
    attrs={"rank": int(os.environ.get("TRACE_CHECK_RANK", "0"))},
):
    pass
"""


def _grant_env(driver, claim_uid: str) -> dict:
    """The env a container consuming this claim would see (the CDI spec's
    claim-wide env — sim/multihost.MultiHostGang._grant_env without the
    mount rewrite, which the rank stand-in does not need)."""
    spec = driver.state._cdi.read_claim_spec(claim_uid)
    if spec is None:
        raise RuntimeError(f"no CDI spec for {claim_uid}")
    env = {}
    for kv in spec.get("containerEdits", {}).get("env", []):
        k, _, v = kv.partition("=")
        env[k] = v
    return env


def self_check() -> int:
    """Traced mini-bench + tree assertions; 0 on a complete root→rank tree."""
    from tpudra.controller.gang import GangMember, GangReservationManager
    from tpudra.kube import gvr
    from tpudra.kube.fake import FakeKube
    from tpudra.plugin.checkpoint import CheckpointManager
    from tpudra.sim.multihost import (
        DriverGangBinder,
        build_cd_stack,
        close_cd_stack,
        make_channel_claim,
        make_compute_domain,
    )

    nodes = ["tc-node-0", "tc-node-1"]
    failures: list[str] = []
    with tempfile.TemporaryDirectory(prefix="tpudra-trace-check-") as base:
        log = os.path.join(base, "trace.jsonl")
        os.environ[trace.ENV_TRACE] = "1"
        os.environ[trace.ENV_TRACE_LOG] = log
        trace.reset_for_tests()
        try:
            kube = FakeKube()
            for n in nodes:
                kube.create(gvr.NODES, {"metadata": {"name": n}, "spec": {}})
            kube.create(
                gvr.COMPUTE_DOMAINS,
                make_compute_domain("trace-check", "trace-check-uid", nodes),
                "default",
            )
            drivers = build_cd_stack(kube, nodes, base, prefix="tc")
            gang_cp = CheckpointManager(os.path.join(base, "controller"))
            gangs = GangReservationManager(gang_cp, DriverGangBinder(drivers))
            members = [
                GangMember(node=n, claim_uid=f"tc-m{i}")
                for i, n in enumerate(nodes)
            ]
            claims = {
                m.claim_uid: make_channel_claim(
                    m.claim_uid, m.node, "trace-check-uid"
                )
                for m in members
            }
            for claim in claims.values():
                kube.create(gvr.RESOURCE_CLAIMS, claim, "default")
            gangs.reserve("trace-check", members, claims)
            # One stand-in rank process per member: the grant env is the
            # ONLY thing carried across the process boundary.
            for i, m in enumerate(members):
                env = _grant_env(drivers[m.node], m.claim_uid)
                tp = env.get(trace.TRACEPARENT_ENV, "")
                if not tp:
                    failures.append(
                        f"grant env for {m.claim_uid} carries no "
                        f"{trace.TRACEPARENT_ENV}"
                    )
                    continue
                proc = subprocess.run(
                    [sys.executable, "-c", _RANK_SNIPPET],
                    env={
                        trace.ENV_TRACE: "1",
                        trace.ENV_TRACE_LOG: log,
                        trace.TRACEPARENT_ENV: tp,
                        "TRACE_CHECK_RANK": str(i),
                        "PYTHONPATH": REPO,
                        "PATH": os.environ.get("PATH", ""),
                    },
                    capture_output=True,
                    text=True,
                    timeout=60,
                )
                if proc.returncode != 0:
                    failures.append(
                        f"rank stand-in {i} failed: {proc.stderr[-300:]}"
                    )
            gangs.release("trace-check")
            close_cd_stack(drivers)
            gang_cp.close()
            trace.flush()  # same-process reader: drain the buffered tail
            failures.extend(_assert_tree(log, len(members)))
        finally:
            os.environ.pop(trace.ENV_TRACE, None)
            os.environ.pop(trace.ENV_TRACE_LOG, None)
            trace.reset_for_tests()
    if failures:
        for f in failures:
            print(f"trace-check: FAIL: {f}")
        return 1
    print("trace-check: OK (complete gang.reserve → rank.worker span tree)")
    return 0


def _assert_tree(log: str, n_members: int) -> list:
    """The completeness assertions: one trace, gang.reserve root, one
    bind-member per member with checkpoint+CDI child phases, and one
    rank.worker per member chaining to its bind-member."""
    failures: list[str] = []
    spans = trace.read_log(log)
    traces = build_traces(spans)
    gang_traces = [
        (tid, t)
        for tid, t in traces.items()
        if any(r["name"] == "gang.reserve" for r in t["roots"])
    ]
    if len(gang_traces) != 1:
        return [f"expected exactly 1 gang.reserve-rooted trace, got {len(gang_traces)}"]
    tid, t = gang_traces[0]
    root = next(r for r in t["roots"] if r["name"] == "gang.reserve")
    if root.get("parent"):
        failures.append("gang.reserve is not a root span")
    binds = [
        rec for rec in descendants(root, t["children"])
        if rec["name"] == "gang.bind-member"
    ]
    if len(binds) != n_members:
        failures.append(
            f"expected {n_members} gang.bind-member spans under the root, "
            f"got {len(binds)}"
        )
    for bind in binds:
        names = {rec["name"] for rec in descendants(bind, t["children"])}
        for want in ("plugin.prepare", "checkpoint.commit", "bind.cdi-write"):
            if want not in names:
                failures.append(
                    f"bind-member {bind.get('attrs', {}).get('claim')} has no "
                    f"{want} child phase (got {sorted(names)})"
                )
    ranks = [rec for rec in t["spans"].values() if rec["name"] == "rank.worker"]
    if len(ranks) != n_members:
        failures.append(f"expected {n_members} rank.worker spans, got {len(ranks)}")
    for rank in ranks:
        chain = _ancestor_chain(rank, t["spans"])
        if "gang.bind-member" not in chain or "gang.reserve" not in chain:
            failures.append(
                f"rank.worker (pid {rank.get('pid')}) does not chain to a "
                f"gang.bind-member under the root (chain: {chain})"
            )
        if rank.get("pid") == root.get("pid"):
            failures.append(
                "rank.worker span was emitted by the controller process — "
                "the process boundary was not crossed"
            )
    return failures


# --------------------------------------------------------------------- CLI


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Render per-claim/per-gang timelines and critical "
        "paths from a tpudra trace log (docs/tracing.md)."
    )
    parser.add_argument("log", nargs="?", help="trace JSONL file")
    parser.add_argument("--trace", default=None, help="trace id (prefix ok)")
    parser.add_argument("--limit", type=int, default=16)
    parser.add_argument(
        "--json", action="store_true",
        help="emit {trace_id: critical_path_summary} as JSON",
    )
    parser.add_argument(
        "--self-check", action="store_true",
        help="run the traced mini-bench and assert a complete "
        "root→rank span tree (the make trace-check gate)",
    )
    args = parser.parse_args(argv)
    if args.self_check:
        return self_check()
    if not args.log:
        parser.error("a trace log path is required (or --self-check)")
    if args.json:
        spans = trace.read_log(args.log)
        traces = build_traces(spans)
        out = {
            tid: [
                critical_path_summary(root, t["children"])
                for root in t["roots"]
            ]
            for tid, t in traces.items()
            if args.trace is None or tid.startswith(args.trace)
        }
        print(json.dumps(out, indent=2, sort_keys=True))
        return 0
    print(report(args.log, trace_id=args.trace, limit=args.limit))
    return 0


if __name__ == "__main__":
    sys.exit(main())

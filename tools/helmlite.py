"""helmlite: a minimal Helm-template renderer for chart validation in CI.

The build environment has no ``helm`` binary, but the chart under
``deployments/helm/tpu-dra-driver`` must be render-verified (the reference's
e2e suite installs per-file via ``helm upgrade -i``, tests/bats/helpers.sh:42-60).
This implements exactly the template subset the chart uses, so
``tests/test_helm.py`` can assert every manifest renders and parses:

- actions: ``{{ pipeline }}`` with ``-`` whitespace trimming
- data: ``.Values...``, ``.Release.Name/Namespace``,
  ``.Chart.Name/Version/AppVersion``, ``.Capabilities.APIVersions.Has``
- control flow: ``if``/``else if``/``else``/``end``,
  ``range [$k, [$v] :=] ...`` and ``with ...`` (both rebind dot, as in Go),
  ``$var := expr`` declaration and ``$var = expr`` assignment
- ``define``/``include`` (loaded from ``_*.tpl`` files; include renders
  with the caller-supplied dot, so helper patterns like
  ``include "x" (dict "context" . ...)`` work)
- functions: ``quote squote default not and or eq ne gt lt empty fail
  printf toYaml nindent indent trunc trimSuffix lower contains replace
  required join list dict hasKey index splitList concat append int trim
  dir``
- pipelines: ``a | b | c``

It is intentionally NOT a general Go-template engine: unsupported syntax
raises, which is the desired behavior for a chart linter — if a template
uses a construct helmlite doesn't know, the test should fail loudly and
either the template gets simplified or helmlite grows the verb.  The
non-circular fidelity check is ``tests/test_helm.py::TestReferenceChart``:
helmlite renders the REFERENCE driver's chart — a template corpus helmlite
was never written against — and asserts known-good objects come out.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass, field
from typing import Any, Optional

import yaml


class TemplateError(Exception):
    pass


# ---------------------------------------------------------------------------
# Values plumbing
# ---------------------------------------------------------------------------


def deep_merge(base: dict, override: dict) -> dict:
    out = dict(base)
    for k, v in override.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = deep_merge(out[k], v)
        else:
            out[k] = v
    return out


class _APIVersions:
    """``.Capabilities.APIVersions`` with the ``Has`` method charts probe
    for cluster API availability (OpenShift SCCs, resource.k8s.io tiers)."""

    def __init__(self, versions):
        self._versions = set(versions or ())

    def Has(self, version: str) -> bool:  # noqa: N802 — Go method name
        return version in self._versions


_UNSET = object()


@dataclass
class Context:
    values: dict
    release_name: str = "tpudra"
    release_namespace: str = "tpudra-system"
    chart: dict = field(default_factory=dict)
    locals: dict = field(default_factory=dict)
    # The current dot (None = the root context).  ``with`` and ``range``
    # rebind it, Go-style.
    dot: Any = None
    # What ``$`` resolves to: Go binds it to the data the template
    # EXECUTION started with — the chart root for top-level templates,
    # but the caller-supplied dot inside an include.
    dollar: Any = _UNSET
    # API versions ``.Capabilities.APIVersions.Has`` answers for (helm
    # fills this from the live cluster; callers pass a fixed set).
    api_versions: tuple = ()

    def root(self) -> dict:
        return {
            "Values": self.values,
            "Release": {
                "Name": self.release_name,
                "Namespace": self.release_namespace,
                "Service": "Helm",
            },
            "Chart": self.chart,
            "Capabilities": {"APIVersions": _APIVersions(self.api_versions)},
        }

    def current_dot(self) -> Any:
        return self.root() if self.dot is None else self.dot


# ---------------------------------------------------------------------------
# Expression evaluation
# ---------------------------------------------------------------------------

_TOKEN = re.compile(
    r"""
    \s*(
        "(?:[^"\\]|\\.)*"     # double-quoted string
      | `[^`]*`               # raw string
      | \(|\)                 # parens
      | \|                    # pipe
      | \$[A-Za-z0-9_]*(?:\.[A-Za-z0-9_.]+)?  # variable, opt. field path
                              # ($x.f / $.f are ONE token — whitespace
                              # separates them from a path argument)
      | \.[A-Za-z0-9_.]*      # field path
      | -?\d+(?:\.\d+)?       # number
      | [A-Za-z_][A-Za-z0-9_]*  # ident (function or true/false)
    )""",
    re.VERBOSE,
)


def tokenize(expr: str) -> list[str]:
    out, pos = [], 0
    while pos < len(expr):
        m = _TOKEN.match(expr, pos)
        if not m:
            if expr[pos:].strip() == "":
                break
            raise TemplateError(f"cannot tokenize {expr[pos:]!r} in {expr!r}")
        out.append(m.group(1))
        pos = m.end()
    return out


def truthy(v: Any) -> bool:
    return bool(v) and v is not None


class Evaluator:
    def __init__(self, ctx: Context, defines: dict[str, str]):
        self.ctx = ctx
        self.defines = defines

    # -- field / literal resolution -----------------------------------------

    def resolve_path(self, path: str, base: Any) -> Any:
        cur = base
        for part in [p for p in path.split(".") if p]:
            if isinstance(cur, dict):
                cur = cur.get(part)
            else:
                cur = getattr(cur, part, None)
            if cur is None:
                return None
        return cur

    def atom(self, tok: str) -> Any:
        if tok.startswith('"'):
            return tok[1:-1].encode().decode("unicode_escape")
        if tok.startswith("`"):
            return tok[1:-1]
        if tok == ".":
            return self.ctx.current_dot()
        if tok.startswith("."):
            # Field paths resolve against the CURRENT dot (with/range
            # rebind it); ``$`` below reaches the root regardless.
            return self.resolve_path(tok[1:], self.ctx.current_dot())
        if tok.startswith("$"):
            name, _, path = tok[1:].partition(".")
            if not name:
                base = (
                    self.ctx.root()
                    if self.ctx.dollar is _UNSET
                    else self.ctx.dollar
                )
            elif name in self.ctx.locals:
                base = self.ctx.locals[name]
            else:
                raise TemplateError(f"unknown variable ${name}")
            return self.resolve_path(path, base) if path else base
        if re.fullmatch(r"-?\d+", tok):
            return int(tok)
        if re.fullmatch(r"-?\d+\.\d+", tok):
            return float(tok)
        if tok == "true":
            return True
        if tok == "false":
            return False
        if tok in ("nil", "null"):
            return None
        raise TemplateError(f"unresolvable atom {tok!r}")

    # -- function dispatch ---------------------------------------------------

    def call(self, fn: str, args: list[Any]) -> Any:
        if fn == "quote":
            return '"' + str("" if args[0] is None else args[0]).replace('"', '\\"') + '"'
        if fn == "squote":
            return "'" + str("" if args[0] is None else args[0]) + "'"
        if fn == "default":
            # sprig's empty(): 0, "", nil, false, empty collections all take
            # the default — matching real helm exactly.
            return args[1] if truthy(args[1]) else args[0]
        if fn == "not":
            return not truthy(args[0])
        if fn == "and":
            cur = True
            for a in args:
                cur = a
                if not truthy(a):
                    return a
            return cur
        if fn == "or":
            for a in args:
                if truthy(a):
                    return a
            return args[-1] if args else None
        if fn == "eq":
            return all(a == args[0] for a in args[1:])
        if fn == "ne":
            return args[0] != args[1]
        if fn == "empty":
            return not truthy(args[0])
        if fn == "fail":
            raise TemplateError(f"fail: {args[0]}")
        if fn == "required":
            if not truthy(args[1]):
                raise TemplateError(f"required: {args[0]}")
            return args[1]
        if fn == "printf":
            return _go_printf(args[0], args[1:])
        if fn == "toYaml":
            return yaml.safe_dump(args[0], default_flow_style=False).rstrip("\n")
        if fn == "nindent":
            n = int(args[0])
            text = str(args[1])
            pad = " " * n
            return "\n" + "\n".join(
                pad + line if line else line for line in text.splitlines()
            )
        if fn == "indent":
            n = int(args[0])
            pad = " " * n
            return "\n".join(
                pad + line if line else line for line in str(args[1]).splitlines()
            )
        if fn == "trunc":
            n = int(args[0])
            s = str(args[1])
            return s[:n] if n >= 0 else s[n:]
        if fn == "trimSuffix":
            s = str(args[1])
            return s[: -len(args[0])] if args[0] and s.endswith(args[0]) else s
        if fn == "lower":
            return str(args[0]).lower()
        if fn == "contains":
            return str(args[0]) in str(args[1])
        if fn == "replace":
            return str(args[2]).replace(str(args[0]), str(args[1]))
        if fn == "join":
            return str(args[0]).join(str(x) for x in (args[1] or []))
        if fn == "include":
            name, dot = args[0], args[1]
            body = self.defines.get(name)
            if body is None:
                raise TemplateError(f"include of undefined template {name!r}")
            # Included templates run with the caller-supplied dot and a
            # fresh variable scope (Go semantics) — this is what makes
            # helper patterns like ``include "x" (dict "context" . ...)``
            # and ``include "y" (list $a $b)`` render correctly.
            sub_ctx = Context(
                values=self.ctx.values,
                release_name=self.ctx.release_name,
                release_namespace=self.ctx.release_namespace,
                chart=self.ctx.chart,
                dot=dot,
                dollar=dot,  # Go: $ binds to the execution's start data
                api_versions=self.ctx.api_versions,
            )
            sub = Renderer(sub_ctx, self.defines)
            return sub.render(body).strip("\n")
        if fn == "list":
            return list(args)
        if fn == "dict":
            if len(args) % 2:
                raise TemplateError("dict requires an even argument count")
            return {args[i]: args[i + 1] for i in range(0, len(args), 2)}
        if fn == "hasKey":
            return isinstance(args[0], dict) and args[1] in args[0]
        if fn == "index":
            cur = args[0]
            for key in args[1:]:
                if cur is None:
                    return None
                cur = cur[key] if not isinstance(cur, dict) else cur.get(key)
            return cur
        if fn == "splitList":
            return [p for p in str(args[1]).split(str(args[0]))]
        if fn == "concat":
            out: list = []
            for a in args:
                out.extend(a or [])
            return out
        if fn == "append":
            return list(args[0] or []) + [args[1]]
        if fn == "int":
            try:
                return int(args[0] or 0)
            except (TypeError, ValueError):
                return 0
        if fn == "gt":
            return args[0] > args[1]
        if fn == "lt":
            return args[0] < args[1]
        if fn == "trim":
            return str(args[0]).strip()
        if fn == "dir":
            return os.path.dirname(str(args[0]))
        raise TemplateError(f"unsupported function {fn!r}")

    # -- pipeline ------------------------------------------------------------

    def eval(self, expr: str) -> Any:
        return self.eval_tokens(tokenize(expr))

    def eval_tokens(self, toks: list[str]) -> Any:
        # Split on top-level pipes.
        stages: list[list[str]] = [[]]
        depth = 0
        for t in toks:
            if t == "(":
                depth += 1
            elif t == ")":
                depth -= 1
            if t == "|" and depth == 0:
                stages.append([])
            else:
                stages[-1].append(t)
        value, first = None, True
        for stage in stages:
            if not stage:
                raise TemplateError(f"empty pipeline stage in {toks!r}")
            if first:
                value = self.eval_command(stage)
                first = False
            else:
                fn, args = stage[0], self.eval_args(stage[1:])
                value = self.call(fn, args + [value])
        return value

    def eval_command(self, toks: list[str]) -> Any:
        head = toks[0]
        if head == "(":
            # Entire command may be a parenthesized pipeline (possibly with
            # trailing args — not supported; keep it simple).
            inner, rest = self._match_paren(toks)
            if rest:
                raise TemplateError(f"unexpected tokens after parens: {rest!r}")
            return self.eval_tokens(inner)
        if re.fullmatch(r"[A-Za-z_][A-Za-z0-9_]*", head) and head not in (
            "true",
            "false",
            "nil",
            "null",
        ):
            return self.call(head, self.eval_args(toks[1:]))
        value, rest = self.atom(head), toks[1:]
        if rest:
            # A field path with arguments is a Go method call —
            # ``.Capabilities.APIVersions.Has "resource.k8s.io/v1"``.
            if callable(value):
                return value(*self.eval_args(rest))
            raise TemplateError(
                f"unexpected argument list after {head!r}: {toks!r}"
            )
        return value

    def eval_args(self, toks: list[str]) -> list[Any]:
        args: list[Any] = []
        i = 0
        while i < len(toks):
            if toks[i] == "(":
                inner, _rest = self._match_paren(toks[i:])
                args.append(self.eval_tokens(inner))
                i += len(inner) + 2
            else:
                args.append(self.atom(toks[i]))
                i += 1
        return args

    @staticmethod
    def _match_paren(toks: list[str]) -> tuple[list[str], list[str]]:
        assert toks[0] == "("
        depth = 0
        for i, t in enumerate(toks):
            if t == "(":
                depth += 1
            elif t == ")":
                depth -= 1
                if depth == 0:
                    return toks[1:i], toks[i + 1 :]
        raise TemplateError(f"unbalanced parens in {toks!r}")


def _gostr(v: Any) -> str:
    """Render a value the way Go templates do (true/false, no None)."""
    if v is None:
        return ""
    if v is True:
        return "true"
    if v is False:
        return "false"
    return str(v)


def _go_printf(fmt: str, args: list[Any]) -> str:
    # %s/%d/%v are all the chart needs.
    out, ai = [], 0
    i = 0
    while i < len(fmt):
        c = fmt[i]
        if c == "%" and i + 1 < len(fmt):
            spec = fmt[i + 1]
            if spec == "%":
                out.append("%")
            else:
                out.append(_gostr(args[ai]))
                ai += 1
            i += 2
            continue
        out.append(c)
        i += 1
    return "".join(out)


# ---------------------------------------------------------------------------
# Template parsing/rendering
# ---------------------------------------------------------------------------

_ACTION = re.compile(r"\{\{-?\s*(.*?)\s*-?\}\}", re.DOTALL)


def _split_actions(src: str) -> list[tuple[str, str]]:
    """Returns [(kind, payload)]: kind 'text' or 'action'.  Handles the
    ``{{-``/``-}}`` whitespace-trim markers the way Go templates do."""
    parts: list[tuple[str, str]] = []
    pos = 0
    for m in _ACTION.finditer(src):
        text = src[pos : m.start()]
        if m.group(0).startswith("{{-"):
            # Go trims ALL trailing whitespace incl. newlines.
            text = text.rstrip()
        parts.append(("text", text))
        parts.append(("action", m.group(1)))
        pos = m.end()
        if m.group(0).endswith("-}}"):
            while pos < len(src) and src[pos] in " \t\n\r":
                pos += 1
    parts.append(("text", src[pos:]))
    return parts


@dataclass
class _Node:
    kind: str  # text | action | if | range | define
    payload: str = ""
    branches: list = field(default_factory=list)  # for if: [(cond, nodes)...], else: (None, nodes)
    body: list = field(default_factory=list)


def _parse(parts: list[tuple[str, str]]) -> list[_Node]:
    nodes, stack = [], []

    def sink() -> list:
        if stack:
            top = stack[-1]
            if top.kind in ("if", "with"):
                return top.branches[-1][1]
            return top.body
        return nodes

    for kind, payload in parts:
        if kind == "text":
            if payload:
                sink().append(_Node("text", payload))
            continue
        stripped = payload.strip()
        if stripped.startswith("/*"):
            continue  # comment
        if stripped.startswith("if "):
            n = _Node("if")
            n.branches = [(stripped[3:].strip(), [])]
            sink().append(n)
            stack.append(n)
        elif stripped.startswith("with "):
            # Same branch structure as if, but the truthy value becomes dot.
            n = _Node("with")
            n.branches = [(stripped[5:].strip(), [])]
            sink().append(n)
            stack.append(n)
        elif stripped.startswith("else if "):
            if not stack or stack[-1].kind not in ("if", "with"):
                raise TemplateError("else if outside if")
            stack[-1].branches.append((stripped[len("else if ") :].strip(), []))
        elif stripped == "else":
            if not stack or stack[-1].kind not in ("if", "with"):
                raise TemplateError("else outside if")
            stack[-1].branches.append((None, []))
        elif re.match(r"^\$\w+\s*:?=\s*", stripped):
            # Variable declaration ($x := expr) or assignment ($x = expr);
            # one flat per-render scope, which matches how charts use them.
            sink().append(_Node("assign", stripped))
        elif stripped.startswith("range "):
            n = _Node("range", stripped[len("range ") :].strip())
            sink().append(n)
            stack.append(n)
        elif stripped.startswith("define "):
            name = stripped[len("define ") :].strip().strip('"')
            n = _Node("define", name)
            sink().append(n)
            stack.append(n)
        elif stripped == "end":
            if not stack:
                raise TemplateError("end without open block")
            stack.pop()
        else:
            sink().append(_Node("action", stripped))
    if stack:
        raise TemplateError(f"unclosed block {stack[-1].kind}")
    return nodes


class Renderer:
    def __init__(self, ctx: Context, defines: dict[str, str]):
        self.ctx = ctx
        self.defines = defines
        self.ev = Evaluator(ctx, defines)

    def render(self, src: str) -> str:
        return self._render_nodes(_parse(_split_actions(src)))

    def _render_nodes(self, nodes: list[_Node]) -> str:
        out: list[str] = []
        for n in nodes:
            if n.kind == "text":
                out.append(n.payload)
            elif n.kind == "action":
                out.append(_gostr(self.ev.eval(n.payload)))
            elif n.kind == "define":
                # Re-serialize the body so include can re-render it with the
                # caller's context.  (Bodies are stored raw at load time via
                # load_defines; a define encountered mid-file is ignored.)
                continue
            elif n.kind == "if":
                for cond, body in n.branches:
                    if cond is None or truthy(self.ev.eval(cond)):
                        out.append(self._render_nodes(body))
                        break
            elif n.kind == "with":
                out.append(self._render_with(n))
            elif n.kind == "assign":
                var, _, expr = re.match(
                    r"^\$(\w+)\s*(:?=)\s*(.+)$", n.payload
                ).groups()
                self.ctx.locals[var] = self.ev.eval(expr)
            elif n.kind == "range":
                out.append(self._render_range(n))
        return "".join(out)

    def _render_with(self, n: _Node) -> str:
        """``with expr``: render the body with dot rebound to the value
        when truthy; else branches render with dot unchanged (Go)."""
        cond, body = n.branches[0]
        value = self.ev.eval(cond)
        if truthy(value):
            saved = self.ctx.dot
            self.ctx.dot = value
            try:
                return self._render_nodes(body)
            finally:
                self.ctx.dot = saved
        for cond2, body2 in n.branches[1:]:
            if cond2 is None or truthy(self.ev.eval(cond2)):
                return self._render_nodes(body2)
        return ""

    def _render_range(self, n: _Node) -> str:
        """All three Go range forms; dot is rebound to each element (with
        or without loop variables — Go does both)."""
        spec = n.payload
        kvar = vvar = None
        m = re.match(r"^\$(\w+),\s*\$(\w+)\s*:=\s*(.+)$", spec)
        if m:
            kvar, vvar, expr = m.groups()
        else:
            m = re.match(r"^\$(\w+)\s*:=\s*(.+)$", spec)
            if m:
                vvar, expr = m.groups()
            else:
                expr = spec
        coll = self.ev.eval(expr)
        if isinstance(coll, str):
            # Go templates cannot range over a string; silently iterating
            # characters would render N wrong copies instead of failing
            # the lint (this module's contract).
            raise TemplateError(f"range can't iterate over string {coll!r}")
        if isinstance(coll, dict):
            items = list(coll.items())
        else:
            items = list(enumerate(coll or []))
        out = []
        saved = self.ctx.dot
        try:
            for k, v in items:
                if kvar:
                    self.ctx.locals[kvar] = k
                if vvar:
                    self.ctx.locals[vvar] = v
                self.ctx.dot = v
                out.append(self._render_nodes(n.body))
        finally:
            self.ctx.dot = saved
            for var in (kvar, vvar):
                if var:
                    self.ctx.locals.pop(var, None)
        return "".join(out)


# ---------------------------------------------------------------------------
# Chart-level driver
# ---------------------------------------------------------------------------


def load_defines(src: str) -> dict[str, str]:
    """Extract {{ define "name" }}...{{ end }} bodies textually, tracking
    block nesting so defines containing if/range blocks keep their inner
    {{ end }}s."""
    defines: dict[str, str] = {}
    open_name: Optional[str] = None
    depth = 0
    body_start = 0
    for m in _ACTION.finditer(src):
        payload = m.group(1).strip()
        if open_name is None:
            dm = re.match(r'define\s+"([^"]+)"', payload)
            if dm:
                open_name = dm.group(1)
                depth = 0
                body_start = m.end()
            continue
        if payload.startswith(("if ", "range ", "with ")) or re.match(
            r'define\s+"', payload
        ):
            depth += 1
        elif payload == "end":
            if depth == 0:
                defines[open_name] = src[body_start : m.start()]
                open_name = None
            else:
                depth -= 1
    if open_name is not None:
        raise TemplateError(f"unterminated define {open_name!r}")
    return defines


class Chart:
    def __init__(self, chart_dir: str):
        self.dir = chart_dir
        with open(os.path.join(chart_dir, "Chart.yaml")) as f:
            self.meta = yaml.safe_load(f)
        with open(os.path.join(chart_dir, "values.yaml")) as f:
            self.default_values = yaml.safe_load(f) or {}
        self.defines: dict[str, str] = {}
        tdir = os.path.join(chart_dir, "templates")
        self.templates: dict[str, str] = {}
        for name in sorted(os.listdir(tdir)):
            path = os.path.join(tdir, name)
            with open(path) as f:
                src = f.read()
            if name.startswith("_"):
                self.defines.update(load_defines(src))
            elif name.endswith((".yaml", ".yml", ".tpl")):
                self.templates[name] = src

    def render(
        self,
        values: Optional[dict] = None,
        release_name: str = "tpudra",
        namespace: str = "tpudra-system",
        api_versions: tuple = (),
    ) -> dict[str, list[dict]]:
        """Render every template; returns {template_name: [parsed docs]}.
        ``api_versions`` answers ``.Capabilities.APIVersions.Has`` (helm
        reads these off the live cluster; here the caller fixes them)."""
        merged = deep_merge(self.default_values, values or {})
        chart_meta = {
            "Name": self.meta.get("name", ""),
            "Version": self.meta.get("version", ""),
            "AppVersion": self.meta.get("appVersion", ""),
        }
        out: dict[str, list[dict]] = {}
        for name, src in self.templates.items():
            ctx = Context(
                values=merged,
                release_name=release_name,
                release_namespace=namespace,
                chart=chart_meta,
                api_versions=api_versions,
            )
            text = Renderer(ctx, self.defines).render(src)
            try:
                docs = [d for d in yaml.safe_load_all(text) if d]
            except yaml.YAMLError as e:
                raise TemplateError(f"{name}: rendered YAML invalid: {e}\n{text}") from e
            out[name] = docs
        return out

    def crds(self) -> list[dict]:
        crd_dir = os.path.join(self.dir, "crds")
        docs = []
        if os.path.isdir(crd_dir):
            for name in sorted(os.listdir(crd_dir)):
                with open(os.path.join(crd_dir, name)) as f:
                    docs.extend(d for d in yaml.safe_load_all(f) if d)
        return docs


def main(argv=None) -> int:
    """CLI: render a chart to YAML on stdout (a `helm template` stand-in
    for environments without the helm binary):

        python tools/helmlite.py deployments/helm/tpu-dra-driver \
            --set image.tag=v0.1.0 | kubectl apply -f -
    """
    import argparse
    import sys

    p = argparse.ArgumentParser(
        "helmlite", description="minimal `helm template` for chart rendering"
    )
    p.add_argument("chart_dir")
    p.add_argument(
        "--set", action="append", default=[], dest="sets",
        help="dotted.key=value override (repeatable)",
    )
    p.add_argument("--release", default="tpudra")
    p.add_argument("--namespace", default="tpudra-system")
    p.add_argument(
        "--no-crds", action="store_true", help="omit the chart's crds/ directory"
    )
    args = p.parse_args(argv)

    overrides: dict = {}
    for spec in args.sets:
        key, sep, value = spec.partition("=")
        if not sep:
            p.error(f"--set {spec!r}: expected key=value")
        node = overrides
        parts = key.split(".")
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        # Light coercion, mirroring helm: bools and ints stay typed.
        if value in ("true", "false"):
            typed: object = value == "true"
        else:
            try:
                typed = int(value)
            except ValueError:
                typed = value
        node[parts[-1]] = typed

    chart = Chart(args.chart_dir)
    docs: list[dict] = []
    if not args.no_crds:
        docs.extend(chart.crds())
    rendered = chart.render(
        overrides, release_name=args.release, namespace=args.namespace
    )
    for name in sorted(rendered):
        docs.extend(rendered[name])
    sys.stdout.write(yaml.safe_dump_all(docs, sort_keys=False))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

#!/usr/bin/env python3
"""kubectlite — the kubectl subset the bats e2e suite needs.

Talks to the apiserver named by ``KUBE_API_SERVER`` (the hermetic fake) or,
failing that, a kubeconfig — so the same test scripts drive either the
simulator or a real cluster (where plain kubectl also works, since the wire
format is identical).

Supported verbs: apply -f, get (-o json|yaml|name|jsonpath=...), delete,
wait (--for=condition=X / --for=jsonpath=.../ --for=delete), logs (reads the
simulator's log annotations), label, version.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time
import urllib.error

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import yaml  # noqa: E402

from tpudra.kube import gvr as gvrmod  # noqa: E402
from tpudra.kube.client import KubeClient  # noqa: E402
from tpudra.kube.errors import ApiError, Conflict, NotFound  # noqa: E402
from tpudra.sim.kubelet import LOG_ANNOTATION_PREFIX  # noqa: E402

ALIASES = {
    "po": "pods", "pod": "pods",
    "no": "nodes", "node": "nodes",
    "cm": "configmaps", "configmap": "configmaps",
    "svc": "services", "service": "services",
    "ds": "daemonsets", "daemonset": "daemonsets",
    "deploy": "deployments", "deployment": "deployments",
    "resourceclaim": "resourceclaims",
    "rct": "resourceclaimtemplates",
    "resourceclaimtemplate": "resourceclaimtemplates",
    "resourceslice": "resourceslices",
    "deviceclass": "deviceclasses",
    "cd": "computedomains", "computedomain": "computedomains",
    "cdclique": "computedomaincliques",
    "computedomainclique": "computedomaincliques",
}


def resolve_type(name: str) -> gvrmod.GVR:
    plural = ALIASES.get(name.lower(), name.lower())
    for g in gvrmod.ALL_GVRS:
        if g.resource == plural or g.kind.lower() == name.lower():
            return g
    sys.exit(f"error: unknown resource type {name!r}")


def resolve_kind(kind: str, api_version: str) -> gvrmod.GVR:
    group = api_version.split("/")[0] if "/" in api_version else ""
    for g in gvrmod.ALL_GVRS:
        if g.kind == kind and g.group == group:
            return g
    sys.exit(f"error: no resource registered for kind {kind!r} ({api_version})")


def client() -> KubeClient:
    server = os.environ.get("KUBE_API_SERVER")
    if server:
        return KubeClient(server)
    if os.environ.get("KUBECONFIG") or os.path.exists(
        os.path.expanduser("~/.kube/config")
    ):
        return KubeClient.from_kubeconfig()
    sys.exit("error: KUBE_API_SERVER is not set and no kubeconfig found")


def load_docs(path: str) -> list[dict]:
    data = sys.stdin.read() if path == "-" else open(path).read()
    return [d for d in yaml.safe_load_all(data) if d]


# ------------------------------------------------------------------ jsonpath

def _jsonpath_tokens(expr: str):
    """Tokenize a jsonpath body into key / index steps.

    Beyond bare keys, supports kubectl's two ways to address keys that
    contain dots (annotation/label keys like sim.tpu.google.com/event):
    backslash-escaped dots (``.annotations.sim\\.tpu\\.google\\.com/event``)
    and bracket-quoted keys (``.annotations['sim.tpu.google.com/event']``).
    """
    i, n = 0, len(expr)
    while i < n:
        c = expr[i]
        if c == ".":
            i += 1
        elif c == "[":
            j = expr.find("]", i)
            if j < 0:
                raise ValueError(f"unclosed '[' at offset {i}")
            inner = expr[i + 1 : j]
            if inner == "*":
                yield ("wild", None)
            elif len(inner) >= 2 and inner[0] in "'\"" and inner[-1] == inner[0]:
                yield ("key", inner[1:-1])
            else:
                try:
                    yield ("idx", int(inner))
                except ValueError:
                    raise ValueError(f"bad index/quoted key [{inner}]") from None
            i = j + 1
        else:
            # Bare key: runs to the next unescaped '.' or '['.
            out = []
            while i < n and expr[i] not in ".[":
                if expr[i] == "\\" and i + 1 < n:
                    out.append(expr[i + 1])
                    i += 2
                else:
                    out.append(expr[i])
                    i += 1
            yield ("key", "".join(out))


def jsonpath(obj, expr: str):
    """Minimal jsonpath: {.a.b[0].c}, [*] wildcards, ['quoted.key'] and
    backslash-escaped dotted keys.  Raises ValueError (with the offending
    segment) on malformed expressions, like kubectl's own parse error."""
    orig = expr
    expr = expr.strip()
    if expr.startswith("{") and expr.endswith("}"):
        expr = expr[1:-1]
    expr = expr.lstrip(".")
    try:
        tokens = list(_jsonpath_tokens(expr))
    except ValueError as e:
        raise ValueError(f"malformed jsonpath {orig!r}: {e}") from None
    values = [obj]
    for kind, arg in tokens:
        next_values = []
        for v in values:
            if kind == "key":
                if isinstance(v, dict) and arg in v:
                    next_values.append(v[arg])
            elif kind == "wild":
                if isinstance(v, list):
                    next_values.extend(v)
            else:
                if isinstance(v, list) and -len(v) <= arg < len(v):
                    next_values.append(v[arg])
        values = next_values
    return values


def fmt_value(v) -> str:
    if isinstance(v, (dict, list)):
        return json.dumps(v)
    return str(v)


# --------------------------------------------------------------------- verbs

def cmd_apply(args) -> int:
    kube = client()
    for doc in load_docs(args.filename):
        g = resolve_kind(doc.get("kind", ""), doc.get("apiVersion", ""))
        ns = doc.get("metadata", {}).get("namespace") or args.namespace
        name = doc.get("metadata", {}).get("name", "")
        try:
            kube.create(g, doc, ns if g.namespaced else None)
            verb = "created"
        except (Conflict, ApiError) as e:
            if "exists" not in str(e).lower():
                raise
            live = kube.get(g, name, ns if g.namespaced else None)
            doc.setdefault("metadata", {})["resourceVersion"] = live["metadata"].get(
                "resourceVersion", ""
            )
            doc["metadata"].setdefault("uid", live["metadata"].get("uid"))
            kube.update(g, doc, ns if g.namespaced else None)
            verb = "configured"
        print(f"{g.resource}/{name} {verb}")
    return 0


def cmd_delete(args) -> int:
    kube = client()
    targets: list[tuple[gvrmod.GVR, str, str]] = []
    if args.filename:
        for doc in load_docs(args.filename):
            g = resolve_kind(doc.get("kind", ""), doc.get("apiVersion", ""))
            ns = doc.get("metadata", {}).get("namespace") or args.namespace
            targets.append((g, doc["metadata"]["name"], ns))
    else:
        g = resolve_type(args.type)
        for name in args.names:
            targets.append((g, name, args.namespace))
    rc = 0
    for g, name, ns in targets:
        try:
            kube.delete(g, name, ns if g.namespaced else None)
            print(f"{g.resource}/{name} deleted")
        except NotFound:
            if not args.ignore_not_found:
                print(f"error: {g.resource}/{name} not found", file=sys.stderr)
                rc = 1
    return rc


def _get_objects(kube, args):
    g = resolve_type(args.type)
    ns = None if args.all_namespaces else (args.namespace if g.namespaced else None)
    if args.names:
        return g, [kube.get(g, n, ns) for n in args.names]
    out = kube.list(
        g, ns,
        label_selector=args.selector or None,
        field_selector=args.field_selector or None,
    )
    return g, out.get("items", [])


def cmd_get(args) -> int:
    kube = client()
    try:
        g, objs = _get_objects(kube, args)
    except NotFound as e:
        if args.ignore_not_found:
            return 0
        sys.exit(f"error: {e}")
    o = args.output
    if o == "json":
        payload = objs[0] if (args.names and len(objs) == 1) else {
            "apiVersion": "v1", "kind": "List", "items": objs,
        }
        print(json.dumps(payload, indent=2))
    elif o == "yaml":
        payload = objs[0] if (args.names and len(objs) == 1) else {
            "apiVersion": "v1", "kind": "List", "items": objs,
        }
        print(yaml.safe_dump(payload, sort_keys=False))
    elif o == "name":
        for obj in objs:
            print(f"{g.resource}/{obj['metadata']['name']}")
    elif o and o.startswith("jsonpath="):
        expr = o[len("jsonpath="):]
        scope = objs[0] if (args.names and len(objs) == 1) else {"items": objs}
        try:
            values = jsonpath(scope, expr)
        except ValueError as e:
            sys.exit(f"error: {e}")
        print(" ".join(fmt_value(v) for v in values))
    else:
        rows = []
        for obj in objs:
            phase = obj.get("status", {}).get("phase", "")
            ready = ""
            for c in obj.get("status", {}).get("conditions", []):
                if c.get("type") == "Ready":
                    ready = c.get("status", "")
            rows.append((obj["metadata"]["name"], phase, ready))
        if not rows:
            # kubectl exits 0 on an empty table list.
            print("No resources found", file=sys.stderr)
            return 0
        print(f"{'NAME':40} {'PHASE':12} READY")
        for name, phase, ready in rows:
            print(f"{name:40} {phase:12} {ready}")
    return 0


def _condition_met(obj: dict, cond: str) -> bool:
    want_type, _, want_status = cond.partition("=")
    want_status = want_status or "True"
    for c in obj.get("status", {}).get("conditions", []):
        if c.get("type", "").lower() == want_type.lower():
            return str(c.get("status", "")).lower() == want_status.lower()
    return False


def cmd_wait(args) -> int:
    kube = client()
    g, name = None, None
    if "/" in args.target:
        tname, name = args.target.split("/", 1)
        g = resolve_type(tname)
    else:
        g = resolve_type(args.target)
    timeout = parse_duration(args.timeout)
    deadline = time.monotonic() + timeout
    mode = args.wait_for
    last_err = ""
    while time.monotonic() < deadline:
        try:
            if name:
                objs = [kube.get(g, name, args.namespace if g.namespaced else None)]
            else:
                objs = kube.list(
                    g,
                    args.namespace if g.namespaced else None,
                    label_selector=args.selector or None,
                ).get("items", [])
            if mode == "delete":
                if not objs:
                    return 0
            elif mode.startswith("condition="):
                if objs and all(_condition_met(o, mode[len("condition="):]) for o in objs):
                    return 0
            elif mode.startswith("jsonpath="):
                expr, _, want = mode[len("jsonpath="):].partition("=")
                ok = bool(objs)
                for o in objs:
                    try:
                        got = jsonpath(o, expr)
                    except ValueError as e:
                        # Malformed expression never becomes true: error out
                        # instead of polling until the wait timeout.
                        sys.exit(f"error: {e}")
                    if want:
                        ok = ok and got and fmt_value(got[0]) == want
                    else:
                        ok = ok and bool(got)
                if ok:
                    return 0
            else:
                sys.exit(f"error: unsupported --for {mode!r}")
            last_err = "condition not met"
        except NotFound as e:
            if mode == "delete":
                return 0
            last_err = str(e)
        time.sleep(0.2)
    print(f"error: timed out waiting for {args.target}: {last_err}", file=sys.stderr)
    return 1


def cmd_logs(args) -> int:
    kube = client()
    pod = kube.get(gvrmod.PODS, args.pod, args.namespace)
    ann = pod["metadata"].get("annotations", {})
    if args.container:
        keys = [LOG_ANNOTATION_PREFIX + args.container]
    else:
        keys = sorted(k for k in ann if k.startswith(LOG_ANNOTATION_PREFIX))
    if not keys or not any(k in ann for k in keys):
        # Logs land in annotations when a container exits or on demand; a
        # running container's output may not be synced yet.
        print("", end="")
        return 0
    for k in keys:
        if k in ann:
            sys.stdout.write(ann[k])
    return 0


def cmd_label(args) -> int:
    kube = client()
    g = resolve_type(args.type)
    labels = {}
    for kv in args.labels:
        if kv.endswith("-"):
            labels[kv[:-1]] = None
        else:
            k, _, v = kv.partition("=")
            labels[k] = v
    kube.patch(
        g, args.name, {"metadata": {"labels": labels}},
        args.namespace if g.namespaced else None,
    )
    print(f"{g.resource}/{args.name} labeled")
    return 0


def parse_duration(s: str) -> float:
    m = re.fullmatch(r"(\d+(?:\.\d+)?)(s|m|h)?", s)
    if not m:
        sys.exit(f"error: bad duration {s!r}")
    mult = {"s": 1, "m": 60, "h": 3600}[m.group(2) or "s"]
    return float(m.group(1)) * mult


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="kubectlite", description=__doc__)
    sub = p.add_subparsers(dest="verb", required=True)

    ap = sub.add_parser("apply")
    ap.add_argument("-f", "--filename", required=True)
    ap.add_argument("-n", "--namespace", default="default")
    ap.set_defaults(fn=cmd_apply)

    dp = sub.add_parser("delete")
    dp.add_argument("type", nargs="?")
    dp.add_argument("names", nargs="*")
    dp.add_argument("-f", "--filename")
    dp.add_argument("-n", "--namespace", default="default")
    dp.add_argument("--ignore-not-found", action="store_true")
    dp.set_defaults(fn=cmd_delete)

    gp = sub.add_parser("get")
    gp.add_argument("type")
    gp.add_argument("names", nargs="*")
    gp.add_argument("-n", "--namespace", default="default")
    gp.add_argument("-A", "--all-namespaces", action="store_true")
    gp.add_argument("-o", "--output", default="")
    gp.add_argument("-l", "--selector", default="")
    gp.add_argument("--field-selector", default="")
    gp.add_argument("--ignore-not-found", action="store_true")
    gp.set_defaults(fn=cmd_get)

    wp = sub.add_parser("wait")
    wp.add_argument("target", help="type/name or type with -l")
    wp.add_argument("--for", dest="wait_for", required=True)
    wp.add_argument("-n", "--namespace", default="default")
    wp.add_argument("-l", "--selector", default="")
    wp.add_argument("--timeout", default="30s")
    wp.set_defaults(fn=cmd_wait)

    lp = sub.add_parser("logs")
    lp.add_argument("pod")
    lp.add_argument("-c", "--container", default="")
    lp.add_argument("-n", "--namespace", default="default")
    lp.set_defaults(fn=cmd_logs)

    lb = sub.add_parser("label")
    lb.add_argument("type")
    lb.add_argument("name")
    lb.add_argument("labels", nargs="+")
    lb.add_argument("-n", "--namespace", default="default")
    lb.set_defaults(fn=cmd_label)

    vp = sub.add_parser("version")
    vp.set_defaults(fn=lambda a: (print("kubectlite (tpudra hermetic harness)"), 0)[1])

    args = p.parse_args(argv)
    if args.verb == "delete" and not args.filename and not (args.type and args.names):
        p.error("delete needs a resource type plus name(s), or -f FILE")
    try:
        return args.fn(args)
    except ApiError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    except urllib.error.URLError as e:
        print(f"error: cannot reach the apiserver: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())

"""Custom-resource API types for the TPU DRA driver.

The analog of the reference's api/nvidia.com/resource/v1beta1 package: opaque
per-claim configs with Normalize/Validate (api.go:41-45) and strict/non-strict
decoders dispatching on apiVersion+kind (api.go:47-58).
"""

from __future__ import annotations

from typing import Protocol, runtime_checkable

from tpudra import API_GROUP, API_VERSION
from tpudra.api import serde
from tpudra.api.computedomain import (
    COMPUTE_DOMAIN_CHANNEL_CONFIG_KIND,
    COMPUTE_DOMAIN_DAEMON_CONFIG_KIND,
    ComputeDomainChannelConfig,
    ComputeDomainDaemonConfig,
)
from tpudra.api.serde import DecodeError
from tpudra.api.tpuconfig import (
    TPU_CONFIG_KIND,
    TPU_PARTITION_CONFIG_KIND,
    VFIO_DEVICE_CONFIG_KIND,
    TpuConfig,
    TpuPartitionConfig,
    VfioDeviceConfig,
)

API_VERSION_STR = f"{API_GROUP}/{API_VERSION}"


@runtime_checkable
class Config(Protocol):
    """Every opaque config implements normalize() and validate()
    (reference api.go:41-45)."""

    def normalize(self) -> None: ...

    def validate(self) -> None: ...


_KINDS = {
    TPU_CONFIG_KIND: TpuConfig,
    TPU_PARTITION_CONFIG_KIND: TpuPartitionConfig,
    VFIO_DEVICE_CONFIG_KIND: VfioDeviceConfig,
    COMPUTE_DOMAIN_CHANNEL_CONFIG_KIND: ComputeDomainChannelConfig,
    COMPUTE_DOMAIN_DAEMON_CONFIG_KIND: ComputeDomainDaemonConfig,
}


def decode_config(data: dict, *, strict: bool = True) -> Config:
    """Decode an opaque config object by apiVersion+kind.

    Strict mode rejects unknown fields (webhook/prepare path); non-strict
    tolerates fields written by newer driver versions (checkpoint path,
    reference api.go:54-58).
    """
    if not isinstance(data, dict):
        raise DecodeError("opaque config must be a JSON object")
    api_version = data.get("apiVersion", "")
    kind = data.get("kind", "")
    if api_version != API_VERSION_STR:
        raise DecodeError(
            f"unsupported apiVersion {api_version!r} (want {API_VERSION_STR})"
        )
    cls = _KINDS.get(kind)
    if cls is None:
        raise DecodeError(f"unsupported kind {kind!r}")
    return serde.decode(cls, data, strict=strict)


def encode_config(config: Config) -> dict:
    return serde.encode(config)


__all__ = [
    "Config",
    "DecodeError",
    "decode_config",
    "encode_config",
    "TpuConfig",
    "TpuPartitionConfig",
    "VfioDeviceConfig",
    "ComputeDomainChannelConfig",
    "ComputeDomainDaemonConfig",
    "API_VERSION_STR",
]

"""Kubernetes resource.Quantity parsing (the subset our configs need).

The reference uses k8s.io/apimachinery resource.Quantity for MPS pinned-memory
limits (api/nvidia.com/resource/v1beta1/sharing.go:63,82-89).  We support the
binary (Ki/Mi/Gi/Ti/Pi/Ei) and decimal (k/M/G/T/P/E, m) suffixes plus plain
integers, which covers every quantity a device-memory limit can express.
"""

from __future__ import annotations

import re

_SUFFIXES = {
    "": 1,
    "k": 10**3,
    "M": 10**6,
    "G": 10**9,
    "T": 10**12,
    "P": 10**15,
    "E": 10**18,
    "Ki": 2**10,
    "Mi": 2**20,
    "Gi": 2**30,
    "Ti": 2**40,
    "Pi": 2**50,
    "Ei": 2**60,
}

_QUANTITY_RE = re.compile(r"^([+-]?[0-9]+(?:\.[0-9]+)?)([a-zA-Z]{0,2})$")


class InvalidQuantity(ValueError):
    pass


def parse_quantity(s: str | int | float) -> int:
    """Parse a quantity string to an integer number of base units (bytes).

    Fractional results round up, matching k8s canonicalization for values
    that cannot be represented exactly.
    """
    if isinstance(s, (int, float)):
        return int(s)
    m = _QUANTITY_RE.match(s.strip())
    if not m:
        raise InvalidQuantity(f"invalid quantity {s!r}")
    number, suffix = m.group(1), m.group(2)
    if suffix != "m" and suffix not in _SUFFIXES:
        raise InvalidQuantity(f"invalid quantity suffix {suffix!r} in {s!r}")
    if "." not in number:
        # Integer path: exact arithmetic (k8s Quantity is exact; float would
        # lose precision above 2^53).
        value = int(number)
        if suffix == "m":
            return -(-value // 1000) if value >= 0 else value // 1000
        return value * _SUFFIXES[suffix]
    value = float(number)
    scaled = value / 1000.0 if suffix == "m" else value * _SUFFIXES[suffix]
    out = int(scaled)
    if scaled > out:
        out += 1
    return out


def format_mebibytes(nbytes: int) -> tuple[str, bool]:
    """Render a byte count as whole mebibytes ("<n>M" — the unit string the
    MPS-analog control daemon consumes; reference sharing.go:262-265).

    Returns (text, valid); valid is False when the limit truncates to zero.
    """
    mib = nbytes // (1024 * 1024)
    return f"{mib}M", mib > 0

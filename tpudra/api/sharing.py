"""Sharing strategy types for TPU chips and partitions.

The analog of api/nvidia.com/resource/v1beta1/sharing.go.  Two strategies:

- ``TimeSlicing``: cooperative time-sharing of a full chip.  TPUs have no
  hardware compute-policy knob like `nvidia-smi compute-policy`; the interval
  is carried through to the runtime as a scheduling hint
  (``TPU_TIMESLICE_HINT``) and recorded on the device attribute surface.
- ``MultiProcess``: the MPS analog — several processes share one chip, each
  restricted to a slice of HBM and a percentage of TensorCores, brokered by a
  per-claim control daemon (reference sharing.go:123-445).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from tpudra.api.quantity import InvalidQuantity, format_mebibytes, parse_quantity

TIME_SLICING_STRATEGY = "TimeSlicing"
MULTI_PROCESS_STRATEGY = "MultiProcess"

DEFAULT_TIME_SLICE = "Default"
SHORT_TIME_SLICE = "Short"
MEDIUM_TIME_SLICE = "Medium"
LONG_TIME_SLICE = "Long"

_TIME_SLICE_ORDINALS = {
    DEFAULT_TIME_SLICE: 0,
    SHORT_TIME_SLICE: 1,
    MEDIUM_TIME_SLICE: 2,
    LONG_TIME_SLICE: 3,
}


def time_slice_ordinal(interval: str) -> int:
    """Integer encoding of a timeslice interval; -1 if invalid
    (reference sharing.go:232-244)."""
    return _TIME_SLICE_ORDINALS.get(interval, -1)


class SharingValidationError(ValueError):
    pass


def _validate_strategy_gate(strategy: str) -> None:
    """A strategy is only valid when its feature gate is enabled — admission
    must reject configuration of disabled features (reference validate.go:26-45,
    'unknown GPU sharing strategy' whenever the gate is off)."""
    from tpudra import featuregates

    if strategy == TIME_SLICING_STRATEGY:
        if not featuregates.enabled(featuregates.TIME_SLICING_SETTINGS):
            raise SharingValidationError(
                f"unknown sharing strategy: {strategy!r} "
                f"(feature gate {featuregates.TIME_SLICING_SETTINGS} is disabled)"
            )
    elif strategy == MULTI_PROCESS_STRATEGY:
        if not featuregates.enabled(featuregates.MULTI_PROCESS_SHARING):
            raise SharingValidationError(
                f"unknown sharing strategy: {strategy!r} "
                f"(feature gate {featuregates.MULTI_PROCESS_SHARING} is disabled)"
            )
    else:
        raise SharingValidationError(f"unknown sharing strategy: {strategy!r}")


@dataclass
class TimeSlicingConfig:
    interval: Optional[str] = field(default=None, metadata={"json": "interval"})

    def validate(self) -> None:
        if self.interval is not None and self.interval not in _TIME_SLICE_ORDINALS:
            raise SharingValidationError(
                f"unknown time-slice interval: {self.interval!r}"
            )


@dataclass
class MultiProcessConfig:
    """Settings for the multi-process (MPS-analog) control daemon."""

    default_active_tensorcore_percentage: Optional[int] = field(
        default=None, metadata={"json": "defaultActiveTensorCorePercentage"}
    )
    # Pinned HBM limit applied to every allocated chip, overridable per chip
    # via default_per_device_pinned_hbm_limit (keys: chip UUID or claim-local
    # device index).
    default_pinned_hbm_limit: Optional[str] = field(
        default=None, metadata={"json": "defaultPinnedHbmLimit"}
    )
    default_per_device_pinned_hbm_limit: dict[str, str] = field(
        default_factory=dict, metadata={"json": "defaultPerDevicePinnedHbmLimit"}
    )

    def validate(self) -> None:
        pct = self.default_active_tensorcore_percentage
        if pct is not None and not 0 < pct <= 100:
            raise SharingValidationError(
                f"defaultActiveTensorCorePercentage must be in (0, 100]: {pct}"
            )
        for key, value in list(self.default_per_device_pinned_hbm_limit.items()):
            try:
                parse_quantity(value)
            except InvalidQuantity as e:
                raise SharingValidationError(f"limit for {key!r}: {e}") from e
        if self.default_pinned_hbm_limit is not None:
            try:
                parse_quantity(self.default_pinned_hbm_limit)
            except InvalidQuantity as e:
                raise SharingValidationError(f"defaultPinnedHbmLimit: {e}") from e

    def normalized_limits(self, uuids: list[str]) -> dict[str, str]:
        """Resolve per-device pinned HBM limits for the allocated ``uuids``.

        The default limit (if any) applies to every device first, then
        per-device entries override it.  Keys may be chip UUIDs or integer
        indexes into ``uuids``.  Mirrors MpsPerDevicePinnedMemoryLimit.Normalize
        (reference sharing.go:251-276): values are rendered as whole mebibytes
        and must not truncate to zero.
        """
        limits: dict[str, str] = {}
        if self.default_pinned_hbm_limit is not None and uuids:
            text, ok = format_mebibytes(parse_quantity(self.default_pinned_hbm_limit))
            if not ok:
                raise SharingValidationError(
                    "invalid limit: default value set too low: "
                    f"{self.default_pinned_hbm_limit}"
                )
            for uuid in uuids:
                limits[uuid] = text

        known = set(uuids)
        for key, value in self.default_per_device_pinned_hbm_limit.items():
            if key in known:
                uuid = key
            else:
                try:
                    index = int(key)
                except ValueError:
                    raise SharingValidationError(
                        f"invalid device: unable to parse key as an integer: {key}"
                    ) from None
                if not 0 <= index < len(uuids):
                    raise SharingValidationError(f"invalid device: invalid device index: {index}")
                uuid = uuids[index]
            text, ok = format_mebibytes(parse_quantity(value))
            if not ok:
                raise SharingValidationError(
                    f"invalid limit: value set too low: {key}: {value}"
                )
            limits[uuid] = text
        return limits


@dataclass
class TpuSharing:
    """Sharing strategy selection for a full TPU chip
    (reference GpuSharing, sharing.go:66-71)."""

    strategy: str = field(default="", metadata={"json": "strategy"})
    time_slicing_config: Optional[TimeSlicingConfig] = field(
        default=None, metadata={"json": "timeSlicingConfig"}
    )
    multi_process_config: Optional[MultiProcessConfig] = field(
        default=None, metadata={"json": "multiProcessConfig"}
    )

    @property
    def is_time_slicing(self) -> bool:
        return self.strategy == TIME_SLICING_STRATEGY

    @property
    def is_multi_process(self) -> bool:
        return self.strategy == MULTI_PROCESS_STRATEGY

    def get_time_slicing_config(self) -> Optional[TimeSlicingConfig]:
        if not self.is_time_slicing:
            raise SharingValidationError(
                f"strategy is not set to {TIME_SLICING_STRATEGY!r}"
            )
        if self.multi_process_config is not None:
            raise SharingValidationError(
                f"cannot use multiProcessConfig with the {TIME_SLICING_STRATEGY!r} strategy"
            )
        return self.time_slicing_config

    def get_multi_process_config(self) -> Optional[MultiProcessConfig]:
        if not self.is_multi_process:
            raise SharingValidationError(
                f"strategy is not set to {MULTI_PROCESS_STRATEGY!r}"
            )
        if self.time_slicing_config is not None:
            raise SharingValidationError(
                f"cannot use timeSlicingConfig with the {MULTI_PROCESS_STRATEGY!r} strategy"
            )
        return self.multi_process_config

    def validate(self) -> None:
        _validate_strategy_gate(self.strategy)
        if self.is_time_slicing:
            cfg = self.get_time_slicing_config()
            if cfg is not None:
                cfg.validate()
        if self.is_multi_process:
            cfg = self.get_multi_process_config()
            if cfg is not None:
                cfg.validate()


@dataclass
class PartitionSharing:
    """Sharing for TPU partitions: only MultiProcess is meaningful — a
    partition is already an isolated compute slice, so time-slicing it adds
    nothing.  Deliberately has no timeSlicingConfig field, so the strict
    decoder rejects it (reference MigDeviceSharing, sharing.go:73-77)."""

    strategy: str = field(default="", metadata={"json": "strategy"})
    multi_process_config: Optional[MultiProcessConfig] = field(
        default=None, metadata={"json": "multiProcessConfig"}
    )

    @property
    def is_time_slicing(self) -> bool:
        return self.strategy == TIME_SLICING_STRATEGY

    @property
    def is_multi_process(self) -> bool:
        return self.strategy == MULTI_PROCESS_STRATEGY

    def get_time_slicing_config(self) -> Optional[TimeSlicingConfig]:
        return None

    def get_multi_process_config(self) -> Optional[MultiProcessConfig]:
        if not self.is_multi_process:
            raise SharingValidationError(
                f"strategy is not set to {MULTI_PROCESS_STRATEGY!r}"
            )
        return self.multi_process_config

    def validate(self) -> None:
        _validate_strategy_gate(self.strategy)
        if self.is_multi_process and self.multi_process_config is not None:
            self.multi_process_config.validate()

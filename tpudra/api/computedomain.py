"""ComputeDomain / ComputeDomainClique CRD types and their opaque configs.

Analogs of api/nvidia.com/resource/v1beta1/{computedomain,computedomainclique,
computedomainconfig}.go.  Where the reference's ComputeDomain orchestrates an
IMEX domain (Multi-Node NVLink memory sharing), ours reserves an ICI-connected
TPU slice: the clique is the set of hosts on one ICI fabric partition, the
channel is the per-workload grant of slice visibility, and readiness means all
hosts in the slice have a running coordination daemon.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from tpudra import API_GROUP, API_VERSION

API_VERSION_STR = f"{API_GROUP}/{API_VERSION}"

COMPUTE_DOMAIN_KIND = "ComputeDomain"
COMPUTE_DOMAIN_CLIQUE_KIND = "ComputeDomainClique"
COMPUTE_DOMAIN_CHANNEL_CONFIG_KIND = "ComputeDomainChannelConfig"
COMPUTE_DOMAIN_DAEMON_CONFIG_KIND = "ComputeDomainDaemonConfig"

COMPUTE_DOMAIN_STATUS_READY = "Ready"
COMPUTE_DOMAIN_STATUS_NOT_READY = "NotReady"

CHANNEL_ALLOCATION_MODE_SINGLE = "Single"
CHANNEL_ALLOCATION_MODE_ALL = "All"

# Label placed on nodes to attract the per-CD daemon DaemonSet
# (reference: "resource.nvidia.com/computeDomain").
COMPUTE_DOMAIN_NODE_LABEL = f"{API_GROUP}/computeDomain"


class ComputeDomainValidationError(ValueError):
    pass


@dataclass
class ComputeDomainResourceClaimTemplate:
    name: str = field(default="", metadata={"json": "name"})


@dataclass
class ComputeDomainChannelSpec:
    resource_claim_template: ComputeDomainResourceClaimTemplate = field(
        default_factory=ComputeDomainResourceClaimTemplate,
        metadata={"json": "resourceClaimTemplate"},
    )
    # "Single" grants one channel; "All" grants every channel in the domain
    # (reference computedomain.go:103-108).
    allocation_mode: str = field(
        default=CHANNEL_ALLOCATION_MODE_SINGLE, metadata={"json": "allocationMode"}
    )


@dataclass
class ComputeDomainSpec:
    # Number of hosts expected in the domain.  A TPU slice is allocated as a
    # unit, so unlike IMEX domains (join-anytime), num_nodes doubles as the
    # slice host count; 0 means "derive from the slice topology".
    num_nodes: int = field(default=0, metadata={"json": "numNodes"})
    channel: Optional[ComputeDomainChannelSpec] = field(
        default=None, metadata={"json": "channel"}
    )


@dataclass
class ComputeDomainNode:
    name: str = field(default="", metadata={"json": "name"})
    ip_address: str = field(default="", metadata={"json": "ipAddress"})
    clique_id: str = field(default="", metadata={"json": "cliqueID"})
    # (clique_id, index) is unique; the index pins the node's stable DNS name
    # (reference computedomain.go:131-147).
    index: int = field(default=0, metadata={"json": "index"})
    status: str = field(
        default=COMPUTE_DOMAIN_STATUS_NOT_READY, metadata={"json": "status"}
    )


@dataclass
class ComputeDomainStatus:
    status: str = field(
        default=COMPUTE_DOMAIN_STATUS_NOT_READY, metadata={"json": "status"}
    )
    nodes: list[ComputeDomainNode] = field(default_factory=list, metadata={"json": "nodes"})


@dataclass
class DaemonInfo:
    """One daemon's membership entry in a clique
    (reference cmd/compute-domain-daemon/cdclique.go DaemonInfo)."""

    node_name: str = field(default="", metadata={"json": "nodeName"})
    ip_address: str = field(default="", metadata={"json": "ipAddress"})
    clique_id: str = field(default="", metadata={"json": "cliqueID"})
    index: int = field(default=0, metadata={"json": "index"})
    status: str = field(
        default=COMPUTE_DOMAIN_STATUS_NOT_READY, metadata={"json": "status"}
    )


@dataclass
class ComputeDomainCliqueSpec:
    compute_domain_uid: str = field(default="", metadata={"json": "computeDomainUID"})
    clique_id: str = field(default="", metadata={"json": "cliqueID"})


@dataclass
class ComputeDomainCliqueStatus:
    daemons: list[DaemonInfo] = field(default_factory=list, metadata={"json": "daemons"})


@dataclass
class ComputeDomainChannelConfig:
    """Opaque config on workload ResourceClaimTemplates
    (reference computedomainconfig.go ComputeDomainChannelConfig)."""

    api_version: str = field(default=API_VERSION_STR, metadata={"json": "apiVersion"})
    kind: str = field(
        default=COMPUTE_DOMAIN_CHANNEL_CONFIG_KIND, metadata={"json": "kind"}
    )
    domain_id: str = field(default="", metadata={"json": "domainID"})
    allocation_mode: str = field(
        default=CHANNEL_ALLOCATION_MODE_SINGLE, metadata={"json": "allocationMode"}
    )

    def normalize(self) -> None:
        if not self.allocation_mode:
            self.allocation_mode = CHANNEL_ALLOCATION_MODE_SINGLE

    def validate(self) -> None:
        if not self.domain_id:
            raise ComputeDomainValidationError("domainID must be set")
        if self.allocation_mode not in (
            CHANNEL_ALLOCATION_MODE_SINGLE,
            CHANNEL_ALLOCATION_MODE_ALL,
        ):
            raise ComputeDomainValidationError(
                f"invalid allocationMode: {self.allocation_mode!r}"
            )


@dataclass
class ComputeDomainDaemonConfig:
    """Opaque config on the daemon ResourceClaimTemplate
    (reference computedomainconfig.go ComputeDomainDaemonConfig)."""

    api_version: str = field(default=API_VERSION_STR, metadata={"json": "apiVersion"})
    kind: str = field(
        default=COMPUTE_DOMAIN_DAEMON_CONFIG_KIND, metadata={"json": "kind"}
    )
    domain_id: str = field(default="", metadata={"json": "domainID"})

    def normalize(self) -> None:
        return None

    def validate(self) -> None:
        if not self.domain_id:
            raise ComputeDomainValidationError("domainID must be set")

"""Opaque per-claim device configs for the TPU resource family.

Analogs of GpuConfig / MigDeviceConfig / VfioDeviceConfig
(reference api/nvidia.com/resource/v1beta1/{gpuconfig,migconfig,vfiodeviceconfig}.go).
These arrive as opaque parameters on ResourceClaims (matched by driver name)
and are strict-decoded, normalized, and validated by the webhook at admission
time and by the kubelet plugin at prepare time.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from tpudra import API_GROUP, API_VERSION
from tpudra import featuregates
from tpudra.api.sharing import (
    DEFAULT_TIME_SLICE,
    TIME_SLICING_STRATEGY,
    MultiProcessConfig,
    PartitionSharing,
    TimeSlicingConfig,
    TpuSharing,
)

TPU_CONFIG_KIND = "TpuConfig"
TPU_PARTITION_CONFIG_KIND = "TpuPartitionConfig"
VFIO_DEVICE_CONFIG_KIND = "VfioDeviceConfig"

API_VERSION_STR = f"{API_GROUP}/{API_VERSION}"


@dataclass
class TpuConfig:
    """Parameters for configuring a full TPU chip (reference gpuconfig.go:29-33)."""

    api_version: str = field(default=API_VERSION_STR, metadata={"json": "apiVersion"})
    kind: str = field(default=TPU_CONFIG_KIND, metadata={"json": "kind"})
    sharing: Optional[TpuSharing] = field(default=None, metadata={"json": "sharing"})

    @classmethod
    def default(cls) -> "TpuConfig":
        """Default config; carries a TimeSlicing stanza only when the gate is
        on (reference gpuconfig.go:36-53)."""
        config = cls()
        if featuregates.enabled(featuregates.TIME_SLICING_SETTINGS):
            config.sharing = TpuSharing(
                strategy=TIME_SLICING_STRATEGY,
                time_slicing_config=TimeSlicingConfig(interval=DEFAULT_TIME_SLICE),
            )
        return config

    def normalize(self) -> None:
        """Fill implied defaults (reference gpuconfig.go:56-80)."""
        if self.sharing is None:
            if not featuregates.enabled(featuregates.TIME_SLICING_SETTINGS):
                return
            self.sharing = TpuSharing(strategy=TIME_SLICING_STRATEGY)
        if featuregates.enabled(featuregates.TIME_SLICING_SETTINGS):
            if self.sharing.is_time_slicing and self.sharing.time_slicing_config is None:
                self.sharing.time_slicing_config = TimeSlicingConfig(
                    interval=DEFAULT_TIME_SLICE
                )
        if featuregates.enabled(featuregates.MULTI_PROCESS_SHARING):
            if self.sharing.is_multi_process and self.sharing.multi_process_config is None:
                self.sharing.multi_process_config = MultiProcessConfig()

    def validate(self) -> None:
        if self.sharing is not None:
            self.sharing.validate()


@dataclass
class TpuPartitionConfig:
    """Parameters for a TPU TensorCore partition (the MIG-device analog,
    reference migconfig.go)."""

    api_version: str = field(default=API_VERSION_STR, metadata={"json": "apiVersion"})
    kind: str = field(default=TPU_PARTITION_CONFIG_KIND, metadata={"json": "kind"})
    sharing: Optional[PartitionSharing] = field(default=None, metadata={"json": "sharing"})

    @classmethod
    def default(cls) -> "TpuPartitionConfig":
        return cls()

    def normalize(self) -> None:
        if self.sharing is None:
            return
        if featuregates.enabled(featuregates.MULTI_PROCESS_SHARING):
            if self.sharing.is_multi_process and self.sharing.multi_process_config is None:
                self.sharing.multi_process_config = MultiProcessConfig()

    def validate(self) -> None:
        if self.sharing is not None:
            self.sharing.validate()


@dataclass
class VfioDeviceConfig:
    """Parameters for a VFIO-passthrough TPU PCI function
    (reference vfiodeviceconfig.go)."""

    api_version: str = field(default=API_VERSION_STR, metadata={"json": "apiVersion"})
    kind: str = field(default=VFIO_DEVICE_CONFIG_KIND, metadata={"json": "kind"})

    @classmethod
    def default(cls) -> "VfioDeviceConfig":
        return cls()

    def normalize(self) -> None:
        return None

    def validate(self) -> None:
        return None

"""Dataclass <-> JSON-object codec with strict/non-strict modes.

The analog of the reference's scheme-backed decoders
(api/nvidia.com/resource/v1beta1/api.go:47-58): the *strict* decoder rejects
unknown fields (used by the admission webhook and the prepare path for configs
authored against the current API), while the *non-strict* decoder ignores them
(used when reading checkpoints written by a newer driver version).
"""

from __future__ import annotations

import dataclasses
import types
import typing
from typing import Any, Type, TypeVar

T = TypeVar("T")


class DecodeError(ValueError):
    pass


_HINTS_CACHE: dict[type, dict[str, Any]] = {}


def _type_hints(cls: type) -> dict[str, Any]:
    hints = _HINTS_CACHE.get(cls)
    if hints is None:
        hints = typing.get_type_hints(cls)
        _HINTS_CACHE[cls] = hints
    return hints


def _json_name(field: dataclasses.Field) -> str:
    return field.metadata.get("json", field.name)


def _unwrap_optional(tp):
    origin = typing.get_origin(tp)
    if origin in (typing.Union, types.UnionType):
        args = [a for a in typing.get_args(tp) if a is not type(None)]
        if len(args) == 1:
            return args[0]
    return tp


def _decode_value(tp, value: Any, strict: bool, path: str) -> Any:
    tp = _unwrap_optional(tp)
    origin = typing.get_origin(tp)
    if dataclasses.is_dataclass(tp):
        if not isinstance(value, dict):
            raise DecodeError(f"{path}: expected object, got {type(value).__name__}")
        return decode(tp, value, strict=strict, path=path)
    if origin in (list, tuple):
        if not isinstance(value, list):
            raise DecodeError(f"{path}: expected array, got {type(value).__name__}")
        args = typing.get_args(tp)
        if origin is list:
            item_tps = [args[0] if args else Any] * len(value)
        elif len(args) == 2 and args[1] is Ellipsis:  # tuple[X, ...]
            item_tps = [args[0]] * len(value)
        else:  # fixed-shape tuple[X, Y, ...]
            if len(args) != len(value):
                raise DecodeError(
                    f"{path}: expected {len(args)} elements, got {len(value)}"
                )
            item_tps = list(args)
        items = [
            _decode_value(item_tp, v, strict, f"{path}[{i}]")
            for i, (item_tp, v) in enumerate(zip(item_tps, value))
        ]
        return tuple(items) if origin is tuple else items
    if origin is dict:
        _, val_tp = typing.get_args(tp) or (str, Any)
        if not isinstance(value, dict):
            raise DecodeError(f"{path}: expected object, got {type(value).__name__}")
        return {k: _decode_value(val_tp, v, strict, f"{path}.{k}") for k, v in value.items()}
    if tp is int and isinstance(value, bool):
        raise DecodeError(f"{path}: expected int, got bool")
    if tp in (int, float, str, bool) and not isinstance(value, tp):
        # JSON numbers may arrive as int where float expected.
        if tp is float and isinstance(value, int):
            return float(value)
        raise DecodeError(
            f"{path}: expected {tp.__name__}, got {type(value).__name__}"
        )
    return value


def decode(cls: Type[T], data: dict, *, strict: bool = True, path: str = "") -> T:
    """Decode a JSON object into dataclass ``cls``.

    Field JSON names come from ``metadata={"json": ...}`` (defaulting to the
    attribute name).  Unknown keys raise DecodeError in strict mode and are
    ignored otherwise.
    """
    if not isinstance(data, dict):
        raise DecodeError(f"{path or cls.__name__}: expected object")
    fields = {_json_name(f): f for f in dataclasses.fields(cls)}
    hints = _type_hints(cls)
    kwargs = {}
    for key, value in data.items():
        f = fields.get(key)
        if f is None:
            if strict:
                raise DecodeError(f"{path or cls.__name__}: unknown field {key!r}")
            continue
        if value is None:
            continue
        kwargs[f.name] = _decode_value(hints[f.name], value, strict, f"{path}.{key}" if path else key)
    try:
        return cls(**kwargs)
    except TypeError as e:
        raise DecodeError(f"{path or cls.__name__}: {e}") from e


def encode(obj: Any) -> Any:
    """Encode a dataclass to a JSON-ready object, dropping None fields."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out = {}
        for f in dataclasses.fields(obj):
            v = getattr(obj, f.name)
            if v is None:
                continue
            out[_json_name(f)] = encode(v)
        return out
    if isinstance(obj, (list, tuple)):
        return [encode(v) for v in obj]
    if isinstance(obj, dict):
        return {k: encode(v) for k, v in obj.items()}
    return obj

"""End-to-end claim tracing: spans across controller → plugin → checkpoint → rank.

The bind-path histograms (``tpudra_bind_phase_seconds``) answer "how slow
is phase X on average"; this module answers the question aggregates
cannot: *which* phase of *which* member on *which* node was the critical
path of one particular gang bind, across the controller/kubelet process
boundary.  It is the span layer every later perf and placement PR reads
— ``tools/trace_report.py`` reconstructs per-claim/per-gang timelines
from its output and prints a critical-path breakdown.

Construction mirrors ``tpudra/lockwitness.py`` (the other opt-in
measurement apparatus): with ``TPUDRA_TRACE=1`` in the environment,
``start_span`` returns a real :class:`Span` that appends one JSONL record
to ``TPUDRA_TRACE_LOG`` (default ``tpudra-trace.jsonl`` in the working
directory) when it closes; with the variable unset — every production
default — it returns one shared no-op object, so the disabled fast path
allocates nothing and writes nothing.

Span model (W3C-trace-context-shaped, stdlib only):

- a span is (trace_id, span_id, parent_id, name, wall start, duration,
  attrs); IDs are random hex (16-byte trace, 8-byte span).
- the ACTIVE span is a contextvar: a span opened while another is active
  becomes its child, and ``contextvars.copy_context()`` carries the
  lineage across thread-pool hops (the resolver pool, the effects pool).
- ``current_traceparent()`` renders the active context as a
  ``00-<trace>-<span>-01`` string — the one value that crosses every
  boundary we own: gRPC metadata (:data:`GRPC_METADATA_KEY`) on
  NodePrepare/NodeUnprepare, a ``traceparent`` field journaled in WAL
  gang/claim records (recovery resumes the original trace), and
  :data:`TRACEPARENT_ENV` in the grant env (worker ranks emit child
  spans from the claim's CDI environment alone).
- ``start_span(name, parent=...)`` adopts a remote parent from such a
  string; spans record which process (pid) and thread emitted them, so
  one log file shared by N rank processes still yields one coherent tree.

**Flight recorder.**  Every closed span also lands in a bounded
in-process ring (``TPUDRA_TRACE_RING`` entries, default 512).  The chaos
soak dumps ``recent_spans()`` next to the seed + fault timeline on every
invariant violation — the causal middle of "what was the system doing
when the invariant broke" — and ``DebugEndpoint`` serves the same ring at
``/debug/traces``.

Span hygiene is machine-checked (tpudra-lint SPAN-HYGIENE): span names
are literal strings and ``start_span`` is always used as a context
manager, so no span can leak open and no name can hide from grep.
"""

from __future__ import annotations

import atexit
import contextvars
import json
import logging
import os
import random
import threading
import time
from collections import deque
from typing import Optional

logger = logging.getLogger(__name__)

ENV_TRACE = "TPUDRA_TRACE"
ENV_TRACE_LOG = "TPUDRA_TRACE_LOG"
ENV_TRACE_RING = "TPUDRA_TRACE_RING"
DEFAULT_LOG = "tpudra-trace.jsonl"
DEFAULT_RING = 512

#: The env var the grant (CDI spec / daemon settings) carries so worker
#: ranks join the bind's trace (workload/envspec.ClaimEnv.traceparent).
TRACEPARENT_ENV = "TPUDRA_TRACEPARENT"
#: gRPC metadata key on NodePrepareResources/NodeUnprepareResources
#: (metadata keys must be lowercase per the gRPC spec).
GRPC_METADATA_KEY = "tpudra-traceparent"


def enabled() -> bool:
    return os.environ.get(ENV_TRACE, "") not in ("", "0")


def log_path() -> str:
    return os.environ.get(ENV_TRACE_LOG, "") or os.path.join(
        os.getcwd(), DEFAULT_LOG
    )


# ------------------------------------------------------------- trace context

#: (trace_id, span_id) of the active span in this context; None at a root.
_CURRENT: contextvars.ContextVar[Optional[tuple]] = contextvars.ContextVar(
    "tpudra-trace-current", default=None
)


_tls = threading.local()


def _new_id(nbytes: int) -> str:
    """Random hex from a per-thread PRNG seeded once from os.urandom:
    span IDs need uniqueness, not cryptographic strength, and the two
    urandom syscalls per span were a measurable slice of the traced-bind
    overhead budget (the ≤5% A/B gate, bench --trace-ab)."""
    rng = getattr(_tls, "rng", None)
    if rng is None:
        rng = random.Random(os.urandom(16))
        _tls.rng = rng
    return "%0*x" % (nbytes * 2, rng.getrandbits(nbytes * 8))


def format_traceparent(trace_id: str, span_id: str) -> str:
    return f"00-{trace_id}-{span_id}-01"


def parse_traceparent(value: Optional[str]) -> Optional[tuple]:
    """(trace_id, span_id) from a ``00-<trace>-<span>-01`` string; None on
    anything malformed — a garbled traceparent degrades to a fresh trace,
    never a crash (the same contract as envspec's mesh-env parse)."""
    if not value:
        return None
    parts = value.strip().split("-")
    if len(parts) != 4:
        return None
    _, trace_id, span_id, _ = parts
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16), int(span_id, 16)
    except ValueError:
        return None
    return (trace_id, span_id)


def current_traceparent() -> str:
    """The active span as a traceparent string, or "" when tracing is
    disabled or no span is active — callers propagate it verbatim and the
    receiving side's ``parse_traceparent`` treats "" as no-parent."""
    if not enabled():
        return ""
    current = _CURRENT.get()
    if current is None:
        return ""
    return format_traceparent(current[0], current[1])


# ----------------------------------------------------------------- recording

_sink_guard = threading.Lock()
_sink = None  # opened lazily, OUTSIDE _sink_guard (no open-under-lock)
_buf_guard = threading.Lock()  # guards _pending AND _ring (one hop per span)
_pending: list = []  # records awaiting serialization (the hot-path buffer)
_ring: Optional[deque] = None
_PID = os.getpid()
#: Write cadence: a span close only APPENDS its record dict to the
#: pending buffer and the flight-recorder ring (one lock, no
#: serialization, no syscall); json.dumps + the write + the flush happen
#: at most once per window (plus at interpreter exit via atexit, and
#: explicitly via ``flush()``).  Per-span serialization and flush
#: syscalls were the bulk of the traced-bind overhead budget (the ≤5%
#: A/B gate, bench --trace-ab).  A crash can lose at most the last
#: window's UNWRITTEN records — and the flight-recorder RING (what a
#: soak violation dumps) is in-memory and loses nothing.
_FLUSH_INTERVAL_S = 0.25
_last_flush = 0.0


def _thread_name() -> str:
    name = getattr(_tls, "name", None)
    if name is None:
        name = threading.current_thread().name
        _tls.name = name
    return name


def _submit(record: dict) -> None:
    """Ring + pending buffer under ONE lock; drain when the window is
    due.  The window is claimed BEFORE the I/O so concurrent closers keep
    buffering instead of queueing behind the writer."""
    global _last_flush, _ring
    now = time.monotonic()
    batch = None
    with _buf_guard:
        if _ring is None:
            try:
                size = int(os.environ.get(ENV_TRACE_RING, "") or DEFAULT_RING)
            except ValueError:
                size = DEFAULT_RING
            _ring = deque(maxlen=max(1, size))
            atexit.register(flush)
        _ring.append(record)
        _pending.append(record)
        if now - _last_flush >= _FLUSH_INTERVAL_S:
            _last_flush = now
            batch = list(_pending)
            _pending.clear()
    if batch is not None:
        _write_batch(batch)


_write_warned = False


def _write_batch(batch: list) -> None:
    """Serialize + append one batch.  An unwritable log (missing dir,
    full disk) DROPS the batch with one warning per process instead of
    raising: a span close sits inside the production bind path when
    tracing is armed, and the observability layer must never take it
    down — the flight-recorder ring keeps the spans either way."""
    global _sink, _write_warned
    try:
        if _sink is None:
            # Open before taking the guard; a racing double-open leaves one
            # extra O_APPEND handle to close, never a torn line.
            fh = open(log_path(), "a", encoding="utf-8")
            with _sink_guard:
                if _sink is None:
                    _sink = fh
                    fh = None
            if fh is not None:
                fh.close()
        # default=repr: a non-JSON attr value (a set, a custom object)
        # degrades to its repr instead of poisoning the whole batch —
        # and whatever json still rejects is caught below, never raised
        # into the traced bind path.
        lines = "".join(
            json.dumps(record, sort_keys=True, default=repr) + "\n"
            for record in batch
        )
        with _sink_guard:
            _sink.write(lines)
            _sink.flush()
    except (OSError, TypeError, ValueError) as e:  # ValueError: closed sink
        if not _write_warned:
            _write_warned = True
            logger.warning(
                "trace log %s is unwritable (%s): dropping span batches; "
                "the in-memory flight recorder keeps recording",
                log_path(), e,
            )


def flush() -> None:
    """Drain the pending buffer to the log and flush it (readers that
    consume the log from the SAME process — tests, trace_report's
    self-check, bench's phase aggregation — call this before reading;
    cross-process readers wait for the writer's exit hook or its next
    cadence window)."""
    with _buf_guard:
        batch = list(_pending)
        _pending.clear()
    if batch:
        _write_batch(batch)
    else:
        with _sink_guard:
            if _sink is not None:
                _sink.flush()


def recent_spans(limit: Optional[int] = None) -> list:
    """The flight recorder's recent spans, NEWEST FIRST, bounded by the
    ring size (and ``limit`` when given).  Cheap: a snapshot of the ring,
    no file IO — safe to call from an invariant monitor or a debug
    endpoint while binds are in flight."""
    with _buf_guard:
        spans = list(_ring) if _ring is not None else []
    spans.reverse()
    if limit is not None:
        spans = spans[: max(0, limit)]
    return spans


def reset_for_tests() -> None:
    """Drain pending records, then drop the sink and flight-recorder
    state so a test can trace into a fresh log file (the lockwitness
    reset contract)."""
    global _sink, _ring, _last_flush, _write_warned
    flush()
    with _sink_guard:
        sink, _sink = _sink, None
    with _buf_guard:
        _ring = None
        _pending.clear()
        _last_flush = 0.0
    _write_warned = False
    if sink is not None:
        sink.close()


# --------------------------------------------------------------------- spans


class Span:
    """One traced operation; use ONLY as a context manager (SPAN-HYGIENE).

    The span becomes the context's active span between ``__enter__`` and
    ``__exit__``; on exit it appends its record to the JSONL log and the
    flight-recorder ring.  ``set_attr`` attaches small JSON-able values
    (phase timings, claim uids, node names) — the attribution payload
    ``trace_report`` prints."""

    __slots__ = (
        "trace_id", "span_id", "parent_id", "name", "attrs",
        "_t0", "_wall0", "_token",
    )

    def __init__(self, trace_id: str, span_id: str, parent_id: str, name: str):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.attrs: dict = {}
        self._t0 = 0.0
        self._wall0 = 0.0
        self._token = None

    def set_attr(self, key: str, value) -> None:
        self.attrs[key] = value

    @property
    def traceparent(self) -> str:
        return format_traceparent(self.trace_id, self.span_id)

    def __enter__(self) -> "Span":
        self._wall0 = time.time()
        self._t0 = time.perf_counter()
        self._token = _CURRENT.set((self.trace_id, self.span_id))
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        dur = time.perf_counter() - self._t0
        if self._token is not None:
            _CURRENT.reset(self._token)
            self._token = None
        record = {
            "t": "span",
            "trace": self.trace_id,
            "span": self.span_id,
            "parent": self.parent_id,
            "name": self.name,
            "start": round(self._wall0, 6),
            "dur_ms": round(dur * 1000.0, 3),
            "pid": _PID,
            "thread": _thread_name(),
        }
        if exc_type is not None:
            record["error"] = f"{exc_type.__name__}: {exc}"
        if self.attrs:
            record["attrs"] = self.attrs
        _submit(record)
        return False


class _NoopSpan:
    """The disabled fast path: ONE shared instance, no allocation per
    call, every method a no-op.  Safe to nest — it keeps no state."""

    __slots__ = ()

    def set_attr(self, key: str, value) -> None:
        pass

    @property
    def traceparent(self) -> str:
        return ""

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


NOOP_SPAN = _NoopSpan()


def start_span(name: str, parent: Optional[str] = None, attrs: Optional[dict] = None):
    """Open a span named ``name`` (a LITERAL string — SPAN-HYGIENE).

    Parentage, in priority order: an explicit ``parent`` traceparent
    string (a remote context from gRPC metadata, a WAL record, or the
    grant env), else the context's active span, else a fresh root trace.
    Returns the shared no-op object when tracing is disabled."""
    if not enabled():
        return NOOP_SPAN
    ctx = parse_traceparent(parent) if parent else None
    if ctx is None:
        current = _CURRENT.get()
        if current is not None:
            ctx = current
    if ctx is not None:
        trace_id, parent_id = ctx
    else:
        trace_id, parent_id = _new_id(16), ""
    span = Span(trace_id, _new_id(8), parent_id, name)
    if attrs:
        span.attrs.update(attrs)
    return span


def record_span(
    name: str,
    wall_start: float,
    dur_s: float,
    attrs: Optional[dict] = None,
) -> None:
    """Emit a RETROACTIVE span measured with plain counters — for paths
    hot enough that even the context-manager protocol is measurable (the
    per-mutate group-commit wait, the per-batch fsync).  The span parents
    on the context's ACTIVE span but never becomes anyone's parent (it is
    already over), so concurrent children keep their real lineage.  The
    disabled cost is one env check."""
    if not enabled():
        return
    current = _CURRENT.get()
    if current is not None:
        trace_id, parent_id = current
    else:
        trace_id, parent_id = _new_id(16), ""
    record = {
        "t": "span",
        "trace": trace_id,
        "span": _new_id(8),
        "parent": parent_id,
        "name": name,
        "start": round(wall_start, 6),
        "dur_ms": round(dur_s * 1000.0, 3),
        "pid": _PID,
        "thread": _thread_name(),
    }
    if attrs:
        record["attrs"] = attrs
    _submit(record)


# ------------------------------------------------------------------- reading


def read_log(path: str) -> list:
    """Span records from a JSONL trace log, in file order.  Malformed
    lines are skipped — a crashed process may tear its final line (the
    lockwitness read contract)."""
    spans: list = []
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("t") == "span" and rec.get("span") and rec.get("trace"):
                    spans.append(rec)
    except FileNotFoundError:
        pass
    return spans

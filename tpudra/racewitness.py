"""Runtime race witness (the racegraph dynamic side).

tpudra-racegraph's static model (tpudra/analysis/racemodel.py) claims
every cross-thread field keeps a consistent lockset or a happens-before
edge; this module is its runtime cross-check, the third witness after
the lock witness (lock-order) and the WAL witness (crash-consistency).

With ``TPUDRA_RACE_WITNESS=1`` armed, each instrumented shared-field
access logs a SAMPLE: the field id (the static model's ``Class.attr``
display name), the accessing thread, whether it wrote, the lock IDs held
right now (piggybacked on the lock witness's per-thread held stack), and
the thread's **vector clock**.  Instrumented happens-before points —
thread start handoffs, queue put/get, condition notify/wait, event set —
advance the clocks: ``note_hb_send`` merges the sender's clock into the
channel and ticks the sender's own epoch; ``note_hb_recv`` merges the
channel into the receiver.  Two samples are then provably ordered exactly
when one's clock dominates the other's epoch — and a pair of WRITE
samples from different threads with disjoint locksets and NO ordering is
a witnessed race, whatever the schedule happened to interleave.

``python -m tpudra.analysis --race-witness <log>`` merges the log into
the static model (tpudra/analysis/racemerge.py): witnessed races fail,
and so do MODEL GAPS — an access from a thread role the model says
cannot reach that field.  Coverage (modeled shared fields never
witnessed) is reported without failing.

With the variable unset (every production path), every hook is a single
falsy env check — zero allocation, zero I/O.

Conventions shared with the static model:

- Field ids are the model's display names (``WorkQueue._heap``); channel
  ids are the guarding primitive's lock-witness id where one exists
  (``workqueue.cond``) so the three witnesses tell one story.
- Thread names are the role vocabulary: production threads are spawned
  with ``name=`` matching the static role ids, and the merge classifies
  a sample's thread to the longest role-id prefix (``MainThread`` →
  ``main``; unnamed test threads match no role and cannot gap).
- Clocks are per-process: samples from different pids never race each
  other (each pid has its own memory), so every record carries the pid.
"""

from __future__ import annotations

import json
import os
import threading

from tpudra import lockwitness

ENV_WITNESS = "TPUDRA_RACE_WITNESS"
ENV_WITNESS_LOG = "TPUDRA_RACE_WITNESS_LOG"
DEFAULT_LOG = "tpudra-race-witness.jsonl"

MAIN_THREAD_NAME = "MainThread"


def enabled() -> bool:
    return os.environ.get(ENV_WITNESS, "") not in ("", "0")


def log_path() -> str:
    return os.environ.get(ENV_WITNESS_LOG, "") or os.path.join(
        os.getcwd(), DEFAULT_LOG
    )


# ----------------------------------------------------------------- recording

_guard = threading.Lock()
_sink = None  # opened lazily, OUTSIDE _guard (no open-under-lock)
#: thread name → {thread name → epoch}: the per-thread vector clocks.
#: Keyed by name, not TLS — the merge compares by thread name and tests
#: need to inspect foreign threads' clocks.
_vcs: dict = {}
#: channel id → merged clock of every send so far.
_channels: dict = {}
_written: set = set()  # emitted sample keys (first-seen dedup)
_meta_done = False


def _my_vc_locked(name: str) -> dict:
    vc = _vcs.get(name)
    if vc is None:
        vc = _vcs[name] = {name: 0}
    return vc


def _merge_into_locked(dst: dict, src: dict) -> None:
    for k, v in src.items():
        if v > dst.get(k, -1):
            dst[k] = v


# tpudra-lock: nonblocking the witness is the measurement apparatus: armed only in test harnesses, and the sink append+flush must run inside the instrumented critical section so the sampled lockset is the one actually held
def _emit(record: dict) -> None:
    global _sink
    if _sink is None:
        # Open before taking the guard; a racing double-open leaves one
        # extra O_APPEND handle to close, never a torn line.
        fh = open(log_path(), "a", encoding="utf-8")
        with _guard:
            if _sink is None:
                _sink = fh
                fh = None
        if fh is not None:
            fh.close()
    line = json.dumps(record, sort_keys=True) + "\n"
    with _guard:
        _sink.write(line)
        _sink.flush()


def _emit_meta_once() -> None:
    """One record per process saying whether the LOCK witness is armed:
    without it the held stacks are empty and every lockset in this pid's
    samples is vacuous — the merge must not call those races."""
    global _meta_done
    with _guard:
        if _meta_done:
            return
        _meta_done = True
    _emit(
        {
            "t": "meta",
            "pid": os.getpid(),
            "locks_armed": lockwitness.enabled(),
        }
    )


def note_hb_send(channel: str) -> None:
    """A happens-before source: queue put, condition notify, event set,
    or the pre-``start()`` handoff of a thread spawn.  Publishes the
    caller's clock into the channel, then ticks the caller's epoch so
    later work is NOT covered by this publication."""
    if not enabled():
        return
    name = threading.current_thread().name
    with _guard:
        vc = _my_vc_locked(name)
        chan = _channels.setdefault(channel, {})
        _merge_into_locked(chan, vc)
        vc[name] = vc.get(name, 0) + 1


def note_hb_recv(channel: str) -> None:
    """A happens-before sink: queue get, condition wait return, event
    wait, or a spawned thread's loop entry.  Everything the channel has
    seen now happens-before this thread's subsequent accesses."""
    if not enabled():
        return
    name = threading.current_thread().name
    with _guard:
        chan = _channels.get(channel)
        if chan:
            _merge_into_locked(_my_vc_locked(name), chan)


def note_access(field: str, write: bool = True) -> None:
    """Sample one access to a modeled shared field.  First-seen dedup per
    (field, thread, write, held-lockset): the witness samples states, it
    does not trace — same philosophy as the lock witness's first-seen
    edges, bounded output however hot the loop."""
    if not enabled():
        return
    _emit_meta_once()
    name = threading.current_thread().name
    locks = tuple(lockwitness.held_by_current_thread())
    key = (field, name, write, locks)
    with _guard:
        if key in _written:
            return
        _written.add(key)
        vc = dict(_my_vc_locked(name))
    _emit(
        {
            "t": "access",
            "field": field,
            "thread": name,
            "write": write,
            "locks": list(locks),
            "vc": vc,
            "pid": os.getpid(),
        }
    )


def vector_clock(thread_name: str | None = None) -> dict:
    """The (copied) clock of one thread (tests)."""
    name = thread_name or threading.current_thread().name
    with _guard:
        return dict(_vcs.get(name, {}))


def reset_for_tests() -> None:
    """Drop clocks/channels/dedup/sink state so a test can witness into a
    fresh log file."""
    global _sink, _vcs, _channels, _written, _meta_done
    with _guard:
        sink, _sink = _sink, None
        _vcs = {}
        _channels = {}
        _written = set()
        _meta_done = False
    if sink is not None:
        sink.close()


# ------------------------------------------------------------------- reading


class Sample:
    __slots__ = ("field", "thread", "write", "locks", "vc", "pid")

    def __init__(self, field, thread, write, locks, vc, pid):
        self.field = field
        self.thread = thread
        self.write = write
        self.locks = frozenset(locks)
        self.vc = vc
        self.pid = pid

    def ordered_before(self, other: "Sample") -> bool:
        """True when this sample provably happens-before ``other``: the
        other thread has (transitively) received this thread's epoch."""
        return other.vc.get(self.thread, -1) >= self.vc.get(self.thread, 0)


def read_log(path: str) -> tuple[list, dict]:
    """(samples, {pid: locks_armed}) recorded in a witness log.
    Malformed lines are skipped — a SIGKILLed witness process may tear
    its final line."""
    samples: list = []
    armed: dict = {}
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("t") == "meta" and "pid" in rec:
                    armed[rec["pid"]] = bool(rec.get("locks_armed"))
                elif rec.get("t") == "access" and rec.get("field"):
                    samples.append(
                        Sample(
                            field=rec["field"],
                            thread=rec.get("thread", "?"),
                            write=bool(rec.get("write")),
                            locks=rec.get("locks", ()),
                            vc={
                                str(k): int(v)
                                for k, v in (rec.get("vc") or {}).items()
                            },
                            pid=rec.get("pid", 0),
                        )
                    )
    except FileNotFoundError:
        pass
    return samples, armed

"""Controller shell: informers feeding one shared work queue.

The analog of compute-domain-controller/controller.go:75-105.  Events from
the ComputeDomain and ComputeDomainClique informers collapse into keyed work
items (newest wins — pkg/workqueue semantics) handled by
``ComputeDomainManager.reconcile``; clique events re-enqueue their owning CD
so status aggregation is event-driven, with a periodic full resync as the
safety net.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from dataclasses import dataclass
from typing import Optional

from tpudra.controller.cleanup import CleanupManager
from tpudra.controller.computedomain import ComputeDomainManager, RetryLater
from tpudra.controller.resourceclaimtemplate import CD_UID_LABEL
from tpudra.kube import gvr
from tpudra.kube.client import KubeAPI
from tpudra.kube.informer import Informer
from tpudra import metrics
from tpudra.workqueue import (
    PRIORITY_HIGH,
    PRIORITY_LOW,
    PRIORITY_NORMAL,
    WorkQueue,
    default_controller_rate_limiter,
)

logger = logging.getLogger(__name__)

_RECONCILE_OK = metrics.RECONCILES_TOTAL.labels("computedomain", "ok")
_RECONCILE_REQUEUE = metrics.RECONCILES_TOTAL.labels("computedomain", "requeue")
_RECONCILE_ERROR = metrics.RECONCILES_TOTAL.labels("computedomain", "error")
_RECONCILE_LATENCY = metrics.RECONCILE_LATENCY_SECONDS.labels("computedomain")


@dataclass
class ManagerConfig:
    driver_namespace: str = "tpudra-system"
    image: str = "tpudra:latest"
    max_nodes_per_domain: int = 0
    resync_period: float = 600.0
    additional_namespaces: tuple[str, ...] = ()
    # Rendered into spawned daemon pods as LOG_VERBOSITY (the reference's
    # klog -v template propagation, daemonset.go:45-56).
    log_verbosity: int = 0
    # Priority-lane + per-key-fair work-queue dispatch (workqueue.py);
    # False restores the single-heap FIFO — the measurable "before" arm of
    # bench.py --cluster-scale.
    fair_queue: bool = True
    # Seeds the rate limiter's backoff jitter so cluster-scale A/B arms
    # replay identical retry schedules; None keeps the module-global RNG.
    seed: Optional[int] = None
    # Worker threads serving the shared work queue.  The queue's per-key
    # active set already forbids two workers on one key (client-go
    # dirty/processing semantics), so >1 worker parallelizes *distinct*
    # keys — concurrent gang waves and CD reconciles stop serializing
    # behind one loop.  1 restores the single-worker behavior (the
    # measurable "before" arm).
    workers: int = 4
    # Directory for the gang-reservation checkpoint (controller/gang.py).
    # None disables the gang manager; a Controller built with a state dir
    # AND a gang_binder recovers in-flight gangs at run() start.
    gang_state_dir: Optional[str] = None


class Controller:
    def __init__(
        self,
        kube: KubeAPI,
        config: ManagerConfig | None = None,
        gang_binder=None,
    ):
        self._kube = kube
        self._config = config or ManagerConfig()
        #: Gang slice reservation (controller/gang.py): present when the
        #: config names a state dir and a binder transport was injected.
        self.gangs = None
        self._gang_cp = None
        if self._config.gang_state_dir is not None and gang_binder is not None:
            from tpudra.controller.gang import GangReservationManager
            from tpudra.plugin.checkpoint import CheckpointManager

            self._gang_cp = CheckpointManager(self._config.gang_state_dir)
            self.gangs = GangReservationManager(self._gang_cp, gang_binder)
        self.manager = ComputeDomainManager(
            kube,
            self._config.driver_namespace,
            image=self._config.image,
            max_nodes_per_domain=self._config.max_nodes_per_domain,
            additional_namespaces=self._config.additional_namespaces,
            log_verbosity=self._config.log_verbosity,
        )
        rng = (
            random.Random(self._config.seed)
            if self._config.seed is not None
            else None
        )
        self.queue = WorkQueue(
            rate_limiter=default_controller_rate_limiter(rng=rng),
            name="controller",
            fair=self._config.fair_queue,
        )
        self._cd_informer = Informer(kube, gvr.COMPUTE_DOMAINS)
        self._clique_informer = Informer(
            kube, gvr.COMPUTE_DOMAIN_CLIQUES, namespace=self._config.driver_namespace
        )
        # Per-CD daemon pods (daemonsetpods.go analog): non-fabric node
        # membership reads through this cache, and pod readiness flips
        # drive status syncs as events instead of waiting for a resync.
        self._pod_informer = Informer(
            kube,
            gvr.PODS,
            namespace=self._config.driver_namespace,
            label_selector=CD_UID_LABEL,
        )
        # Existence checks + clique aggregation + pod membership read
        # through these caches once synced (kills the per-reconcile LISTs).
        self.manager.use_informers(
            self._cd_informer, self._clique_informer, self._pod_informer
        )
        # Orphan GC sweeps every managed namespace (the driver namespace
        # plus --additional-namespaces, mnsdaemonset.go semantics).
        self._cleanups = [
            CleanupManager(kube, gvr.DAEMONSETS, ns, self.manager.cd_exists)
            for ns in self.manager.daemonsets.namespaces
        ] + [
            CleanupManager(
                kube,
                gvr.RESOURCE_CLAIM_TEMPLATES,
                self._config.driver_namespace,
                self.manager.cd_exists,
            ),
        ]

    # -- event plumbing -----------------------------------------------------

    def _enqueue_cd(
        self, namespace: str, name: str, priority: int = PRIORITY_NORMAL
    ) -> None:
        key = ("cd", namespace, name)
        self.queue.enqueue_keyed(
            key,
            lambda: self._reconcile_with_retry(namespace, name, key),
            priority=priority,
        )

    def _reconcile_with_retry(self, namespace: str, name: str, key) -> None:
        t0 = time.monotonic()
        try:
            self.manager.reconcile(namespace, name)
            _RECONCILE_OK.inc()
        except RetryLater as e:
            logger.info("requeue %s/%s: %s", namespace, name, e)
            _RECONCILE_REQUEUE.inc()
            raise  # the work queue's rate limiter schedules the retry
        except Exception:
            logger.exception("reconcile %s/%s failed", namespace, name)
            _RECONCILE_ERROR.inc()
            raise
        finally:
            # Every pass samples, requeues and errors included: the latency
            # a hot object inflicts is the p99 this histogram exists for.
            _RECONCILE_LATENCY.observe(time.monotonic() - t0)

    def _on_cd_event(self, _etype: str, obj: dict) -> None:
        meta = obj.get("metadata", {})
        # Teardown outranks routine reconciles: a terminating CD holds a
        # finalizer the user is waiting on, and behind a busy lane it
        # would queue with the crowd (workqueue priority lanes).
        priority = (
            PRIORITY_HIGH if meta.get("deletionTimestamp") else PRIORITY_NORMAL
        )
        self._enqueue_cd(meta.get("namespace", ""), meta.get("name", ""), priority)

    def _on_clique_event(self, _etype: str, obj: dict) -> None:
        cd_uid = obj.get("spec", {}).get("computeDomainUID", "")
        if not cd_uid:
            return
        # Both informer threads start concurrently; a clique event can land
        # before the CD informer's initial LIST completes, so fall back to
        # the API until it has synced (same pre-sync hazard as cd_exists).
        # The fallback is an apiserver LIST, and handlers run under the
        # informer's dispatch lock — so the lookup itself is DEFERRED to a
        # queue worker; only the cache branch resolves in-handler.
        if self._cd_informer.has_synced:
            for cd in self._cd_informer.by_index("uid", cd_uid):
                self._enqueue_cd(
                    cd["metadata"]["namespace"], cd["metadata"]["name"]
                )
                return
            return
        self.queue.enqueue_keyed(
            ("clique-lookup", cd_uid),
            lambda: self._resolve_clique_cd(cd_uid),
        )

    def _resolve_clique_cd(self, cd_uid: str) -> None:
        """Pre-sync clique→CD resolution, on a queue worker (never under
        the informer dispatch lock)."""
        if self._cd_informer.has_synced:
            cds = self._cd_informer.by_index("uid", cd_uid)
        else:
            cds = [
                cd
                for cd in self._kube.list(gvr.COMPUTE_DOMAINS).get("items", [])
                if cd["metadata"]["uid"] == cd_uid
            ]
        for cd in cds:
            self._enqueue_cd(cd["metadata"]["namespace"], cd["metadata"]["name"])
            return

    def _on_pod_event(self, _etype: str, obj: dict) -> None:
        """A per-CD daemon pod changed (created / readiness flip / gone):
        resync its ComputeDomain — for non-fabric nodes the pod IS the
        membership signal (daemonsetpods.go analog)."""
        cd_uid = obj.get("metadata", {}).get("labels", {}).get(CD_UID_LABEL, "")
        if not cd_uid:
            return
        self._on_clique_event("", {"spec": {"computeDomainUID": cd_uid}})

    # -- lifecycle ----------------------------------------------------------

    def run(self, stop: threading.Event) -> None:
        self._cd_informer.add_handler(self._on_cd_event)
        self._clique_informer.add_handler(self._on_clique_event)
        self._pod_informer.add_handler(self._on_pod_event)
        self._cd_informer.start(stop)
        self._clique_informer.start(stop)
        self._pod_informer.start(stop)
        self._cd_informer.wait_for_sync()
        self._clique_informer.wait_for_sync()
        self._pod_informer.wait_for_sync()
        for c in self._cleanups:
            c.start(stop)
        self.manager.nodes.start(stop)
        if self.gangs is not None:
            # Crash recovery FIRST: an in-flight gang from the previous
            # incarnation must converge to none-bound before new waves
            # (or reconciles acting on its members) dispatch.  A rollback
            # a node failure beats must NOT kill the controller — the
            # record is durable, so the sweep re-enqueues itself and the
            # work queue's rate limiter schedules the retries.
            self._recover_gangs()
        threading.Thread(
            target=self._resync_loop, args=(stop,), daemon=True, name="cd-resync"
        ).start()
        for i in range(max(0, self._config.workers - 1)):
            threading.Thread(
                target=self.queue.run,
                args=(stop,),
                daemon=True,
                name=f"controller-worker-{i + 1}",
            ).start()
        self.queue.run(stop)  # blocks until stop
        if self._gang_cp is not None:
            # Clean-shutdown journal compaction — the downgrade gate the
            # plugins honor in stop() (CheckpointManager.close()).
            self._gang_cp.close()

    def _recover_gangs(self) -> None:
        """First recovery attempt, inline at startup.  A failure hands
        the sweep to the work queue, whose per-item rate limiter owns the
        retry backoff (the queued closure RAISES on failure on purpose)."""
        try:
            self._recover_gangs_once()
        except Exception as e:  # noqa: BLE001 — recovery must not kill run()
            logger.warning("gang recovery incomplete, retrying via queue: %s", e)
            self.queue.enqueue_keyed(
                ("gang-recover",), self._recover_gangs_once
            )

    def _recover_gangs_once(self) -> None:
        rolled = self.gangs.recover()  # raises → the queue retries with backoff
        if rolled:
            logger.warning(
                "recovered %d interrupted gang(s): %s", len(rolled), rolled
            )

    def start(self, stop: threading.Event) -> threading.Thread:
        t = threading.Thread(target=self.run, args=(stop,), daemon=True, name="controller")
        t.start()
        return t

    def _resync_loop(self, stop: threading.Event) -> None:
        while not stop.is_set():
            stop.wait(self._config.resync_period)
            if stop.is_set():
                return
            self._resync_once()

    def _resync_once(self) -> None:
        for cd in self._cd_informer.list():
            meta = cd.get("metadata", {})
            # The periodic backstop must never preempt event-driven work —
            # a 1000-CD sweep rides the LOW lane — EXCEPT for terminating
            # CDs, which keep the HIGH urgency their deletion event earned
            # (the workqueue also refuses to demote a pending HIGH entry,
            # but a sweep that lands after the teardown pass failed and
            # drained must not requeue it as LOW).
            priority = (
                PRIORITY_HIGH if meta.get("deletionTimestamp") else PRIORITY_LOW
            )
            self._enqueue_cd(
                meta.get("namespace", ""), meta.get("name", ""), priority
            )

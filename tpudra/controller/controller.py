"""Controller shell: informers feeding one shared work queue.

The analog of compute-domain-controller/controller.go:75-105.  Events from
the ComputeDomain and ComputeDomainClique informers collapse into keyed work
items (newest wins — pkg/workqueue semantics) handled by
``ComputeDomainManager.reconcile``; clique events re-enqueue their owning CD
so status aggregation is event-driven, with a periodic full resync as the
safety net.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from dataclasses import dataclass
from typing import Optional

from tpudra import CLAIM_UNHEALTHY_CONDITION, lockwitness
from tpudra.controller.cleanup import CleanupManager
from tpudra.controller.computedomain import ComputeDomainManager, RetryLater
from tpudra.controller.resourceclaimtemplate import CD_UID_LABEL
from tpudra.kube import gvr
from tpudra.kube.client import KubeAPI
from tpudra.kube.informer import Informer
from tpudra import metrics
from tpudra.workqueue import (
    PRIORITY_HIGH,
    PRIORITY_LOW,
    PRIORITY_NORMAL,
    WorkQueue,
    default_controller_rate_limiter,
)

logger = logging.getLogger(__name__)


def _has_unhealthy_condition(claim: dict) -> bool:
    """Cache-filter for the claim-health informer: keep only claims whose
    status carries the plugin's DeviceUnhealthy escalation (entering the
    filtered cache dispatches ADDED — the remediation trigger)."""
    return any(
        c.get("type") == CLAIM_UNHEALTHY_CONDITION and c.get("status") == "True"
        for c in claim.get("status", {}).get("conditions", [])
    )


_RECONCILE_OK = metrics.RECONCILES_TOTAL.labels("computedomain", "ok")
_RECONCILE_REQUEUE = metrics.RECONCILES_TOTAL.labels("computedomain", "requeue")
_RECONCILE_ERROR = metrics.RECONCILES_TOTAL.labels("computedomain", "error")
_RECONCILE_LATENCY = metrics.RECONCILE_LATENCY_SECONDS.labels("computedomain")


@dataclass
class ManagerConfig:
    driver_namespace: str = "tpudra-system"
    image: str = "tpudra:latest"
    max_nodes_per_domain: int = 0
    resync_period: float = 600.0
    additional_namespaces: tuple[str, ...] = ()
    # Rendered into spawned daemon pods as LOG_VERBOSITY (the reference's
    # klog -v template propagation, daemonset.go:45-56).
    log_verbosity: int = 0
    # Priority-lane + per-key-fair work-queue dispatch (workqueue.py);
    # False restores the single-heap FIFO — the measurable "before" arm of
    # bench.py --cluster-scale.
    fair_queue: bool = True
    # Seeds the rate limiter's backoff jitter so cluster-scale A/B arms
    # replay identical retry schedules; None keeps the module-global RNG.
    seed: Optional[int] = None
    # Worker threads serving the shared work queue.  The queue's per-key
    # active set already forbids two workers on one key (client-go
    # dirty/processing semantics), so >1 worker parallelizes *distinct*
    # keys — concurrent gang waves and CD reconciles stop serializing
    # behind one loop.  1 restores the single-worker behavior (the
    # measurable "before" arm).
    workers: int = 4
    # Directory for the gang-reservation checkpoint (controller/gang.py).
    # None disables the gang manager; a Controller built with a state dir
    # AND a gang_binder recovers in-flight gangs at run() start.
    gang_state_dir: Optional[str] = None
    # -- leader election (controller/lease.py, docs/ha.md) ------------------
    # False (default) keeps the single-replica behavior every existing
    # harness relies on: the controller leads unconditionally, unfenced.
    # True gates run() on holding the coordination.k8s.io Lease: informer
    # handlers drop events and the work queue pauses while not leading,
    # and every leadership term hands the gang manager a fresh fencing
    # token (GangReservationManager.set_term).
    leader_elect: bool = False
    #: Candidate identity (pod name in production); "" = random.
    leader_identity: str = ""
    lease_name: str = "tpudra-controller"
    lease_duration_s: float = 15.0
    lease_renew_interval_s: float = 5.0


class Controller:
    def __init__(
        self,
        kube: KubeAPI,
        config: ManagerConfig | None = None,
        gang_binder=None,
        gang_claim_resolver=None,
        gang_remediation_planner=None,
    ):
        self._kube = kube
        self._config = config or ManagerConfig()
        #: Gang slice reservation (controller/gang.py): present when the
        #: config names a state dir and a binder transport was injected.
        #: ``gang_claim_resolver`` lets crash recovery RESUME an
        #: interrupted remediation (refetch target claims);
        #: ``gang_remediation_planner`` turns a degraded GangStatus into
        #: ``(replacements, claims)`` for the remediation sweep — without
        #: one, degraded gangs stay degraded until an operator acts.
        self.gangs = None
        self._gang_cp = None
        self._gang_planner = gang_remediation_planner
        if self._config.gang_state_dir is not None and gang_binder is not None:
            from tpudra.controller.gang import GangReservationManager
            from tpudra.plugin.checkpoint import CheckpointManager

            self._gang_cp = CheckpointManager(self._config.gang_state_dir)
            self.gangs = GangReservationManager(
                self._gang_cp, gang_binder, claim_resolver=gang_claim_resolver
            )
        self.manager = ComputeDomainManager(
            kube,
            self._config.driver_namespace,
            image=self._config.image,
            max_nodes_per_domain=self._config.max_nodes_per_domain,
            additional_namespaces=self._config.additional_namespaces,
            log_verbosity=self._config.log_verbosity,
        )
        rng = (
            random.Random(self._config.seed)
            if self._config.seed is not None
            else None
        )
        self.queue = WorkQueue(
            rate_limiter=default_controller_rate_limiter(rng=rng),
            name="controller",
            fair=self._config.fair_queue,
        )
        # -- leadership (docs/ha.md).  Without election the controller
        # leads unconditionally from construction (the event pre-set), so
        # every existing single-replica harness behaves identically.  With
        # it, the event flips with the lease and everything event-driven
        # checks it: handlers drop events while follower (the acquire-time
        # resync rebuilds state), the queue pauses, and each term re-fences
        # the gang manager.
        self._leader_evt = threading.Event()
        self._leader_term = 0
        #: Serializes dispatch-gate transitions between the elector thread
        #: (pause on loss) and the leader-startup thread (resume after
        #: recovery): without it a loss racing the startup's resume could
        #: leave the queue running while follower.
        self._leader_gate_lock = lockwitness.make_lock(
            "controller.leader_gate_lock"
        )
        self.elector = None
        if self._config.leader_elect:
            from tpudra.controller.lease import LeaseElector

            self.elector = LeaseElector(
                kube,
                identity=self._config.leader_identity,
                name=self._config.lease_name,
                namespace=self._config.driver_namespace,
                lease_duration_s=self._config.lease_duration_s,
                renew_interval_s=self._config.lease_renew_interval_s,
                on_started_leading=self._on_started_leading,
                on_stopped_leading=self._on_stopped_leading,
                rng=rng,
            )
            self.queue.pause()  # nothing dispatches until the lease is won
        else:
            self._leader_evt.set()
        self._cd_informer = Informer(kube, gvr.COMPUTE_DOMAINS)
        self._clique_informer = Informer(
            kube, gvr.COMPUTE_DOMAIN_CLIQUES, namespace=self._config.driver_namespace
        )
        # Claim-health watch: the node plugins escalate device faults onto
        # bound claims as a DeviceUnhealthy status condition
        # (plugin/driver.py); this informer is how the controller SEES
        # those conditions without node access and feeds them into gang
        # remediation.  Gated on the gang manager (its only consumer), and
        # cache-filtered to claims CARRYING the condition — O(sick
        # claims), not O(cluster claims), so the gang feature does not buy
        # a full claim cache.
        self._claim_health_informer = None
        if self.gangs is not None:
            self._claim_health_informer = Informer(
                kube,
                gvr.RESOURCE_CLAIMS,
                cache_filter=_has_unhealthy_condition,
            )
            self._claim_health_informer.add_handler(self._on_claim_health_event)
        # Per-CD daemon pods (daemonsetpods.go analog): non-fabric node
        # membership reads through this cache, and pod readiness flips
        # drive status syncs as events instead of waiting for a resync.
        self._pod_informer = Informer(
            kube,
            gvr.PODS,
            namespace=self._config.driver_namespace,
            label_selector=CD_UID_LABEL,
        )
        # Existence checks + clique aggregation + pod membership read
        # through these caches once synced (kills the per-reconcile LISTs).
        self.manager.use_informers(
            self._cd_informer, self._clique_informer, self._pod_informer
        )
        # Orphan GC sweeps every managed namespace (the driver namespace
        # plus --additional-namespaces, mnsdaemonset.go semantics).
        self._cleanups = [
            CleanupManager(
                kube, gvr.DAEMONSETS, ns, self.manager.cd_exists,
                enabled=self._leader_evt.is_set,
            )
            for ns in self.manager.daemonsets.namespaces
        ] + [
            CleanupManager(
                kube,
                gvr.RESOURCE_CLAIM_TEMPLATES,
                self._config.driver_namespace,
                self.manager.cd_exists,
                enabled=self._leader_evt.is_set,
            ),
        ]

    # -- leadership ---------------------------------------------------------

    @property
    def is_leader(self) -> bool:
        return self._leader_evt.is_set()

    @property
    def leader_term(self) -> int:
        """The fencing token of the current leadership term (0 while
        follower or before the first acquisition)."""
        return self._leader_term

    def _on_started_leading(self, term: int) -> None:
        """Elector callback (elector thread): adopt the term and re-fence
        the gang manager, then hand the startup sequence to its own
        thread — gang recovery must run BEFORE dispatch resumes (the same
        recovery-first ordering the non-elected run() enforces inline: an
        in-flight gang from the dead leader converges under OUR term
        before any reconcile can touch its members), and running it here
        would stall lease renewal for the length of a recovery."""
        logger.warning(
            "controller %s: leading at term %d",
            self.elector.identity if self.elector else "-", term,
        )
        if self.gangs is not None:
            try:
                high, _ = self.gangs.fence_state()
            except Exception:  # noqa: BLE001 — unreadable store: fence decides
                high = 0
            if term <= high and self.elector is not None:
                # A deleted-and-recreated Lease restarted the numbering at
                # or below the WAL's journaled high-water: push the lease
                # counter past history (CAS as holder) so fencing resumes
                # ABOVE it instead of refusing this leader forever.
                try:
                    term = self.elector.advance_term(high + 1)
                except Exception:  # noqa: BLE001 — blip: lead anyway; the
                    # WAL fence refuses gang commits loudly (StaleLeader,
                    # counted) until the next acquisition repairs the term.
                    logger.exception(
                        "fencing-term repair failed (lease term %d <= "
                        "journaled %d); gang mutates will be refused until "
                        "the next term", term, high,
                    )
            try:
                self.gangs.set_term(term)
            except ValueError:
                # A same-process regression (this manager already held a
                # higher term): keep the higher fence — the WAL refusals
                # protect state while the lease numbering catches up.
                logger.exception("gang fencing term not adopted")
        self._leader_term = term
        self._leader_evt.set()
        threading.Thread(
            target=self._leader_startup,
            args=(term,),
            daemon=True,
            name="leader-startup",
        ).start()

    def _leader_startup(self, term: int) -> None:
        """Recovery-first leadership startup, off the elector thread:
        recover gangs (inline first attempt; a failure enqueues the
        queued retry exactly like the non-elected path), then open the
        dispatch gate and resync.  The gate transition re-checks the term
        under ``_leader_gate_lock`` so a loss that raced the recovery
        cannot be un-paused by a stale startup thread."""
        if self.gangs is not None:
            try:
                # Claim the store BEFORE recovery: the fence must outrank
                # the dead leader's term even when it left nothing to
                # converge — otherwise a revived stale incarnation's fresh
                # gang reserve would find its own old high-water mark
                # at-or-below and be accepted (split-brain).
                self.gangs.claim_store()
            except Exception:  # noqa: BLE001 — outranked or store down: the
                # per-mutate fence still refuses stale commits loudly.
                logger.exception("leadership store claim failed (term %d)", term)
            self._recover_gangs()
        with self._leader_gate_lock:
            if self._leader_term != term or not self._leader_evt.is_set():
                return  # lost (or re-won under a newer term) mid-startup
            self.queue.resume()
        # Full resync: every event that arrived while follower was
        # dropped at the handlers; the level-triggered caches rebuild.
        # Claim-health escalations dropped while follower (including the
        # initial LIST) get the same treatment HERE, once per
        # acquisition — the condition is a one-shot write with no
        # wire-level retry.  Not in the periodic resync: a lingering
        # condition would cost a WAL re-mark + remediation enqueue every
        # cycle (the degraded-gang sweep already owns that backstop).
        if self._claim_health_informer is not None:
            for claim in self._claim_health_informer.list():
                self._on_claim_health_event("ADDED", claim)
        self._resync_once()

    def _on_stopped_leading(self) -> None:
        """Elector callback: stop ACTING immediately — gates closed, queue
        paused.  Queued work survives (coalesced, newest-wins) so a
        re-acquire resumes warm; the WAL fence covers the window where a
        stale in-flight item outlives this callback."""
        logger.warning(
            "controller %s: lost leadership; suspending dispatch",
            self.elector.identity if self.elector else "-",
        )
        with self._leader_gate_lock:
            self._leader_evt.clear()
            self.queue.pause()

    # -- event plumbing -----------------------------------------------------

    def _enqueue_cd(
        self, namespace: str, name: str, priority: int = PRIORITY_NORMAL
    ) -> None:
        key = ("cd", namespace, name)
        self.queue.enqueue_keyed(
            key,
            lambda: self._reconcile_with_retry(namespace, name, key),
            priority=priority,
        )

    def _reconcile_with_retry(self, namespace: str, name: str, key) -> None:
        t0 = time.monotonic()
        try:
            self.manager.reconcile(namespace, name)
            _RECONCILE_OK.inc()
        except RetryLater as e:
            logger.info("requeue %s/%s: %s", namespace, name, e)
            _RECONCILE_REQUEUE.inc()
            raise  # the work queue's rate limiter schedules the retry
        except Exception:
            logger.exception("reconcile %s/%s failed", namespace, name)
            _RECONCILE_ERROR.inc()
            raise
        finally:
            # Every pass samples, requeues and errors included: the latency
            # a hot object inflicts is the p99 this histogram exists for.
            _RECONCILE_LATENCY.observe(time.monotonic() - t0)

    def _on_cd_event(self, _etype: str, obj: dict) -> None:
        if not self._leader_evt.is_set():
            return  # follower: the acquire-time resync rebuilds this
        meta = obj.get("metadata", {})
        # Teardown outranks routine reconciles: a terminating CD holds a
        # finalizer the user is waiting on, and behind a busy lane it
        # would queue with the crowd (workqueue priority lanes).
        priority = (
            PRIORITY_HIGH if meta.get("deletionTimestamp") else PRIORITY_NORMAL
        )
        self._enqueue_cd(meta.get("namespace", ""), meta.get("name", ""), priority)

    def _on_clique_event(self, _etype: str, obj: dict) -> None:
        if not self._leader_evt.is_set():
            return  # follower: the acquire-time resync rebuilds this
        cd_uid = obj.get("spec", {}).get("computeDomainUID", "")
        if not cd_uid:
            return
        # Both informer threads start concurrently; a clique event can land
        # before the CD informer's initial LIST completes, so fall back to
        # the API until it has synced (same pre-sync hazard as cd_exists).
        # The fallback is an apiserver LIST, and handlers run under the
        # informer's dispatch lock — so the lookup itself is DEFERRED to a
        # queue worker; only the cache branch resolves in-handler.
        if self._cd_informer.has_synced:
            for cd in self._cd_informer.by_index("uid", cd_uid):
                self._enqueue_cd(
                    cd["metadata"]["namespace"], cd["metadata"]["name"]
                )
                return
            return
        self.queue.enqueue_keyed(
            ("clique-lookup", cd_uid),
            lambda: self._resolve_clique_cd(cd_uid),
        )

    def _resolve_clique_cd(self, cd_uid: str) -> None:
        """Pre-sync clique→CD resolution, on a queue worker (never under
        the informer dispatch lock)."""
        if self._cd_informer.has_synced:
            cds = self._cd_informer.by_index("uid", cd_uid)
        else:
            cds = [
                cd
                for cd in self._kube.list(gvr.COMPUTE_DOMAINS).get("items", [])
                if cd["metadata"]["uid"] == cd_uid
            ]
        for cd in cds:
            self._enqueue_cd(cd["metadata"]["namespace"], cd["metadata"]["name"])
            return

    def _on_pod_event(self, _etype: str, obj: dict) -> None:
        """A per-CD daemon pod changed (created / readiness flip / gone):
        resync its ComputeDomain — for non-fabric nodes the pod IS the
        membership signal (daemonsetpods.go analog)."""
        cd_uid = obj.get("metadata", {}).get("labels", {}).get(CD_UID_LABEL, "")
        if not cd_uid:
            return
        self._on_clique_event("", {"spec": {"computeDomainUID": cd_uid}})

    # -- lifecycle ----------------------------------------------------------

    def run(self, stop: threading.Event) -> None:
        self._cd_informer.add_handler(self._on_cd_event)
        self._clique_informer.add_handler(self._on_clique_event)
        self._pod_informer.add_handler(self._on_pod_event)
        self._cd_informer.start(stop)
        self._clique_informer.start(stop)
        self._pod_informer.start(stop)
        if self._claim_health_informer is not None:
            self._claim_health_informer.start(stop)
        self._cd_informer.wait_for_sync()
        self._clique_informer.wait_for_sync()
        self._pod_informer.wait_for_sync()
        for c in self._cleanups:
            c.start(stop)
        self.manager.nodes.start(stop)
        if self.elector is not None:
            # Elected mode: recovery belongs to the TERM, not to startup —
            # _on_started_leading re-fences the gang manager and enqueues
            # it; dispatch stays paused until the lease is won.
            self.elector.start(stop)
        elif self.gangs is not None:
            # Crash recovery FIRST: an in-flight gang from the previous
            # incarnation must converge to none-bound before new waves
            # (or reconciles acting on its members) dispatch.  A rollback
            # a node failure beats must NOT kill the controller — the
            # record is durable, so the sweep re-enqueues itself and the
            # work queue's rate limiter schedules the retries.
            self._recover_gangs()
        threading.Thread(
            target=self._resync_loop, args=(stop,), daemon=True, name="cd-resync"
        ).start()
        for i in range(max(0, self._config.workers - 1)):
            threading.Thread(
                target=self.queue.run,
                args=(stop,),
                daemon=True,
                name=f"controller-worker-{i + 1}",
            ).start()
        self.queue.run(stop)  # blocks until stop
        if self._gang_cp is not None:
            # Clean-shutdown journal compaction — the downgrade gate the
            # plugins honor in stop() (CheckpointManager.close()).
            self._gang_cp.close()

    def _recover_gangs(self) -> None:
        """First recovery attempt, inline at startup.  A failure hands
        the sweep to the work queue, whose per-item rate limiter owns the
        retry backoff (the queued closure RAISES on failure on purpose)."""
        try:
            self._recover_gangs_once()
        except Exception as e:  # noqa: BLE001 — recovery must not kill run()
            logger.warning("gang recovery incomplete, retrying via queue: %s", e)
            self.queue.enqueue_keyed(
                ("gang-recover",), self._recover_gangs_once
            )

    def _recover_gangs_once(self) -> None:
        rolled = self.gangs.recover()  # raises → the queue retries with backoff
        if rolled:
            logger.warning(
                "recovered %d interrupted gang(s): %s", len(rolled), rolled
            )
        # Degraded gangs survive recovery all-bound (gang.py's recover
        # contract) — hand them straight to the remediation sweep instead
        # of waiting for the first resync tick.
        self._sweep_degraded_gangs()

    # ------------------------------------------------------- gang health

    def _on_claim_health_event(self, etype: str, obj: dict) -> None:
        """Claim-health informer handler: a claim entered the filtered
        cache (it carries the DeviceUnhealthy condition) — resolve it off
        the dispatch lock via a queued pass.  DELETED (condition cleared /
        claim gone) needs nothing: remediation reads gang state, not the
        condition."""
        if etype == "DELETED":
            return
        if not self._leader_evt.is_set():
            return  # follower: the acquire-time resync sweep re-marks
        uid = obj.get("metadata", {}).get("uid", "")
        reason = next(
            (
                c.get("reason", "")
                for c in obj.get("status", {}).get("conditions", [])
                if c.get("type") == CLAIM_UNHEALTHY_CONDITION
            ),
            "",
        )
        if uid:
            self.queue.enqueue_keyed(
                ("claim-health", uid),
                lambda: self._claim_health_pass(uid, reason),
            )

    def _claim_health_pass(self, claim_uid: str, reason: str) -> None:
        """The queued claim-health closure: raises when the owning gang
        exists but cannot be marked yet (mid-reserve — the record is not
        PREPARE_COMPLETED), so the work queue's rate limiter retries the
        escalation until the reserve settles instead of dropping the
        one-shot signal on the floor."""
        if not self.on_claim_health_condition(claim_uid, reason=reason):
            raise RetryLater(
                f"claim {claim_uid}: owning gang still in-flight; "
                "re-marking after it settles"
            )

    def on_claim_health_condition(
        self, claim_uid: str, reason: str = ""
    ) -> bool:
        """Entry point for the bound-claim health escalation (the
        claim-status condition plugin/driver.py writes): map the claim to
        its gang, journal the degraded mark, and enqueue remediation.  A
        claim belonging to no gang is a node-local concern — nothing to
        do here.  Returns False ONLY when the owning gang exists but is
        not yet markable (in-flight reserve) — the caller should retry."""
        if self.gangs is None:
            return True
        for gang_id, status in self.gangs.gangs().items():
            if any(m.claim_uid == claim_uid for m in status.members):
                if self.gangs.mark_degraded(gang_id, [claim_uid], reason=reason):
                    self.request_gang_remediation(gang_id)
                    return True
                # Terminal-ish phases settle on their own (rollback /
                # remediating already end released or re-bound); only a
                # reserving-phase gang needs the escalation re-delivered
                # once it completes to bound.
                return status.phase != "reserving"
        return True

    def request_gang_remediation(self, gang_id: str) -> None:
        """Queue one remediation pass for a degraded gang (keyed: bursts
        of member escalations collapse to one pass; the rate limiter owns
        retry backoff when the pass raises)."""
        self.queue.enqueue_keyed(
            ("gang-remediate", gang_id),
            lambda: self._remediate_gang(gang_id),
        )

    def _sweep_degraded_gangs(self) -> None:
        """Enqueue remediation for every degraded OR stranded-remediating
        gang — the resync-time backstop for escalations that raced a
        controller restart and for remediations a transient failure left
        in the remediating phase."""
        if self.gangs is None:
            return
        from tpudra.controller.gang import PHASE_DEGRADED, PHASE_REMEDIATING

        for gang_id, status in self.gangs.gangs().items():
            if status.phase in (PHASE_DEGRADED, PHASE_REMEDIATING):
                self.request_gang_remediation(gang_id)

    def _remediate_gang(self, gang_id: str) -> None:
        """One remediation pass on a queue worker.  The planner turns the
        degraded status into (replacements, claims) — selection filtered
        on PUBLISHED slice health (gang.select_healthy_spares) is the
        planner's job, since only the caller knows the candidate node
        population.  No planner / no viable plan keeps the gang degraded
        (journaled; the next sweep retries); a plan runs through
        gangs.remediate, which converges to all-bound-on-healthy or
        cleanly-released.  A gang a FAILED pass left in the remediating
        phase resumes through recover() — without this arm the queued
        retry the comments promise would be a no-op."""
        from tpudra.controller.gang import (
            PHASE_DEGRADED,
            PHASE_REMEDIATING,
            GangOpInProgress,
        )

        status = self.gangs.gangs().get(gang_id)
        if status is None:
            return  # released / recovered since enqueue
        if status.phase == PHASE_REMEDIATING:
            # A prior pass (or crash) left the journaled plan mid-flight:
            # recover() resumes it (re-bind targets via the claim
            # resolver, else clean release) — raising on failure so the
            # rate limiter owns the retry.
            self.gangs.recover()
            return
        if status.phase != PHASE_DEGRADED:
            return  # remediated or healthy again
        if self._gang_planner is None:
            logger.warning(
                "gang %s is degraded but no remediation planner is "
                "configured; leaving it journaled", gang_id,
            )
            return
        plan = self._gang_planner(status)
        if plan is None:
            logger.warning(
                "gang %s: no viable remediation plan (no healthy spares?); "
                "will retry on the next sweep", gang_id,
            )
            return
        replacements, claims = plan
        try:
            self.gangs.remediate(gang_id, replacements, claims)
        except GangOpInProgress:
            ...  # a live reserve/release owns the gang; the sweep re-checks
        # GangBindError/GangRollbackIncomplete propagate: the work queue's
        # rate limiter schedules the retry, and the record (kept, or
        # cleanly dropped by remediate itself) already tells the truth.

    def start(self, stop: threading.Event) -> threading.Thread:
        t = threading.Thread(target=self.run, args=(stop,), daemon=True, name="controller")
        t.start()
        return t

    def _resync_loop(self, stop: threading.Event) -> None:
        while not stop.is_set():
            stop.wait(self._config.resync_period)
            if stop.is_set():
                return
            self._resync_once()

    def _resync_once(self) -> None:
        if not self._leader_evt.is_set():
            return  # a follower's sweep would only queue work the pause holds
        self._sweep_degraded_gangs()
        for cd in self._cd_informer.list():
            meta = cd.get("metadata", {})
            # The periodic backstop must never preempt event-driven work —
            # a 1000-CD sweep rides the LOW lane — EXCEPT for terminating
            # CDs, which keep the HIGH urgency their deletion event earned
            # (the workqueue also refuses to demote a pending HIGH entry,
            # but a sweep that lands after the teardown pass failed and
            # drained must not requeue it as LOW).
            priority = (
                PRIORITY_HIGH if meta.get("deletionTimestamp") else PRIORITY_LOW
            )
            self._enqueue_cd(
                meta.get("namespace", ""), meta.get("name", ""), priority
            )

"""Node label hygiene for ComputeDomains.

The analog of compute-domain-controller/node.go:42-168: the CD kubelet plugin
labels nodes ``resource.tpu.google.com/computeDomain=<uid>`` to attract the
daemon DaemonSet; the controller removes those labels when a CD is deleted and
periodically sweeps labels whose CD no longer exists (a node can miss the
deletion if its plugin was down).
"""

from __future__ import annotations

import logging
import threading
from typing import Callable

from tpudra.api.computedomain import COMPUTE_DOMAIN_NODE_LABEL
from tpudra.kube import gvr
from tpudra.kube.client import KubeAPI
from tpudra.kube.errors import ApiError

logger = logging.getLogger(__name__)


class NodeManager:
    def __init__(self, kube: KubeAPI, cd_exists: Callable[[str], bool], period: float = 600.0):
        self._kube = kube
        self._cd_exists = cd_exists
        self._period = period

    def remove_labels_for(self, cd_uid: str) -> int:
        """Strip the CD label from every node carrying it
        (RemoveComputeDomainLabels, node.go:114)."""
        removed = 0
        nodes = self._kube.list(
            gvr.NODES, label_selector=f"{COMPUTE_DOMAIN_NODE_LABEL}={cd_uid}"
        ).get("items", [])
        for node in nodes:
            name = node["metadata"]["name"]
            try:
                self._kube.patch(
                    gvr.NODES, name, {"metadata": {"labels": {COMPUTE_DOMAIN_NODE_LABEL: None}}}
                )
                removed += 1
            except ApiError as e:
                logger.warning("removing CD label from node %s: %s", name, e)
        return removed

    def sweep_stale_labels(self) -> int:
        """Remove labels referencing CDs that no longer exist."""
        removed = 0
        nodes = self._kube.list(
            gvr.NODES, label_selector=COMPUTE_DOMAIN_NODE_LABEL
        ).get("items", [])
        for node in nodes:
            uid = node["metadata"].get("labels", {}).get(COMPUTE_DOMAIN_NODE_LABEL, "")
            if uid and not self._cd_exists(uid):
                name = node["metadata"]["name"]
                logger.info("sweeping stale CD label %s from node %s", uid, name)
                try:
                    self._kube.patch(
                        gvr.NODES,
                        name,
                        {"metadata": {"labels": {COMPUTE_DOMAIN_NODE_LABEL: None}}},
                    )
                    removed += 1
                except ApiError as e:
                    logger.warning("sweeping node %s: %s", name, e)
        return removed

    def start(self, stop: threading.Event) -> None:
        def run() -> None:
            while not stop.is_set():
                try:
                    self.sweep_stale_labels()
                except Exception:  # noqa: BLE001 — periodic GC must survive
                    logger.exception("node label sweep failed")
                stop.wait(self._period)

        threading.Thread(target=run, daemon=True, name="node-label-sweep").start()

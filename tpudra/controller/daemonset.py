"""Per-ComputeDomain DaemonSet management.

The analog of compute-domain-controller/daemonset.go:58-189: renders
``templates/compute-domain-daemon.tmpl.yaml`` per CD (name
``computedomain-daemon-<uid>``, nodeSelector on the CD label so it lands only
on nodes the CD kubelet plugin has labeled — the "CD follows workload" pull
model), creating the daemon RCT first so the pod's resource claim resolves.
"""

from __future__ import annotations

import hashlib
import json
import logging
import string

import yaml

from tpudra import featuregates, lockwitness
from tpudra.controller.resourceclaimtemplate import CD_UID_LABEL
from tpudra.kube import gvr
from tpudra.kube.client import KubeAPI
from tpudra.kube.errors import NotFound

logger = logging.getLogger(__name__)

from tpudra.paths import template_path

DEFAULT_TEMPLATE_PATH = template_path("compute-domain-daemon.tmpl.yaml")


# Annotation recording the hash of the spec this controller last rendered.
# Drift detection compares rendered-vs-rendered (never rendered-vs-live), so
# it is immune to server-side defaulting AND catches fields a newer template
# *removed* — both directions a live-spec comparison gets wrong.
TEMPLATE_HASH_ANNOTATION = "resource.tpu.google.com/template-hash"


def _spec_hash(spec: dict) -> str:
    return hashlib.sha256(
        json.dumps(spec, sort_keys=True, separators=(",", ":")).encode()
    ).hexdigest()[:32]


class DaemonSetManager:
    def __init__(
        self,
        kube: KubeAPI,
        driver_namespace: str,
        image: str = "tpudra:latest",
        template_path: str = DEFAULT_TEMPLATE_PATH,
        log_verbosity: int = 0,
    ):
        self._kube = kube
        self._ns = driver_namespace
        self._image = image
        self._log_verbosity = log_verbosity
        # The template never changes within a controller process — read it
        # once, not on every reconcile.
        with open(template_path) as f:
            self._template = string.Template(f.read())

    def name(self, cd_uid: str) -> str:
        return f"computedomain-daemon-{cd_uid}"

    def render(self, cd: dict, daemon_rct_name: str) -> dict:
        gates = ",".join(
            f"{k}={'true' if v else 'false'}" for k, v in sorted(featuregates.to_map().items())
        )
        rendered = self._template.substitute(
            name=self.name(cd["metadata"]["uid"]),
            namespace=self._ns,
            cd_uid=cd["metadata"]["uid"],
            cd_namespace=cd["metadata"].get("namespace", ""),
            cd_name=cd["metadata"].get("name", ""),
            image=self._image,
            daemon_rct_name=daemon_rct_name,
            feature_gates=gates,
            log_verbosity=str(self._log_verbosity),
        )
        obj = yaml.safe_load(rendered)
        obj.setdefault("metadata", {}).setdefault("annotations", {})[
            TEMPLATE_HASH_ANNOTATION
        ] = _spec_hash(obj["spec"])
        return obj

    def ensure(self, cd: dict, daemon_rct_name: str) -> dict:
        name = self.name(cd["metadata"]["uid"])
        obj = self.render(cd, daemon_rct_name)
        try:
            live = self._kube.get(gvr.DAEMONSETS, name, self._ns)
        except NotFound:
            logger.info("creating DaemonSet %s/%s", self._ns, name)
            return self._kube.create(gvr.DAEMONSETS, obj, self._ns)
        # Reconcile drift: image/feature-gate/template changes after a
        # controller upgrade must propagate to already-deployed per-CD
        # daemons (reference updates existing DaemonSets, daemonset.go:346).
        live_hash = (
            live.get("metadata", {}).get("annotations", {}).get(TEMPLATE_HASH_ANNOTATION)
        )
        want_hash = obj["metadata"]["annotations"][TEMPLATE_HASH_ANNOTATION]
        if live_hash != want_hash:
            logger.info("updating drifted DaemonSet %s/%s", self._ns, name)
            live["spec"] = obj["spec"]
            meta = live.setdefault("metadata", {})
            meta.setdefault("labels", {}).update(obj["metadata"].get("labels", {}))
            meta.setdefault("annotations", {})[TEMPLATE_HASH_ANNOTATION] = want_hash
            return self._kube.update(gvr.DAEMONSETS, live, self._ns)
        return live

    def get(self, cd_uid: str) -> dict | None:
        try:
            return self._kube.get(gvr.DAEMONSETS, self.name(cd_uid), self._ns)
        except NotFound:
            return None

    def remove(self, cd_uid: str) -> None:
        try:
            self._kube.delete(gvr.DAEMONSETS, self.name(cd_uid), self._ns)
        except NotFound:
            pass

    def assert_removed(self, cd_uid: str) -> bool:
        try:
            self._kube.get(gvr.DAEMONSETS, self.name(cd_uid), self._ns)
            return False
        except NotFound:
            return True

    def list_all(self) -> list[dict]:
        return self._kube.list(
            gvr.DAEMONSETS, self._ns, label_selector=CD_UID_LABEL
        ).get("items", [])


class MultiNamespaceDaemonSetManager:
    """DaemonSet management across the driver namespace plus any
    ``--additional-namespaces`` (mnsdaemonset.go analog).

    Why this exists: after a driver upgrade that moved the deployment
    namespace, per-CD DaemonSets may still live in the old namespace.  New
    DaemonSets always go to the driver namespace, but an existing one found
    in any managed namespace is reconciled where it is, and teardown/GC
    sweep every managed namespace.
    """

    def __init__(
        self,
        kube: KubeAPI,
        driver_namespace: str,
        additional_namespaces: tuple[str, ...] = (),
        image: str = "tpudra:latest",
        template_path: str = DEFAULT_TEMPLATE_PATH,
        log_verbosity: int = 0,
    ):
        self._driver_ns = driver_namespace
        # Dedup while keeping the driver namespace first (create target).
        namespaces = dict.fromkeys((driver_namespace, *additional_namespaces))
        self._managers = {
            ns: DaemonSetManager(
                kube,
                ns,
                image=image,
                template_path=template_path,
                log_verbosity=log_verbosity,
            )
            for ns in namespaces
        }
        # Home-namespace cache: a legacy DaemonSet only *pre*-exists (this
        # controller always creates in the driver namespace), so once a CD's
        # home is resolved it never changes until teardown — the additional-
        # namespace probes are paid once per CD, not once per reconcile.
        # Reconciles arrive from the informer dispatch, the resync loop,
        # AND the leader-startup replay; the cache writes need one guard
        # (tpudra-racegraph pins the lockset).  The namespace probes stay
        # outside it — they hit the apiserver.
        self._home_ns: dict[str, str] = {}
        self._home_lock = lockwitness.make_lock("daemonset.home_ns")

    @property
    def namespaces(self) -> list[str]:
        return list(self._managers)

    def ensure(self, cd: dict, daemon_rct_name: str) -> dict:
        uid = cd["metadata"]["uid"]
        home = self._home_ns.get(uid)
        if home is None:
            home = self._driver_ns
            for ns, mgr in self._managers.items():
                if ns != self._driver_ns and mgr.get(uid) is not None:
                    home = ns
                    break
            with self._home_lock:
                home = self._home_ns.setdefault(uid, home)
        return self._managers[home].ensure(cd, daemon_rct_name)

    def remove(self, cd_uid: str) -> None:
        with self._home_lock:
            self._home_ns.pop(cd_uid, None)
        for mgr in self._managers.values():
            mgr.remove(cd_uid)

    def assert_removed(self, cd_uid: str) -> bool:
        return all(mgr.assert_removed(cd_uid) for mgr in self._managers.values())

    def list_all(self) -> list[dict]:
        return [ds for mgr in self._managers.values() for ds in mgr.list_all()]

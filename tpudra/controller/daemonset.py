"""Per-ComputeDomain DaemonSet management.

The analog of compute-domain-controller/daemonset.go:58-189: renders
``templates/compute-domain-daemon.tmpl.yaml`` per CD (name
``computedomain-daemon-<uid>``, nodeSelector on the CD label so it lands only
on nodes the CD kubelet plugin has labeled — the "CD follows workload" pull
model), creating the daemon RCT first so the pod's resource claim resolves.
"""

from __future__ import annotations

import logging
import os
import string

import yaml

from tpudra import featuregates
from tpudra.controller.resourceclaimtemplate import CD_UID_LABEL
from tpudra.kube import gvr
from tpudra.kube.client import KubeAPI
from tpudra.kube.errors import NotFound

logger = logging.getLogger(__name__)

DEFAULT_TEMPLATE_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "templates",
    "compute-domain-daemon.tmpl.yaml",
)


class DaemonSetManager:
    def __init__(
        self,
        kube: KubeAPI,
        driver_namespace: str,
        image: str = "tpudra:latest",
        template_path: str = DEFAULT_TEMPLATE_PATH,
        log_verbosity: int = 0,
    ):
        self._kube = kube
        self._ns = driver_namespace
        self._image = image
        self._template_path = template_path
        self._log_verbosity = log_verbosity

    def name(self, cd_uid: str) -> str:
        return f"computedomain-daemon-{cd_uid}"

    def render(self, cd: dict, daemon_rct_name: str) -> dict:
        with open(self._template_path) as f:
            template = string.Template(f.read())
        gates = ",".join(
            f"{k}={'true' if v else 'false'}" for k, v in sorted(featuregates.to_map().items())
        )
        rendered = template.substitute(
            name=self.name(cd["metadata"]["uid"]),
            namespace=self._ns,
            cd_uid=cd["metadata"]["uid"],
            image=self._image,
            daemon_rct_name=daemon_rct_name,
            feature_gates=gates,
            log_verbosity=str(self._log_verbosity),
        )
        return yaml.safe_load(rendered)

    def ensure(self, cd: dict, daemon_rct_name: str) -> dict:
        name = self.name(cd["metadata"]["uid"])
        try:
            return self._kube.get(gvr.DAEMONSETS, name, self._ns)
        except NotFound:
            pass
        obj = self.render(cd, daemon_rct_name)
        logger.info("creating DaemonSet %s/%s", self._ns, name)
        return self._kube.create(gvr.DAEMONSETS, obj, self._ns)

    def remove(self, cd_uid: str) -> None:
        try:
            self._kube.delete(gvr.DAEMONSETS, self.name(cd_uid), self._ns)
        except NotFound:
            pass

    def assert_removed(self, cd_uid: str) -> bool:
        try:
            self._kube.get(gvr.DAEMONSETS, self.name(cd_uid), self._ns)
            return False
        except NotFound:
            return True

    def list_all(self) -> list[dict]:
        return self._kube.list(
            gvr.DAEMONSETS, self._ns, label_selector=CD_UID_LABEL
        ).get("items", [])

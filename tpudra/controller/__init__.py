"""ComputeDomain cluster controller.

The analog of cmd/compute-domain-controller/: watches ComputeDomain CRs and
stamps out, per CD, a node daemon DaemonSet plus two ResourceClaimTemplates
(daemon + workload channel), maintains the CD's aggregated status from
ComputeDomainClique CRs, and runs the deletion/finalizer choreography.
"""

from tpudra.controller.controller import Controller, ManagerConfig

__all__ = ["Controller", "ManagerConfig"]

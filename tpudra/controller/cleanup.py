"""Generic orphan garbage collection.

The analog of compute-domain-controller/cleanup.go:46-147
(``CleanupManager[T]``): every CD-owned object the controller stamps out in
the *driver's* namespace (DaemonSets, daemon RCTs) cannot carry a
cross-namespace owner reference, so a periodic pass deletes any such object
whose labeled ComputeDomain no longer exists — covering controller crashes
mid-teardown.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable

from tpudra.controller.resourceclaimtemplate import CD_UID_LABEL
from tpudra.kube.client import KubeAPI
from tpudra.kube.errors import NotFound
from tpudra.kube.gvr import GVR

logger = logging.getLogger(__name__)


class CleanupManager:
    def __init__(
        self,
        kube: KubeAPI,
        target: GVR,
        namespace: str | None,
        cd_exists: Callable[[str], bool],
        period: float = 600.0,
        enabled: Callable[[], bool] | None = None,
    ):
        self._kube = kube
        self._target = target
        self._ns = namespace
        self._cd_exists = cd_exists
        self._period = period
        #: Leadership gate (docs/ha.md): a follower replica must not
        #: sweep — its informer view can lag the leader's (a processed
        #: DELETED without the re-creation), and an ungated delete pass
        #: over that split view would GC objects the leader just stamped.
        #: None = always enabled (the single-replica default).
        self._enabled = enabled

    def cleanup_once(self) -> int:
        if self._enabled is not None and not self._enabled():
            return 0
        removed = 0
        items = self._kube.list(
            self._target, self._ns, label_selector=CD_UID_LABEL
        ).get("items", [])
        for obj in items:
            uid = obj["metadata"].get("labels", {}).get(CD_UID_LABEL, "")
            if uid and not self._cd_exists(uid):
                name = obj["metadata"]["name"]
                ns = obj["metadata"].get("namespace")
                logger.info(
                    "GC: deleting orphaned %s %s/%s (CD %s gone)",
                    self._target.kind, ns or "", name, uid,
                )
                try:
                    self._kube.delete(self._target, name, ns)
                    removed += 1
                except NotFound:
                    pass
        return removed

    def start(self, stop: threading.Event) -> None:
        def run() -> None:
            while not stop.is_set():
                try:
                    self.cleanup_once()
                except Exception:  # noqa: BLE001 — periodic GC must survive
                    logger.exception("%s cleanup pass failed", self._target.kind)
                stop.wait(self._period)

        threading.Thread(
            target=run, daemon=True, name=f"cleanup-{self._target.resource}"
        ).start()

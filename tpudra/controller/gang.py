"""Gang ("all-or-nothing") slice reservation for ComputeDomains.

PAPER.md's north star — ``kubectl apply`` of a ComputeDomain claim → a JAX
all-reduce across a v5p slice — needs a property no node-local path can
give: a claim for an N-node slice must bind **all N node-local claims or
none**.  A partial gang is worse than a failed one: the bound members hold
channels, node labels, and CDI specs that gate other domains off their
nodes, while the workload can never start (libtpu mesh formation needs
every worker).  This manager is the reference driver's IMEX-domain
formation discipline applied to TPU pod-slice reservation:

- **reserve(gang, members)** journals the gang's *intent* (the full member
  list) through the checkpoint WAL before any member binds, binds members
  one at a time through the injected :class:`GangBinder`, journals each
  member's bind, and flips the gang record to ``PrepareCompleted`` only
  when every member is bound.  Any member failure rolls the bound prefix
  back through the binder's unbind (the existing unprepare path — the
  same idempotent teardown kubelet retries ride) and drops the record.

- **crash consistency**: the WAL record is written *before* the first
  bind, so a controller crash mid-gang (the ``mid-gang-reserve`` /
  ``mid-gang-rollback`` crash points, swept by tests/test_gang.py) leaves
  a durable ``PrepareStarted`` gang whose member list is the rollback
  plan.  :meth:`recover` — run at controller start — unbinds **every**
  member of every non-completed gang (unbind of a never-bound member is a
  no-op by the unprepare path's contract) and drops the record: recovery
  converges to all-bound or none-bound, never partial.

- the gang record rides the same :class:`CheckpointManager` WAL as claim
  records (``gang/<id>`` uids — the prefix keeps them out of any
  claim-shaped scan), so group commit, torn-tail repair, and the
  ``post-journal-append`` / ``mid-compaction`` crash points all apply to
  gang state for free.

The binder is injected because the transport differs by context: the
multi-host harness and the chaos soak bind through in-process CD plugin
drivers (``tpudra/sim/multihost.DriverGangBinder`` — the harness plays
kubelet), a production controller would drive per-node claims through the
apiserver and watch their status.  The manager owns only the all-or-
nothing state machine and its durability.
"""

from __future__ import annotations

import contextlib
import json
import logging
import time
from dataclasses import dataclass, field
from typing import Callable, Optional, Protocol

from tpudra import TPU_DRIVER_NAME, lockwitness, metrics, trace, walwitness
from tpudra.kube import gvr
from tpudra.plugin.checkpoint import (
    PREPARE_COMPLETED,
    PREPARE_STARTED,
    Checkpoint,
    CheckpointManager,
    PreparedClaim,
    PreparedDeviceGroup,
)
from tpudra.plugin.device_state import _crashpoint
from tpudra.plugin.resourceslice import (
    SLICE_STORAGE_DEGRADED_ANNOTATION,
    SLICE_UNHEALTHY_ANNOTATION,
)

logger = logging.getLogger(__name__)

#: Checkpoint-uid namespace for gang records.  "/" cannot appear in a k8s
#: object uid, so no claim record can ever collide with a gang record.
GANG_UID_PREFIX = "gang/"

#: The leadership-fence record (docs/ha.md): ONE per gang checkpoint,
#: outside the gang/ namespace so no gang scan ever sees it.  Its
#: config_state carries the high-water fencing ``term`` (the largest
#: leadership term that ever committed here) and the strictly-increasing
#: ``termHistory`` of first-commit-per-term (the chaos soak's
#: single-writer invariant audits it).
GANG_META_UID = "gangmeta/term"

#: config_state phases of a PrepareStarted gang record.  A completed gang
#: (status PREPARE_COMPLETED) with no degraded mark is phase-less: all
#: members bound.
PHASE_RESERVING = "reserving"
PHASE_ROLLBACK = "rollback"
#: A bound gang with a health condition on ≥1 member: all members are
#: still bound (it is NOT partial) but one sits on sick silicon — the
#: remediation loop's input state.
PHASE_DEGRADED = "degraded"
#: Remediation in flight: the target member plan is journaled, the old
#: members are being torn down / the targets re-bound.  Recovery resumes
#: from this record alone.
PHASE_REMEDIATING = "remediating"

_GANGS_BOUND = metrics.GANG_RESERVATIONS_TOTAL.labels("bound")
_GANGS_ROLLED_BACK = metrics.GANG_RESERVATIONS_TOTAL.labels("rolled-back")
_GANGS_RECOVERED = metrics.GANG_RESERVATIONS_TOTAL.labels("recovered")
_GANGS_RELEASED = metrics.GANG_RESERVATIONS_TOTAL.labels("released")
_REMEDIATED = metrics.GANG_REMEDIATIONS_TOTAL.labels("remediated")
_REMEDIATION_RELEASED = metrics.GANG_REMEDIATIONS_TOTAL.labels("released")
_REMEDIATION_FAILED = metrics.GANG_REMEDIATIONS_TOTAL.labels("failed")
_STALE_REJECTED = metrics.GANG_STALE_LEADER_REJECTIONS


class GangBindError(Exception):
    """A member bind failed; the gang was rolled back to none-bound."""


class StaleLeader(Exception):
    """A gang mutate was REFUSED at the checkpoint layer because the
    journaled leadership term outranks this manager's fencing token: a
    newer leader has committed here, so this writer is a superseded
    incarnation (crash-loop overlap, a paused-then-revived process, a
    lease layer gone wrong).  The refusal — not the lease — is what makes
    split-brain unable to corrupt gang state (docs/ha.md).  Counted in
    ``tpudra_gang_stale_leader_rejections_total``; the correct response is
    to stop acting, not to retry."""

    def __init__(self, message: str, journaled_term: int = 0, my_term: int = 0):
        super().__init__(message)
        self.journaled_term = journaled_term
        self.my_term = my_term


class GangOpInProgress(Exception):
    """Another thread is mid-operation on this gang (reserve, release,
    remediate, or a recovery pass) — the caller retries after it settles.
    The guard is a non-blocking per-gang claim, never a lock held across
    binder I/O (docs/lock-order.md: ``gang.ops_lock`` guards only the
    active-set mutation)."""


class GangRollbackIncomplete(Exception):
    """One or more member unbinds failed; the gang record is KEPT in the
    rollback phase so :meth:`GangReservationManager.recover` retries the
    teardown — the record outliving the failure is what makes the
    all-or-nothing contract crash-proof rather than best-effort."""


class _BindStageFailed(Exception):
    """Internal: one stage of a member-bind loop failed (the caller maps
    it to its own rollback semantics)."""

    def __init__(self, stage: str, cause: Exception):
        super().__init__(f"{stage}: {cause}")
        self.stage = stage
        self.cause = cause


def _dedup_members(*lists: list["GangMember"]) -> list["GangMember"]:
    """Concatenate member lists keeping the first of each (node, claim_uid)
    — the teardown set every coordinated rollback visits (old members AND
    any target binds a crash may have left), shared by remediation,
    recovery, and force-release so the paths cannot diverge."""
    seen: set = set()
    out: list[GangMember] = []
    for members in lists:
        for m in members:
            key = (m.node, m.claim_uid)
            if key not in seen:
                seen.add(key)
                out.append(m)
    return out


@dataclass(frozen=True)
class GangMember:
    """One node-local claim of the gang."""

    node: str
    claim_uid: str
    namespace: str = "default"

    def to_state(self) -> dict:
        return {
            "node": self.node,
            "claimUID": self.claim_uid,
            "namespace": self.namespace,
        }

    @classmethod
    def from_state(cls, d: dict) -> "GangMember":
        return cls(
            node=d.get("node", ""),
            claim_uid=d.get("claimUID", ""),
            namespace=d.get("namespace", "default"),
        )


@dataclass
class GangStatus:
    """One gang record, as read back from the checkpoint."""

    gang_id: str
    phase: str  # "bound" | "reserving" | "rollback" | "degraded" | "remediating"
    members: list[GangMember]
    bound: list[str]  # claim uids journaled as bound
    #: Member claim uids marked unhealthy (degraded / remediating phases).
    unhealthy: list[str] = field(default_factory=list)
    #: The journaled remediation plan: the member list the gang is moving
    #: to (remediating phase only).
    target: list[GangMember] = field(default_factory=list)
    #: Traceparent journaled at reserve time (tpudra/trace.py): recovery
    #: and remediation of this gang emit spans into the ORIGINAL trace,
    #: so a crash does not orphan the causal chain.  "" when the gang was
    #: reserved untraced.
    traceparent: str = ""


class GangBinder(Protocol):
    """Transport for one member's bind/unbind.

    ``bind`` raises on failure (any exception — the manager maps it to a
    rollback); ``unbind`` must be idempotent for members that never bound
    (the unprepare path's existing contract: dropping an unknown claim is
    a no-op), because recovery unbinds the *whole* intent list."""

    def bind(self, member: GangMember, claim: dict) -> None: ...

    def unbind(self, member: GangMember) -> None: ...


class GangReservationManager:
    """All-or-nothing reservation of N node-local claims, journaled.

    One instance per controller; ``checkpoints`` is a dedicated
    CheckpointManager over the controller's state dir (gang records must
    not share a file with any plugin's claim records — different process,
    different lock, different GC).

    ``claim_resolver`` (optional) refetches a member's allocated
    ResourceClaim object by :class:`GangMember` — what lets
    :meth:`recover` RESUME an interrupted remediation (re-bind the
    journaled target members) instead of only releasing it; without one,
    recovery of a remediating gang converges to cleanly-released."""

    def __init__(
        self,
        checkpoints: CheckpointManager,
        binder: GangBinder,
        claim_resolver: Optional[Callable[[GangMember], Optional[dict]]] = None,
        term: Optional[int] = None,
    ):
        self._cp = checkpoints
        self._binder = binder
        self._claim_resolver = claim_resolver
        #: Leadership fencing token (docs/ha.md): when set, EVERY mutate
        #: is fenced through the ``gangmeta/term`` record — a journaled
        #: term above ours refuses the commit with :class:`StaleLeader`.
        #: None = legacy unfenced operation (single-process harnesses,
        #: benches, every pre-election caller).
        self._term = term
        # Per-gang operation guard: reserve/release/remediate/recover of
        # ONE gang never interleave (two threads unbinding the same
        # member set would double-free), while distinct gangs proceed
        # concurrently.  The lock guards only the active-set mutation —
        # binder I/O always runs outside it.
        self._ops_lock = lockwitness.make_lock("gang.ops_lock")
        self._active_ops: set[str] = set()

    @contextlib.contextmanager
    def _gang_op(self, gang_id: str, what: str):
        with self._ops_lock:
            if gang_id in self._active_ops:
                raise GangOpInProgress(
                    f"gang {gang_id!r}: another operation is in flight "
                    f"(wanted {what})"
                )
            self._active_ops.add(gang_id)
        try:
            yield
        finally:
            with self._ops_lock:
                self._active_ops.discard(gang_id)

    # -------------------------------------------------------------- fencing

    def set_term(self, term: int) -> None:
        """Adopt a (new) leadership term — called by the election layer's
        ``on_started_leading``.  Terms only move forward: adopting a term
        below the current one is a caller bug, refused loudly."""
        if self._term is not None and term < self._term:
            raise ValueError(
                f"fencing term may not regress ({self._term} -> {term})"
            )
        self._term = term

    @property
    def term(self) -> Optional[int]:
        return self._term

    # tpudra-wal: recovers=gangmeta the fence record is recovered by supersession, not sweeping — the new leader's first fenced commit here rewrites gangmeta/term, and every stale term is refused from then on
    def claim_store(self) -> None:
        """Advance the journaled fence to OUR term with a no-op fenced
        commit — the new leader's first write, made at adoption time.
        Recovery alone is not enough: when the dead leader left nothing
        to converge, no fenced commit ever outranks its term, and a
        revived stale incarnation reserving a FRESH gang would find its
        own old high-water mark at-or-below and be accepted.  Idempotent;
        unfenced managers have no store to claim.  Raises
        :class:`StaleLeader` when a newer term already committed."""
        if self._term is None:
            return
        self._mutate(lambda cp: None, touched=[])

    def _mutate(self, fn, touched: list) -> None:
        """Every gang mutate funnels through here.  Unfenced (term None):
        a plain checkpoint mutate.  Fenced: the commit first consults the
        journaled high-water term — a stored term above ours means a newer
        leader has committed, and THIS commit is refused inside the WAL
        transaction (typed :class:`StaleLeader`, counted) so not even a
        torn lease layer lets a stale incarnation corrupt gang state.  A
        term at-or-below ours is advanced to ours in the SAME commit, with
        the first commit of each term appended to the strictly-increasing
        ``termHistory`` the soak's single-writer invariant audits."""
        term = self._term
        if term is None:
            self._cp.mutate(fn, touched=touched)
            return

        def fenced(cp: Checkpoint) -> None:
            meta = cp.prepared_claims.get(GANG_META_UID)
            state = meta.groups[0].config_state if meta and meta.groups else {}
            stored = int(state.get("term", "0") or 0)
            if stored > term:
                raise StaleLeader(
                    f"gang mutate refused: journaled leadership term "
                    f"{stored} outranks this writer's term {term}",
                    journaled_term=stored,
                    my_term=term,
                )
            if meta is None or stored != term:
                history = list(json.loads(state.get("termHistory", "[]")))
                history.append(term)
                cp.prepared_claims[GANG_META_UID] = PreparedClaim(
                    uid=GANG_META_UID,
                    namespace="",
                    name="term",
                    status=PREPARE_COMPLETED,
                    groups=[
                        PreparedDeviceGroup(
                            devices=[],
                            config_state={
                                "term": str(term),
                                "termHistory": json.dumps(history),
                            },
                        )
                    ],
                )
            fn(cp)

        try:
            self._cp.mutate(fenced, touched=[*touched, GANG_META_UID])
        except StaleLeader:
            _STALE_REJECTED.inc()
            raise

    def fence_state(self) -> tuple[int, list[int]]:
        """(journaled high-water term, first-commit term history) — what
        the chaos soak's single-writer invariant audits: the history must
        be strictly increasing, or a superseded term committed after its
        successor.  (0, []) before any fenced commit."""
        rec = self._cp.read_view().prepared_claims.get(GANG_META_UID)
        state = rec.groups[0].config_state if rec and rec.groups else {}
        return (
            int(state.get("term", "0") or 0),
            list(json.loads(state.get("termHistory", "[]"))),
        )

    # -------------------------------------------------------------- helpers

    @staticmethod
    def _guid(gang_id: str) -> str:
        return GANG_UID_PREFIX + gang_id

    @staticmethod
    def _record(
        gang_id: str,
        members: list[GangMember],
        phase: str,
        bound: list[str],
        extra: Optional[dict] = None,
        traceparent: str = "",
        term: Optional[int] = None,
    ) -> PreparedClaim:
        return PreparedClaim(
            uid=GANG_UID_PREFIX + gang_id,
            namespace="",
            name=gang_id,
            status=PREPARE_STARTED,
            groups=[
                PreparedDeviceGroup(
                    devices=[],
                    # configState values are strings by the checkpoint's
                    # v2 schema (api/serde typing): the member and bound
                    # lists ride as JSON documents inside it.
                    config_state={
                        "phase": phase,
                        "members": json.dumps([m.to_state() for m in members]),
                        "bound": json.dumps(list(bound)),
                        **({"traceparent": traceparent} if traceparent else {}),
                        # The reserving term, for audit: the FENCE is the
                        # gangmeta record (every commit re-checks it); this
                        # field answers "which leadership created this gang".
                        **({"term": str(term)} if term is not None else {}),
                        **(extra or {}),
                    },
                )
            ],
        )

    @staticmethod
    def _parse(rec: PreparedClaim) -> GangStatus:
        state = rec.groups[0].config_state if rec.groups else {}
        if rec.status == PREPARE_COMPLETED:
            # A completed record is all-bound; a degraded mark rides on
            # top of it (the gang is sick, not partial).
            phase = (
                PHASE_DEGRADED
                if state.get("phase") == PHASE_DEGRADED
                else "bound"
            )
        else:
            phase = state.get("phase", PHASE_RESERVING)
        return GangStatus(
            gang_id=rec.uid[len(GANG_UID_PREFIX):],
            phase=phase,
            members=[
                GangMember.from_state(m)
                for m in json.loads(state.get("members", "[]"))
            ],
            bound=list(json.loads(state.get("bound", "[]"))),
            unhealthy=list(json.loads(state.get("unhealthy", "[]"))),
            target=[
                GangMember.from_state(m)
                for m in json.loads(state.get("target", "[]"))
            ],
            traceparent=state.get("traceparent", ""),
        )

    def gangs(self) -> dict[str, GangStatus]:
        """Every gang record in the checkpoint (complete and in-flight)."""
        cp = self._cp.read_view()
        return {
            rec.uid[len(GANG_UID_PREFIX):]: self._parse(rec)
            for uid, rec in cp.prepared_claims.items()
            if uid.startswith(GANG_UID_PREFIX)
        }

    # -------------------------------------------------------------- reserve

    def reserve(
        self,
        gang_id: str,
        members: list[GangMember],
        claims: dict[str, dict],
        on_member_bound: Optional[Callable[[GangMember], None]] = None,
    ) -> GangStatus:
        """Bind every member or none.  ``claims`` maps member claim uid →
        the allocated ResourceClaim object handed to the binder.  Raises
        :class:`GangBindError` after a clean rollback,
        :class:`GangRollbackIncomplete` when the rollback itself needs the
        recovery path to finish.  Idempotent: re-reserving a completed
        gang with the same member set returns its status without
        re-binding (the controller-restart / requeue case)."""
        if not members:
            raise ValueError("a gang needs at least one member")
        guid = self._guid(gang_id)
        t0 = time.monotonic()
        cached: list[GangStatus] = []
        # Captured on the CALLING thread, inside the gang.reserve span
        # (assigned below, read by the closure at call time): the mutator
        # runs on whichever thread leads the group commit, whose context
        # is not this reserve's (tpudra/trace.py lineage rules — the same
        # hoist device_state.begin_prepare and cdplugin state.prepare do).
        reserve_traceparent = ""

        def start(cp: Checkpoint) -> None:
            existing = cp.prepared_claims.get(guid)
            if existing is not None:
                status = self._parse(existing)
                same_members = {m.claim_uid for m in status.members} == {
                    m.claim_uid for m in members
                }
                if status.phase in ("bound", PHASE_DEGRADED) and same_members:
                    # A degraded gang is still all-bound: idempotent
                    # re-reserve returns it (remediation owns the move).
                    cached.append(status)
                    return
                if same_members:
                    raise GangBindError(
                        f"gang {gang_id!r} exists in phase {status.phase!r}: "
                        "its teardown has not converged yet — recover() "
                        "retries it; re-reserve after"
                    )
                raise GangBindError(
                    f"gang {gang_id!r} already exists in phase "
                    f"{status.phase!r} with a different member set"
                )
            cp.prepared_claims[guid] = self._record(
                gang_id, members, PHASE_RESERVING, [],
                traceparent=reserve_traceparent,
                term=self._term,
            )

        with trace.start_span(
            "gang.reserve", attrs={"gang": gang_id, "members": len(members)}
        ), self._gang_op(gang_id, "reserve"):
            reserve_traceparent = trace.current_traceparent()
            self._mutate(start, [guid])
            if cached:
                return cached[0]
            try:
                self._bind_members(
                    gang_id, members, claims, on_member_bound,
                    crash_point="mid-gang-reserve",
                )
            except _BindStageFailed as e:
                logger.warning(
                    "gang %s: %s failed: %s — rolling back",
                    gang_id, e.stage, e.cause,
                )
                self._rollback(gang_id, members)
                _GANGS_ROLLED_BACK.inc()
                raise GangBindError(
                    f"gang {gang_id!r}: {e.stage} failed ({e.cause}); "
                    "all bound member(s) rolled back"
                ) from e.cause
            self._complete(guid)
        _GANGS_BOUND.inc()
        metrics.GANG_BIND_SECONDS.labels(str(len(members))).observe(
            time.monotonic() - t0
        )
        logger.info(
            "gang %s: all %d members bound in %.3fs",
            gang_id, len(members), time.monotonic() - t0,
        )
        return GangStatus(
            gang_id=gang_id,
            phase="bound",
            members=list(members),
            bound=[m.claim_uid for m in members],
        )

    def _bind_members(
        self,
        gang_id: str,
        members: list[GangMember],
        claims: dict[str, dict],
        on_member_bound: Optional[Callable[[GangMember], None]],
        crash_point: str,
    ) -> None:
        """Bind every member in order, journaling each bind.  Raises
        :class:`_BindStageFailed` on any failure — the CALLER owns the
        rollback (reserve unwinds to none-bound; remediate unwinds the
        re-bind targets and releases)."""
        guid = self._guid(gang_id)
        stage = "member bind"
        try:
            for member in members:
                with trace.start_span(
                    "gang.bind-member",
                    attrs={"claim": member.claim_uid, "node": member.node},
                ):
                    stage = f"bind of claim {member.claim_uid!r}"
                    walwitness.note_effect("gang:bind")
                    self._binder.bind(member, claims[member.claim_uid])

                    def journal_bound(cp: Checkpoint, uid=member.claim_uid) -> None:
                        rec = cp.prepared_claims.get(guid)
                        if rec is None or not rec.groups:
                            return  # dropped by a concurrent release; rollback wins
                        state = rec.groups[0].config_state
                        done = json.loads(state.get("bound", "[]"))
                        if uid not in done:
                            done.append(uid)
                            state["bound"] = json.dumps(done)

                    stage = f"bind journal for claim {member.claim_uid!r}"
                    self._mutate(journal_bound, [guid])
                    # Fires (when armed) after the FIRST member is durably
                    # bound and before the rest: the canonical partial-gang
                    # crash for the sweep, as long as the gang has ≥2 members.
                    _crashpoint(crash_point)
                    if on_member_bound is not None:
                        stage = f"post-bind callback for {member.claim_uid!r}"
                        on_member_bound(member)
        except _BindStageFailed:
            raise
        except Exception as e:
            raise _BindStageFailed(stage, e) from e

    def _complete(self, guid: str) -> None:
        def complete(cp: Checkpoint) -> None:
            rec = cp.prepared_claims.get(guid)
            if rec is None or not rec.groups:
                return
            rec.status = PREPARE_COMPLETED
            state = rec.groups[0].config_state
            # Clear any remediation residue: a completed gang is healthy
            # until the next escalation says otherwise.
            state.pop("phase", None)
            state.pop("unhealthy", None)
            state.pop("target", None)
            state.pop("degradedReason", None)

        self._mutate(complete, [guid])

    # ------------------------------------------------------------- rollback

    def _rollback(
        self,
        gang_id: str,
        members: list[GangMember],
        phase: str = PHASE_ROLLBACK,
        drop_record: bool = True,
    ) -> None:
        """Unbind EVERY member (not just the journaled-bound prefix: a
        crash between a bind and its journal append leaves a bound member
        the record never saw) and drop the gang record (``drop_record``)
        — or, for a remediation's coordinated teardown, keep the record
        in ``phase`` with its bound list cleared so the re-reserve resumes
        from durable state.  A failed unbind keeps the record in ``phase``
        and raises — recover() retries until the teardown converges."""
        guid = self._guid(gang_id)

        def mark(cp: Checkpoint) -> None:
            rec = cp.prepared_claims.get(guid)
            if rec is None or not rec.groups:
                return
            rec.status = PREPARE_STARTED
            rec.groups[0].config_state["phase"] = phase

        self._mutate(mark, [guid])
        failures: list[str] = []
        first = True
        for member in reversed(members):
            try:
                self._binder.unbind(member)
            except Exception as e:  # noqa: BLE001 — every member must be visited
                logger.warning(
                    "gang %s: unbind of %s on %s failed: %s",
                    gang_id, member.claim_uid, member.node, e,
                )
                failures.append(f"{member.claim_uid}@{member.node}: {e}")
            if first:
                # Fires (when armed) after the first member's unbind,
                # while the phase-marked record still names the rest.
                first = False
                _crashpoint("mid-gang-rollback")
        if failures:
            raise GangRollbackIncomplete(
                f"gang {gang_id!r}: {len(failures)} member unbind(s) failed "
                f"({'; '.join(failures[:3])}); record kept for recovery"
            )
        if drop_record:
            def drop(cp: Checkpoint) -> None:
                cp.prepared_claims.pop(guid, None)

            self._mutate(drop, [guid])
        else:
            def clear_bound(cp: Checkpoint) -> None:
                rec = cp.prepared_claims.get(guid)
                if rec is None or not rec.groups:
                    return
                rec.groups[0].config_state["bound"] = json.dumps([])

            self._mutate(clear_bound, [guid])

    def release(self, gang_id: str) -> None:
        """Tear down a bound gang (workload done): unbind every member,
        drop the record.  Also accepts an in-flight record (the operator's
        force-release) — including a crash-interrupted REMEDIATING one,
        whose journaled target members may hold binds the member list
        never names (the same union recovery tears down).  The snapshot is
        read INSIDE the op guard: reading it before could tear down a
        stale member list after a concurrent remediation moved the gang,
        stranding the new members' binds recordless."""
        with self._gang_op(gang_id, "release"):
            rec = self.gangs().get(gang_id)
            if rec is None:
                return
            with trace.start_span(
                "gang.release",
                parent=rec.traceparent or None,
                attrs={"gang": gang_id, "members": len(rec.members)},
            ):
                self._rollback(gang_id, _dedup_members(rec.members, rec.target))
        _GANGS_RELEASED.inc()

    # ----------------------------------------------------------- remediation

    def mark_degraded(
        self, gang_id: str, unhealthy_member_uids: list[str], reason: str = ""
    ) -> bool:
        """Journal a health condition on a BOUND gang: the gang stays
        all-bound (it is degraded, not partial) and becomes the
        remediation loop's input.  Returns False when the gang is absent
        or not bound/degraded (an in-flight gang's health is settled by
        its own rollback path).  Idempotent: re-marking merges uids."""
        guid = self._guid(gang_id)
        changed: list[bool] = []

        def mark(cp: Checkpoint) -> None:
            rec = cp.prepared_claims.get(guid)
            if rec is None or not rec.groups or rec.status != PREPARE_COMPLETED:
                return
            state = rec.groups[0].config_state
            state["phase"] = PHASE_DEGRADED
            have = set(json.loads(state.get("unhealthy", "[]")))
            have.update(unhealthy_member_uids)
            state["unhealthy"] = json.dumps(sorted(have))
            if reason:
                state["degradedReason"] = reason
            changed.append(True)

        self._mutate(mark, [guid])
        if changed:
            logger.warning(
                "gang %s marked degraded (%s): unhealthy members %s",
                gang_id, reason or "unspecified", unhealthy_member_uids,
            )
        return bool(changed)

    def remediate(
        self,
        gang_id: str,
        replacements: dict[str, GangMember],
        claims: dict[str, dict],
        on_member_bound: Optional[Callable[[GangMember], None]] = None,
    ) -> GangStatus:
        """Move a degraded (or bound) gang onto healthy silicon: journal
        the target member plan, COORDINATED rollback of the whole current
        gang (all members — a multi-host mesh cannot run partial, so the
        healthy members' binds are torn down with the sick one's), then
        re-reserve every target member.  ``replacements`` maps old member
        claim uid → its replacement member; unmapped members re-bind
        unchanged (their claims must also appear in ``claims``).

        Converges to all-bound-on-target-members or — when the re-reserve
        fails — cleanly-released (targets unwound, record dropped), never
        partial, never on the old silicon.  A crash anywhere resumes from
        the journaled record (:meth:`recover`).  The gang snapshot is read
        and validated INSIDE the op guard: a pre-guard read could race a
        concurrent release (record gone — targets would bind recordless)
        or a finished remediation (stale member list)."""
        guid = self._guid(gang_id)
        t0 = time.monotonic()
        with self._gang_op(gang_id, "remediate"):
            status = self.gangs().get(gang_id)
            if status is None:
                raise GangBindError(f"gang {gang_id!r} does not exist")
            if status.phase not in ("bound", PHASE_DEGRADED):
                raise GangBindError(
                    f"gang {gang_id!r} is in phase {status.phase!r}: only a "
                    "bound or degraded gang can be remediated (recover() owns "
                    "in-flight records)"
                )
            unknown = set(replacements) - {m.claim_uid for m in status.members}
            if unknown:
                raise GangBindError(
                    f"gang {gang_id!r}: replacement(s) for non-member claim(s) "
                    f"{sorted(unknown)}"
                )
            target = [replacements.get(m.claim_uid, m) for m in status.members]
            missing = [m.claim_uid for m in target if m.claim_uid not in claims]
            if missing:
                raise GangBindError(
                    f"gang {gang_id!r}: no claim object for target member(s) "
                    f"{missing}"
                )
            planned: list[bool] = []

            def plan(cp: Checkpoint) -> None:
                rec = cp.prepared_claims.get(guid)
                if rec is None or not rec.groups:
                    return  # vanished under the guard-protected read? abort
                rec.status = PREPARE_STARTED
                state = rec.groups[0].config_state
                state["phase"] = PHASE_REMEDIATING
                state["target"] = json.dumps([m.to_state() for m in target])
                planned.append(True)

            with trace.start_span(
                "gang.remediate",
                parent=status.traceparent or None,
                attrs={
                    "gang": gang_id,
                    "replaced": sorted(replacements),
                },
            ):
                self._mutate(plan, [guid])
                if not planned:
                    raise GangBindError(
                        f"gang {gang_id!r} record vanished before the "
                        "remediation plan could be journaled"
                    )
                # Fires (when armed) with the plan durable and every OLD
                # member still bound — the canonical mid-remediation crash:
                # recovery must finish the rollback and resume (or release).
                _crashpoint("mid-gang-remediate")
                try:
                    self._finish_remediation(
                        gang_id, status.members, target, claims, on_member_bound
                    )
                except (GangRollbackIncomplete, GangOpInProgress):
                    _REMEDIATION_FAILED.inc()
                    raise
        logger.info(
            "gang %s: remediated onto %s in %.3fs",
            gang_id, [m.node for m in target], time.monotonic() - t0,
        )
        _REMEDIATED.inc()
        return GangStatus(
            gang_id=gang_id,
            phase="bound",
            members=list(target),
            bound=[m.claim_uid for m in target],
        )

    def _finish_remediation(
        self,
        gang_id: str,
        old_members: list[GangMember],
        target: list[GangMember],
        claims: dict[str, dict],
        on_member_bound: Optional[Callable[[GangMember], None]] = None,
    ) -> None:
        """The teardown + re-bind half of a remediation, shared with
        recovery: old members all unbound (record kept, remediating
        phase), targets bound and completed; a target-bind failure unwinds
        the targets and drops the record (cleanly released).  Assumes the
        caller holds the gang op and has journaled the target plan."""
        guid = self._guid(gang_id)
        # Coordinated rollback of the WHOLE gang — old AND target members.
        # Recovery re-runs this path, and a crash mid-re-bind leaves
        # target binds the bound list may not name (same reasoning as
        # reserve's unwind-everything contract); unbind of a never-bound
        # member is a no-op.
        self._rollback(
            gang_id,
            _dedup_members(old_members, target),
            phase=PHASE_REMEDIATING,
            drop_record=False,
        )
        try:
            self._bind_members(
                gang_id, target, claims, on_member_bound,
                crash_point="mid-gang-reserve",
            )
        except _BindStageFailed as e:
            logger.warning(
                "gang %s: remediation re-bind %s failed: %s — releasing",
                gang_id, e.stage, e.cause,
            )
            self._rollback(gang_id, target)  # drops the record
            _REMEDIATION_RELEASED.inc()
            raise GangBindError(
                f"gang {gang_id!r}: remediation {e.stage} failed "
                f"({e.cause}); gang cleanly released"
            ) from e.cause

        def retarget(cp: Checkpoint) -> None:
            rec = cp.prepared_claims.get(guid)
            if rec is None or not rec.groups:
                return
            state = rec.groups[0].config_state
            state["members"] = json.dumps([m.to_state() for m in target])

        self._mutate(retarget, [guid])
        self._complete(guid)

    # ------------------------------------------------------------- recovery

    # tpudra-wal: recovers=gang the controller-start sweep converges every in-flight gang record (rollback, resumed remediation, or release) from checkpoint truth
    def recover(self) -> list[str]:
        """Converge every in-flight gang to a consistent state — the
        crash-recovery sweep, run at controller start.  Returns the gang
        ids acted on.  A completed gang is left alone (all members bound),
        and so is a DEGRADED one (all-bound on sick silicon — the
        remediation loop owns the move; tearing it down here would turn a
        running-but-degraded job into a dead one).  A REMEDIATING gang
        resumes from its journaled plan: finish the coordinated rollback,
        then re-bind the target members when a ``claim_resolver`` can
        refetch their claims — otherwise cleanly release.  Reserving /
        rollback records roll back to none-bound as before.  EVERY gang is
        attempted even when one fails (one unreachable node must not
        strand the others' achievable teardowns); failures aggregate into
        one :class:`GangRollbackIncomplete` raised after the sweep, with
        the failed gangs' records kept for the next retry."""
        rolled: list[str] = []
        failures: list[str] = []
        for gang_id in sorted(self.gangs()):
            try:
                with self._gang_op(gang_id, "recover"):
                    # Re-read INSIDE the guard: acting on a pre-guard
                    # snapshot could tear down a gang a concurrent
                    # remediation just moved to bound-on-targets (the
                    # same TOCTOU release/remediate guard against).
                    status = self.gangs().get(gang_id)
                    if status is None or status.phase in (
                        "bound", PHASE_DEGRADED,
                    ):
                        continue
                    logger.warning(
                        "gang %s: recovering %s-phase record "
                        "(%d members, %d journaled bound)",
                        gang_id, status.phase,
                        len(status.members), len(status.bound),
                    )
                    # Recovery spans resume the ORIGINAL trace: the
                    # traceparent journaled at reserve time rides the WAL
                    # record across the crash.
                    with trace.start_span(
                        "gang.recover",
                        parent=status.traceparent or None,
                        attrs={"gang": gang_id, "phase": status.phase},
                    ):
                        if status.phase == PHASE_REMEDIATING:
                            self._resume_remediation(gang_id, status)
                        else:
                            self._rollback(gang_id, status.members)
            except GangOpInProgress:
                logger.info(
                    "gang %s: live operation in flight; recovery skipped",
                    gang_id,
                )
                continue
            except (GangRollbackIncomplete, GangBindError) as e:
                failures.append(f"{gang_id}: {e}")
                continue
            _GANGS_RECOVERED.inc()
            rolled.append(gang_id)
        if failures:
            raise GangRollbackIncomplete(
                f"{len(failures)} gang(s) did not converge this pass "
                f"({'; '.join(failures[:3])}); records kept for retry"
            )
        return rolled

    def _resume_remediation(self, gang_id: str, status: GangStatus) -> None:
        """Resume a crash-interrupted remediation from its journaled
        record.  With a claim resolver and a resolvable target plan, the
        remediation completes (all-bound on the targets); otherwise the
        whole gang — old members and any target binds the crash left — is
        cleanly released.  Never partial either way."""
        target = status.target
        claims: dict[str, dict] = {}
        if target and self._claim_resolver is not None:
            for m in target:
                try:
                    claim = self._claim_resolver(m)
                except Exception:  # noqa: BLE001 — resolver blip: release below
                    logger.exception(
                        "gang %s: claim resolve for %s failed", gang_id, m.claim_uid
                    )
                    claim = None
                if claim is None:
                    claims = {}
                    break
                claims[m.claim_uid] = claim
        if target and len(claims) == len(target):
            logger.warning(
                "gang %s: resuming remediation onto %s",
                gang_id, [m.node for m in target],
            )
            try:
                self._finish_remediation(gang_id, status.members, target, claims)
            except GangBindError:
                # _finish_remediation already released cleanly (and
                # counted the outcome): converged, just not onto targets.
                return
            _REMEDIATED.inc()
            return
        # No plan, or the target claims are gone: release everything the
        # record names (old members plus any target binds).
        self._rollback(gang_id, _dedup_members(status.members, target))
        _REMEDIATION_RELEASED.inc()

    def partially_bound(
        self, bound_probe: Callable[[GangMember], bool]
    ) -> list[str]:
        """Gang ids whose members are PARTIALLY bound right now, per the
        caller's probe (e.g. "is this claim uid in that node's plugin
        checkpoint").  The chaos soak's gang-atomicity invariant: in a
        quiet window this list must be empty — every gang is all-bound
        (complete record, degraded included) or none-bound (no members
        bound).  A REMEDIATING gang is exempt: it is transitional by
        construction, and the gang-degraded age invariant (sim/chaos.py)
        owns how long it may stay that way."""
        partial = []
        for gang_id, status in self.gangs().items():
            if status.phase == PHASE_REMEDIATING:
                continue
            n_bound = sum(1 for m in status.members if bound_probe(m))
            if status.phase in ("bound", PHASE_DEGRADED):
                if n_bound != len(status.members):
                    partial.append(gang_id)
            elif 0 < n_bound < len(status.members):
                partial.append(gang_id)
        return partial


# ------------------------------------------------- published slice health

@dataclass(frozen=True)
class NodeSliceHealth:
    """What one node's published ResourceSlices say about its silicon."""

    node: str
    advertised: int  # devices currently advertised
    unhealthy: int  # withheld-for-health count (SLICE_UNHEALTHY_ANNOTATION)
    #: The node's plugin checkpoint cannot persist (binds are being shed
    #: with a retryable error — SLICE_STORAGE_DEGRADED_ANNOTATION).  Its
    #: silicon may be perfectly healthy, but a gang member placed there
    #: would only spin on shed errors until the disk heals, so placement
    #: treats it as unavailable.
    storage_degraded: bool = False

    @property
    def healthy(self) -> bool:
        return (
            self.unhealthy == 0
            and self.advertised > 0
            and not self.storage_degraded
        )


def published_slice_health(
    kube, driver: str = TPU_DRIVER_NAME
) -> dict[str, NodeSliceHealth]:
    """Read every node's health straight from its published ResourceSlices
    — the controller-side view the remediation's member selection filters
    on (no node access, no plugin RPC: the slices ARE the advertisement).
    A node with unhealthy silicon publishes a nonzero
    ``SLICE_UNHEALTHY_ANNOTATION`` and the sick devices are absent from
    the device list (plugin/resourceslice.py)."""
    advertised: dict[str, int] = {}
    unhealthy: dict[str, int] = {}
    degraded: set[str] = set()
    for item in kube.list(gvr.RESOURCE_SLICES).get("items", []):
        spec = item.get("spec", {})
        if spec.get("driver") != driver:
            continue
        node = spec.get("nodeName", "")
        advertised[node] = advertised.get(node, 0) + len(spec.get("devices", []))
        annotations = item.get("metadata", {}).get("annotations", {})
        ann = annotations.get(SLICE_UNHEALTHY_ANNOTATION)
        if ann is not None:
            try:
                # One count per node pool; slices of one pool repeat it.
                unhealthy[node] = max(unhealthy.get(node, 0), int(ann))
            except ValueError:
                ...  # a foreign/garbled annotation never fails selection
        if annotations.get(SLICE_STORAGE_DEGRADED_ANNOTATION) in ("true", "1"):
            degraded.add(node)
    return {
        node: NodeSliceHealth(
            node=node,
            advertised=advertised.get(node, 0),
            unhealthy=unhealthy.get(node, 0),
            storage_degraded=node in degraded,
        )
        for node in advertised
    }


def select_healthy_spares(
    kube,
    candidates: list[str],
    exclude: Optional[set] = None,
    driver: str = TPU_DRIVER_NAME,
) -> list[str]:
    """Filter candidate spare nodes on PUBLISHED slice health: a node
    qualifies only when its slices advertise ≥1 device with a zero
    unhealthy count, carry no storage-degraded annotation (a bind there
    would only spin on shed errors), and it is not excluded (the degraded
    gang's current nodes).  Returns qualifying nodes, most-advertised
    first — the remediation picks from the front."""
    exclude = exclude or set()
    health = published_slice_health(kube, driver=driver)
    good = [
        health[n]
        for n in candidates
        if n not in exclude and n in health and health[n].healthy
    ]
    good.sort(key=lambda h: (-h.advertised, h.node))
    return [h.node for h in good]

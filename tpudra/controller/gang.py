"""Gang ("all-or-nothing") slice reservation for ComputeDomains.

PAPER.md's north star — ``kubectl apply`` of a ComputeDomain claim → a JAX
all-reduce across a v5p slice — needs a property no node-local path can
give: a claim for an N-node slice must bind **all N node-local claims or
none**.  A partial gang is worse than a failed one: the bound members hold
channels, node labels, and CDI specs that gate other domains off their
nodes, while the workload can never start (libtpu mesh formation needs
every worker).  This manager is the reference driver's IMEX-domain
formation discipline applied to TPU pod-slice reservation:

- **reserve(gang, members)** journals the gang's *intent* (the full member
  list) through the checkpoint WAL before any member binds, binds members
  one at a time through the injected :class:`GangBinder`, journals each
  member's bind, and flips the gang record to ``PrepareCompleted`` only
  when every member is bound.  Any member failure rolls the bound prefix
  back through the binder's unbind (the existing unprepare path — the
  same idempotent teardown kubelet retries ride) and drops the record.

- **crash consistency**: the WAL record is written *before* the first
  bind, so a controller crash mid-gang (the ``mid-gang-reserve`` /
  ``mid-gang-rollback`` crash points, swept by tests/test_gang.py) leaves
  a durable ``PrepareStarted`` gang whose member list is the rollback
  plan.  :meth:`recover` — run at controller start — unbinds **every**
  member of every non-completed gang (unbind of a never-bound member is a
  no-op by the unprepare path's contract) and drops the record: recovery
  converges to all-bound or none-bound, never partial.

- the gang record rides the same :class:`CheckpointManager` WAL as claim
  records (``gang/<id>`` uids — the prefix keeps them out of any
  claim-shaped scan), so group commit, torn-tail repair, and the
  ``post-journal-append`` / ``mid-compaction`` crash points all apply to
  gang state for free.

The binder is injected because the transport differs by context: the
multi-host harness and the chaos soak bind through in-process CD plugin
drivers (``tpudra/sim/multihost.DriverGangBinder`` — the harness plays
kubelet), a production controller would drive per-node claims through the
apiserver and watch their status.  The manager owns only the all-or-
nothing state machine and its durability.
"""

from __future__ import annotations

import json
import logging
import time
from dataclasses import dataclass
from typing import Callable, Optional, Protocol

from tpudra import metrics
from tpudra.plugin.checkpoint import (
    PREPARE_COMPLETED,
    PREPARE_STARTED,
    Checkpoint,
    CheckpointManager,
    PreparedClaim,
    PreparedDeviceGroup,
)
from tpudra.plugin.device_state import _crashpoint

logger = logging.getLogger(__name__)

#: Checkpoint-uid namespace for gang records.  "/" cannot appear in a k8s
#: object uid, so no claim record can ever collide with a gang record.
GANG_UID_PREFIX = "gang/"

#: config_state phases of a PrepareStarted gang record.  A completed gang
#: (status PREPARE_COMPLETED) is phase-less: all members bound.
PHASE_RESERVING = "reserving"
PHASE_ROLLBACK = "rollback"

_GANGS_BOUND = metrics.GANG_RESERVATIONS_TOTAL.labels("bound")
_GANGS_ROLLED_BACK = metrics.GANG_RESERVATIONS_TOTAL.labels("rolled-back")
_GANGS_RECOVERED = metrics.GANG_RESERVATIONS_TOTAL.labels("recovered")
_GANGS_RELEASED = metrics.GANG_RESERVATIONS_TOTAL.labels("released")


class GangBindError(Exception):
    """A member bind failed; the gang was rolled back to none-bound."""


class GangRollbackIncomplete(Exception):
    """One or more member unbinds failed; the gang record is KEPT in the
    rollback phase so :meth:`GangReservationManager.recover` retries the
    teardown — the record outliving the failure is what makes the
    all-or-nothing contract crash-proof rather than best-effort."""


@dataclass(frozen=True)
class GangMember:
    """One node-local claim of the gang."""

    node: str
    claim_uid: str
    namespace: str = "default"

    def to_state(self) -> dict:
        return {
            "node": self.node,
            "claimUID": self.claim_uid,
            "namespace": self.namespace,
        }

    @classmethod
    def from_state(cls, d: dict) -> "GangMember":
        return cls(
            node=d.get("node", ""),
            claim_uid=d.get("claimUID", ""),
            namespace=d.get("namespace", "default"),
        )


@dataclass
class GangStatus:
    """One gang record, as read back from the checkpoint."""

    gang_id: str
    phase: str  # "bound" | "reserving" | "rollback"
    members: list[GangMember]
    bound: list[str]  # claim uids journaled as bound


class GangBinder(Protocol):
    """Transport for one member's bind/unbind.

    ``bind`` raises on failure (any exception — the manager maps it to a
    rollback); ``unbind`` must be idempotent for members that never bound
    (the unprepare path's existing contract: dropping an unknown claim is
    a no-op), because recovery unbinds the *whole* intent list."""

    def bind(self, member: GangMember, claim: dict) -> None: ...

    def unbind(self, member: GangMember) -> None: ...


class GangReservationManager:
    """All-or-nothing reservation of N node-local claims, journaled.

    One instance per controller; ``checkpoints`` is a dedicated
    CheckpointManager over the controller's state dir (gang records must
    not share a file with any plugin's claim records — different process,
    different lock, different GC)."""

    def __init__(self, checkpoints: CheckpointManager, binder: GangBinder):
        self._cp = checkpoints
        self._binder = binder

    # -------------------------------------------------------------- helpers

    @staticmethod
    def _guid(gang_id: str) -> str:
        return GANG_UID_PREFIX + gang_id

    @staticmethod
    def _record(
        gang_id: str, members: list[GangMember], phase: str, bound: list[str]
    ) -> PreparedClaim:
        return PreparedClaim(
            uid=GANG_UID_PREFIX + gang_id,
            namespace="",
            name=gang_id,
            status=PREPARE_STARTED,
            groups=[
                PreparedDeviceGroup(
                    devices=[],
                    # configState values are strings by the checkpoint's
                    # v2 schema (api/serde typing): the member and bound
                    # lists ride as JSON documents inside it.
                    config_state={
                        "phase": phase,
                        "members": json.dumps([m.to_state() for m in members]),
                        "bound": json.dumps(list(bound)),
                    },
                )
            ],
        )

    @staticmethod
    def _parse(rec: PreparedClaim) -> GangStatus:
        state = rec.groups[0].config_state if rec.groups else {}
        phase = (
            "bound"
            if rec.status == PREPARE_COMPLETED
            else state.get("phase", PHASE_RESERVING)
        )
        return GangStatus(
            gang_id=rec.uid[len(GANG_UID_PREFIX):],
            phase=phase,
            members=[
                GangMember.from_state(m)
                for m in json.loads(state.get("members", "[]"))
            ],
            bound=list(json.loads(state.get("bound", "[]"))),
        )

    def gangs(self) -> dict[str, GangStatus]:
        """Every gang record in the checkpoint (complete and in-flight)."""
        cp = self._cp.read_view()
        return {
            rec.uid[len(GANG_UID_PREFIX):]: self._parse(rec)
            for uid, rec in cp.prepared_claims.items()
            if uid.startswith(GANG_UID_PREFIX)
        }

    # -------------------------------------------------------------- reserve

    def reserve(
        self,
        gang_id: str,
        members: list[GangMember],
        claims: dict[str, dict],
        on_member_bound: Optional[Callable[[GangMember], None]] = None,
    ) -> GangStatus:
        """Bind every member or none.  ``claims`` maps member claim uid →
        the allocated ResourceClaim object handed to the binder.  Raises
        :class:`GangBindError` after a clean rollback,
        :class:`GangRollbackIncomplete` when the rollback itself needs the
        recovery path to finish.  Idempotent: re-reserving a completed
        gang with the same member set returns its status without
        re-binding (the controller-restart / requeue case)."""
        if not members:
            raise ValueError("a gang needs at least one member")
        guid = self._guid(gang_id)
        t0 = time.monotonic()
        cached: list[GangStatus] = []

        def start(cp: Checkpoint) -> None:
            existing = cp.prepared_claims.get(guid)
            if existing is not None:
                status = self._parse(existing)
                same_members = {m.claim_uid for m in status.members} == {
                    m.claim_uid for m in members
                }
                if status.phase == "bound" and same_members:
                    cached.append(status)
                    return
                if same_members:
                    raise GangBindError(
                        f"gang {gang_id!r} exists in phase {status.phase!r}: "
                        "its teardown has not converged yet — recover() "
                        "retries it; re-reserve after"
                    )
                raise GangBindError(
                    f"gang {gang_id!r} already exists in phase "
                    f"{status.phase!r} with a different member set"
                )
            cp.prepared_claims[guid] = self._record(
                gang_id, members, PHASE_RESERVING, []
            )

        self._cp.mutate(start, touched=[guid])
        if cached:
            return cached[0]

        bound: list[GangMember] = []
        failed_stage = "member bind"
        try:
            for member in members:
                failed_stage = f"bind of claim {member.claim_uid!r}"
                self._binder.bind(member, claims[member.claim_uid])
                bound.append(member)

                def journal_bound(cp: Checkpoint, uid=member.claim_uid) -> None:
                    rec = cp.prepared_claims.get(guid)
                    if rec is None or not rec.groups:
                        return  # dropped by a concurrent release; rollback wins
                    state = rec.groups[0].config_state
                    done = json.loads(state.get("bound", "[]"))
                    if uid not in done:
                        done.append(uid)
                        state["bound"] = json.dumps(done)

                failed_stage = f"bind journal for claim {member.claim_uid!r}"
                self._cp.mutate(journal_bound, touched=[guid])
                # Fires (when armed) after the FIRST member is durably
                # bound and before the rest: the canonical partial-gang
                # crash for the sweep, as long as the gang has ≥2 members.
                _crashpoint("mid-gang-reserve")
                if on_member_bound is not None:
                    failed_stage = f"post-bind callback for {member.claim_uid!r}"
                    on_member_bound(member)
        except Exception as e:
            logger.warning(
                "gang %s: %s failed after %d/%d bound: %s — rolling back",
                gang_id, failed_stage, len(bound), len(members), e,
            )
            self._rollback(gang_id, members)
            _GANGS_ROLLED_BACK.inc()
            raise GangBindError(
                f"gang {gang_id!r}: {failed_stage} failed ({e}); "
                f"all {len(bound)} bound member(s) rolled back"
            ) from e

        def complete(cp: Checkpoint) -> None:
            rec = cp.prepared_claims.get(guid)
            if rec is None:
                return
            rec.status = PREPARE_COMPLETED

        self._cp.mutate(complete, touched=[guid])
        _GANGS_BOUND.inc()
        metrics.GANG_BIND_SECONDS.labels(str(len(members))).observe(
            time.monotonic() - t0
        )
        logger.info(
            "gang %s: all %d members bound in %.3fs",
            gang_id, len(members), time.monotonic() - t0,
        )
        return GangStatus(
            gang_id=gang_id,
            phase="bound",
            members=list(members),
            bound=[m.claim_uid for m in members],
        )

    # ------------------------------------------------------------- rollback

    def _rollback(self, gang_id: str, members: list[GangMember]) -> None:
        """Unbind EVERY member (not just the journaled-bound prefix: a
        crash between a bind and its journal append leaves a bound member
        the record never saw) and drop the gang record.  A failed unbind
        keeps the record in the rollback phase and raises — recover()
        retries until the teardown converges."""
        guid = self._guid(gang_id)

        def mark(cp: Checkpoint) -> None:
            rec = cp.prepared_claims.get(guid)
            if rec is None or not rec.groups:
                return
            rec.status = PREPARE_STARTED
            rec.groups[0].config_state["phase"] = PHASE_ROLLBACK

        self._cp.mutate(mark, touched=[guid])
        failures: list[str] = []
        first = True
        for member in reversed(members):
            try:
                self._binder.unbind(member)
            except Exception as e:  # noqa: BLE001 — every member must be visited
                logger.warning(
                    "gang %s: unbind of %s on %s failed: %s",
                    gang_id, member.claim_uid, member.node, e,
                )
                failures.append(f"{member.claim_uid}@{member.node}: {e}")
            if first:
                # Fires (when armed) after the first member's unbind,
                # while the rollback-phase record still names the rest.
                first = False
                _crashpoint("mid-gang-rollback")
        if failures:
            raise GangRollbackIncomplete(
                f"gang {gang_id!r}: {len(failures)} member unbind(s) failed "
                f"({'; '.join(failures[:3])}); record kept for recovery"
            )

        def drop(cp: Checkpoint) -> None:
            cp.prepared_claims.pop(guid, None)

        self._cp.mutate(drop, touched=[guid])

    def release(self, gang_id: str) -> None:
        """Tear down a bound gang (workload done): unbind every member,
        drop the record.  Also accepts an in-flight record (the operator's
        force-release)."""
        rec = self.gangs().get(gang_id)
        if rec is None:
            return
        self._rollback(gang_id, rec.members)
        _GANGS_RELEASED.inc()

    # ------------------------------------------------------------- recovery

    def recover(self) -> list[str]:
        """Converge every non-completed gang to none-bound — the crash-
        recovery sweep, run at controller start.  Returns the rolled-back
        gang ids.  A completed gang is left alone (all members bound — the
        other consistent outcome).  EVERY gang is attempted even when one
        rollback fails (one unreachable node must not strand the others'
        fully-achievable teardowns); the failures aggregate into one
        :class:`GangRollbackIncomplete` raised after the sweep, with the
        failed gangs' records kept for the next retry."""
        rolled: list[str] = []
        failures: list[str] = []
        for gang_id, status in sorted(self.gangs().items()):
            if status.phase == "bound":
                continue
            logger.warning(
                "gang %s: recovering %s-phase record (%d members, %d journaled bound)",
                gang_id, status.phase, len(status.members), len(status.bound),
            )
            try:
                self._rollback(gang_id, status.members)
            except GangRollbackIncomplete as e:
                failures.append(f"{gang_id}: {e}")
                continue
            _GANGS_RECOVERED.inc()
            rolled.append(gang_id)
        if failures:
            raise GangRollbackIncomplete(
                f"{len(failures)} gang(s) did not converge this pass "
                f"({'; '.join(failures[:3])}); records kept for retry"
            )
        return rolled

    def partially_bound(
        self, bound_probe: Callable[[GangMember], bool]
    ) -> list[str]:
        """Gang ids whose members are PARTIALLY bound right now, per the
        caller's probe (e.g. "is this claim uid in that node's plugin
        checkpoint").  The chaos soak's gang-atomicity invariant: in a
        quiet window this list must be empty — every gang is all-bound
        (complete record) or none-bound (no members bound)."""
        partial = []
        for gang_id, status in self.gangs().items():
            n_bound = sum(1 for m in status.members if bound_probe(m))
            if status.phase == "bound":
                if n_bound != len(status.members):
                    partial.append(gang_id)
            elif 0 < n_bound < len(status.members):
                partial.append(gang_id)
        return partial

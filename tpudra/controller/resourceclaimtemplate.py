"""ResourceClaimTemplate management for ComputeDomains.

The analog of compute-domain-controller/resourceclaimtemplate.go:79-399. Two
specializations of one base:

- the **daemon RCT**, in the driver's namespace, consumed by the DaemonSet
  pod; deviceClass ``compute-domain-daemon.tpu.google.com``, opaque
  ``ComputeDomainDaemonConfig{domainID}``.
- the **workload RCT**, created in the *CD's own namespace* under the
  user-chosen name from ``spec.channel.resourceClaimTemplate.name``;
  deviceClass ``compute-domain-default-channel.tpu.google.com``, opaque
  ``ComputeDomainChannelConfig{domainID, allocationMode}`` — this is the
  template user pods reference to receive a slice channel.
"""

from __future__ import annotations

import logging

from tpudra import COMPUTE_DOMAIN_DRIVER_NAME
from tpudra.api.computedomain import (
    CHANNEL_ALLOCATION_MODE_SINGLE,
    ComputeDomainChannelConfig,
    ComputeDomainDaemonConfig,
)
from tpudra.api.serde import encode
from tpudra.kube import gvr
from tpudra.kube.client import KubeAPI
from tpudra.kube.errors import NotFound

logger = logging.getLogger(__name__)

CD_UID_LABEL = "resource.tpu.google.com/computeDomain"
DAEMON_DEVICE_CLASS = "compute-domain-daemon.tpu.google.com"
CHANNEL_DEVICE_CLASS = "compute-domain-default-channel.tpu.google.com"


def _rct(
    name: str,
    namespace: str,
    cd: dict,
    device_class: str,
    opaque_config: dict,
    owner_ref: bool,
) -> dict:
    meta: dict = {
        "name": name,
        "namespace": namespace,
        "labels": {CD_UID_LABEL: cd["metadata"]["uid"]},
    }
    if owner_ref:
        meta["ownerReferences"] = [
            {
                "apiVersion": cd.get("apiVersion", ""),
                "kind": cd.get("kind", "ComputeDomain"),
                "name": cd["metadata"]["name"],
                "uid": cd["metadata"]["uid"],
                "controller": True,
            }
        ]
    return {
        "apiVersion": "resource.k8s.io/v1",
        "kind": "ResourceClaimTemplate",
        "metadata": meta,
        "spec": {
            "spec": {
                "devices": {
                    "requests": [
                        {
                            "name": "channel" if device_class == CHANNEL_DEVICE_CLASS else "daemon",
                            "exactly": {
                                "deviceClassName": device_class,
                                "allocationMode": "ExactCount",
                                "count": 1,
                            },
                        }
                    ],
                    "config": [
                        {
                            "opaque": {
                                "driver": COMPUTE_DOMAIN_DRIVER_NAME,
                                "parameters": opaque_config,
                            }
                        }
                    ],
                }
            }
        },
    }


class DaemonResourceClaimTemplateManager:
    """Daemon RCT in the driver namespace (resourceclaimtemplate.go:304)."""

    def __init__(self, kube: KubeAPI, driver_namespace: str):
        self._kube = kube
        self._ns = driver_namespace

    def name(self, cd: dict) -> str:
        return f"compute-domain-daemon-{cd['metadata']['uid']}"

    def ensure(self, cd: dict) -> dict:
        name = self.name(cd)
        try:
            return self._kube.get(gvr.RESOURCE_CLAIM_TEMPLATES, name, self._ns)
        except NotFound:
            pass
        config = ComputeDomainDaemonConfig(domain_id=cd["metadata"]["uid"])
        obj = _rct(
            name,
            self._ns,
            cd,
            DAEMON_DEVICE_CLASS,
            encode(config),
            owner_ref=False,  # cross-namespace owners are not allowed
        )
        logger.info("creating daemon RCT %s/%s", self._ns, name)
        return self._kube.create(gvr.RESOURCE_CLAIM_TEMPLATES, obj, self._ns)

    def remove(self, cd_uid: str) -> None:
        name = f"compute-domain-daemon-{cd_uid}"
        try:
            self._kube.delete(gvr.RESOURCE_CLAIM_TEMPLATES, name, self._ns)
        except NotFound:
            pass

    def assert_removed(self, cd_uid: str) -> bool:
        try:
            self._kube.get(
                gvr.RESOURCE_CLAIM_TEMPLATES, f"compute-domain-daemon-{cd_uid}", self._ns
            )
            return False
        except NotFound:
            return True


class WorkloadResourceClaimTemplateManager:
    """Workload channel RCT in the CD's namespace
    (resourceclaimtemplate.go:364)."""

    def __init__(self, kube: KubeAPI):
        self._kube = kube

    @staticmethod
    def requested_name(cd: dict) -> str | None:
        channel = cd.get("spec", {}).get("channel") or {}
        name = (channel.get("resourceClaimTemplate") or {}).get("name", "")
        return name or None

    def ensure(self, cd: dict) -> dict | None:
        name = self.requested_name(cd)
        if name is None:
            return None
        ns = cd["metadata"]["namespace"]
        try:
            return self._kube.get(gvr.RESOURCE_CLAIM_TEMPLATES, name, ns)
        except NotFound:
            pass
        channel = cd.get("spec", {}).get("channel") or {}
        config = ComputeDomainChannelConfig(
            domain_id=cd["metadata"]["uid"],
            allocation_mode=channel.get("allocationMode", CHANNEL_ALLOCATION_MODE_SINGLE),
        )
        obj = _rct(name, ns, cd, CHANNEL_DEVICE_CLASS, encode(config), owner_ref=True)
        logger.info("creating workload RCT %s/%s", ns, name)
        return self._kube.create(gvr.RESOURCE_CLAIM_TEMPLATES, obj, ns)

    def remove(self, cd: dict) -> None:
        name = self.requested_name(cd)
        if name is None:
            return
        try:
            self._kube.delete(
                gvr.RESOURCE_CLAIM_TEMPLATES, name, cd["metadata"]["namespace"]
            )
        except NotFound:
            pass

    def assert_removed(self, cd: dict) -> bool:
        name = self.requested_name(cd)
        if name is None:
            return True
        try:
            self._kube.get(gvr.RESOURCE_CLAIM_TEMPLATES, name, cd["metadata"]["namespace"])
            return False
        except NotFound:
            return True

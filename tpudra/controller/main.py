"""ComputeDomain controller binary (the cmd/compute-domain-controller analog)."""

from __future__ import annotations

import argparse
import logging

from tpudra.flags import (
    add_common_flags,
    env_default,
    install_stop_handlers,
    make_kube_client_from_args,
    setup_common,
)

logger = logging.getLogger(__name__)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("compute-domain-controller")
    add_common_flags(p)
    p.add_argument("--namespace", default=env_default("NAMESPACE", "tpudra-system"))
    p.add_argument("--image", default=env_default("DAEMON_IMAGE", "tpudra:latest"))
    p.add_argument(
        "--max-nodes-per-domain", type=int,
        default=int(env_default("MAX_NODES_PER_DOMAIN", "0")),
        help="refuse CDs larger than this (0 = unlimited) [MAX_NODES_PER_DOMAIN]",
    )
    p.add_argument(
        "--additional-namespaces",
        default=env_default("ADDITIONAL_NAMESPACES", ""),
        help="comma-separated extra namespaces where per-CD DaemonSets may "
        "live and are swept (reference --additional-namespaces) "
        "[ADDITIONAL_NAMESPACES]",
    )
    p.add_argument(
        "--http-endpoint",
        default=env_default("HTTP_ENDPOINT", ""),
        help="opt-in host:port serving /metrics, /debug/stacks and /healthz "
        "(reference SetupHTTPEndpoint, main.go:256) [HTTP_ENDPOINT]",
    )
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    setup_common(args)

    from tpudra.controller import Controller, ManagerConfig

    kube = make_kube_client_from_args(args)
    controller = Controller(
        kube,
        ManagerConfig(
            driver_namespace=args.namespace,
            image=args.image,
            max_nodes_per_domain=args.max_nodes_per_domain,
            additional_namespaces=tuple(
                ns.strip() for ns in args.additional_namespaces.split(",") if ns.strip()
            ),
            log_verbosity=args.log_verbosity,
        ),
    )
    stop = install_stop_handlers()
    debug = None
    if args.http_endpoint:
        from tpudra.metrics import DebugEndpoint, parse_http_endpoint

        try:
            host, port = parse_http_endpoint(args.http_endpoint)
        except ValueError as e:
            build_parser().error(str(e))
        debug = DebugEndpoint(host, port)
        debug.start()

    logger.info("compute-domain-controller up in namespace %s", args.namespace)
    try:
        controller.run(stop)  # blocks until stop
    finally:
        if debug is not None:
            debug.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

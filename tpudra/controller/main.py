"""ComputeDomain controller binary (the cmd/compute-domain-controller analog)."""

from __future__ import annotations

import argparse
import logging
import signal
import threading

from tpudra.flags import add_common_flags, env_default, make_kube_client, setup_common

logger = logging.getLogger(__name__)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("compute-domain-controller")
    add_common_flags(p)
    p.add_argument("--namespace", default=env_default("NAMESPACE", "tpudra-system"))
    p.add_argument("--image", default=env_default("DAEMON_IMAGE", "tpudra:latest"))
    p.add_argument(
        "--max-nodes-per-domain", type=int,
        default=int(env_default("MAX_NODES_PER_DOMAIN", "0")),
        help="refuse CDs larger than this (0 = unlimited) [MAX_NODES_PER_DOMAIN]",
    )
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    setup_common(args)

    from tpudra.controller import Controller, ManagerConfig

    kube = make_kube_client(args.kubeconfig)
    controller = Controller(
        kube,
        ManagerConfig(
            driver_namespace=args.namespace,
            image=args.image,
            max_nodes_per_domain=args.max_nodes_per_domain,
        ),
    )
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    logger.info("compute-domain-controller up in namespace %s", args.namespace)
    controller.run(stop)  # blocks until stop
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

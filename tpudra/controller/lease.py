"""Lease-based controller leader election with fencing tokens.

PAPER.md's reference architecture runs the controller as a Deployment
whose replicas coordinate through ``coordination.k8s.io`` Lease leader
election (the controller-runtime manager every reference controller
embeds).  This module is that discipline for our controller: one
:class:`LeaseElector` per candidate replica, all competing for one Lease
object through the shared :class:`~tpudra.kube.client.KubeAPI` protocol
(FakeKube in the harnesses, the real apiserver in production).

The algorithm is client-go's ``leaderelection`` package, including its one
subtle-but-load-bearing choice: **expiry is judged by the observer's own
monotonic clock**, never by comparing the record's timestamps against
local wall time.  A candidate remembers *when it last saw the lease
record change* (resourceVersion transition) and treats the lease as
expired only after ``lease_duration_s`` of no observed change — so two
replicas with skewed wall clocks cannot steal a live leader's lease, and
the chaos soak's ``clock_skew`` fault cannot manufacture split-brain.

**Fencing tokens.**  Every acquisition bumps the Lease's
``leaseTransitions`` counter — on EVERY term change, not just holder
changes — and that monotonic value is the term handed to
``on_started_leading(term)``.  The term is the fence: the gang manager
journals it into the checkpoint WAL and refuses commits from any term
below the journaled high-water mark (``controller/gang.py`` StaleLeader),
so even a lease layer gone wrong (a paused-then-revived leader that still
*believes* it leads) cannot corrupt gang state.  Lease-based mutual
exclusion alone is famously insufficient exactly because of that revival
window; the fence is what makes leadership a safety property instead of a
probabilistic one.

**Outage behavior.**  Renew failures retry on the shared full-jitter
:class:`~tpudra.backoff.Backoff` and honor any 429/503 ``Retry-After``
hint as a floor.  Leadership is *held through the grace window*: the
candidate keeps acting as leader until ``lease_duration_s`` has elapsed
since its last successful renew — the instant a rival could legitimately
take the lease — then calls ``on_stopped_leading`` and demotes itself.
An apiserver outage shorter than the grace window therefore costs nothing
but retries; a longer one parks the controller, and the first renew after
recovery either re-establishes the hold or observes the new holder.

Lock discipline: ``lease.state_lock`` guards only in-memory bookkeeping
(leader flag, observation timestamps) and is never held across an
apiserver verb — acquire/renew run lock-free and publish their outcome
under the lock afterwards (docs/lock-order.md).
"""

from __future__ import annotations

import logging
import random
import threading
import time
import uuid
from dataclasses import dataclass
from typing import Callable, Optional

from tpudra import lockwitness, metrics
from tpudra.backoff import Backoff
from tpudra.kube import errors, gvr
from tpudra.kube.client import KubeAPI

logger = logging.getLogger(__name__)

#: Default Lease object name — one per controller deployment, the way the
#: reference's controller-runtime manager names its election lock.
DEFAULT_LEASE_NAME = "tpudra-controller"


def _now_rfc3339() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


@dataclass
class _Observation:
    """What this candidate last saw on the lease record, and WHEN (its own
    monotonic clock) — the only clock expiry is ever judged by."""

    resource_version: str = ""
    holder: str = ""
    transitions: int = 0
    seen_at: float = 0.0  # time.monotonic()


class LeaseElector:
    """One candidate in the controller's leader election.

    ``on_started_leading(term)`` / ``on_stopped_leading()`` run on the
    elector's own thread, in order; a candidate that re-acquires after a
    loss gets a strictly larger ``term``.  ``start(stop)`` spawns the
    loop; :meth:`release` hands the lease off gracefully (shutdown);
    :meth:`crash` kills the loop WITHOUT touching the lease — the
    SIGKILL-shaped stop the chaos soak's failover fault uses, leaving the
    standby to wait out the full expiry window like a real crash would.
    """

    def __init__(
        self,
        kube: KubeAPI,
        identity: str = "",
        name: str = DEFAULT_LEASE_NAME,
        namespace: str = "tpudra-system",
        lease_duration_s: float = 15.0,
        renew_interval_s: float = 5.0,
        on_started_leading: Optional[Callable[[int], None]] = None,
        on_stopped_leading: Optional[Callable[[], None]] = None,
        rng=None,
    ):
        if renew_interval_s >= lease_duration_s:
            raise ValueError(
                "renew_interval_s must be < lease_duration_s (a candidate "
                "that renews slower than expiry loses its own lease)"
            )
        self._kube = kube
        self.identity = identity or f"tpudra-{uuid.uuid4().hex[:8]}"
        self._name = name
        self._namespace = namespace
        self.lease_duration_s = lease_duration_s
        self.renew_interval_s = renew_interval_s
        self._on_started = on_started_leading
        self._on_stopped = on_stopped_leading
        # Retry pacing for failed acquire/renew attempts: full jitter on
        # the shared policy so N candidates hammered by one apiserver flap
        # do not march back in lockstep (tpudra/backoff.py).
        self._backoff = Backoff(
            max(0.05, renew_interval_s / 4.0), lease_duration_s, rng=rng
        )
        self._rng = rng if rng is not None else random
        self._state_lock = lockwitness.make_lock("lease.state_lock")
        self._is_leader = False
        self._term = 0
        self._last_renew = 0.0  # monotonic; last SUCCESSFUL acquire/renew
        self._obs = _Observation()
        #: Highest leaseTransitions this candidate has EVER observed —
        #: survives the Lease object being deleted and recreated (the
        #: operator's force-failover move): minted terms are floored on
        #: it, so a recreated lease cannot restart the fencing sequence
        #: at 1 and fence the new leader out of its own WAL.
        self._max_seen_transitions = 0
        self._crashed = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._gauge = metrics.LEADER_IS_LEADER.labels(self.identity)

    # ------------------------------------------------------------- queries

    @property
    def is_leader(self) -> bool:
        with self._state_lock:
            return self._is_leader

    @property
    def term(self) -> int:
        """The fencing token of the CURRENT term (0 before first
        acquisition; stale once leadership is lost)."""
        with self._state_lock:
            return self._term

    # ----------------------------------------------------------- lifecycle

    def start(self, stop: threading.Event) -> threading.Thread:
        self._thread = threading.Thread(
            target=self.run,
            args=(stop,),
            daemon=True,
            name=f"lease-elector-{self.identity}",
        )
        self._thread.start()
        return self._thread

    def crash(self) -> None:
        """SIGKILL-shaped stop: the loop ends as soon as it notices, the
        lease is left EXACTLY as it stands (held, un-released), and no
        ``on_stopped_leading`` fires — the process is 'gone'.  A standby
        must wait out the full ``lease_duration_s`` expiry window, the
        real crash-failover cost docs/ha.md quantifies.  The leadership
        gauge zeroes: a dead process exports nothing, and the in-process
        harnesses (soak, bench) run many failovers in one registry — a
        stuck 1 per dead identity would fake concurrent leaders."""
        self._crashed.set()
        self._gauge.set(0)

    def run(self, stop: threading.Event) -> None:
        """The candidate loop: acquire when the lease is free or expired,
        renew while holding, demote when the grace window closes."""
        try:
            while not stop.is_set() and not self._crashed.is_set():
                if self.is_leader:
                    self._renew_once(stop)
                else:
                    self._acquire_once(stop)
        finally:
            if (
                self.is_leader
                and not self._crashed.is_set()
            ):
                self.release()

    def release(self) -> None:
        """Graceful handoff: clear the holder so a standby acquires
        immediately instead of waiting out expiry.  Demotes first (the
        callback ordering contract: we stop ACTING before anyone else can
        start)."""
        was_leader = self._demote(reason="released")
        if not was_leader:
            return
        metrics.LEADER_ELECTIONS_TOTAL.labels("released").inc()
        try:
            lease = self._kube.get(gvr.LEASES, self._name, self._namespace)
            spec = lease.setdefault("spec", {})
            if spec.get("holderIdentity") != self.identity:
                return  # someone already took it; nothing to hand off
            spec["holderIdentity"] = ""
            spec["renewTime"] = _now_rfc3339()
            self._kube.update(gvr.LEASES, lease, self._namespace)
        except errors.ApiError as e:
            # Expiry hands it off anyway, just slower.
            logger.info("lease release failed (expiry will cover): %s", e)

    def advance_term(self, min_term: int) -> int:
        """Bump the HELD lease's transitions counter to ``min_term`` and
        adopt it — the repair for a deleted-and-recreated Lease whose
        restarted numbering minted a term at or below a fence's journaled
        high-water (docs/ha.md): the holder pushes the counter past
        history so fencing resumes above it.  CAS-guarded (holder must
        still be this identity); raises ApiError on failure, Conflict
        when the lease is no longer ours."""
        lease = self._kube.get(gvr.LEASES, self._name, self._namespace)
        spec = lease.setdefault("spec", {})
        if (spec.get("holderIdentity", "") or "") != self.identity:
            raise errors.Conflict(
                f"lease {self._name} no longer held by {self.identity}"
            )
        current = int(spec.get("leaseTransitions", 0) or 0)
        term = max(min_term, current)
        if term > current:
            spec["leaseTransitions"] = term
            spec["renewTime"] = _now_rfc3339()
            updated = self._kube.update(gvr.LEASES, lease, self._namespace)
            self._observe(updated)
        with self._state_lock:
            self._term = term
            self._max_seen_transitions = max(
                self._max_seen_transitions, term
            )
        logger.warning(
            "lease %s: %s advanced fencing term to %d (recreated-lease "
            "repair)", self._name, self.identity, term,
        )
        return term

    # ------------------------------------------------------------ acquire

    def _observe(self, lease: dict) -> None:
        """Record the lease state + our OWN monotonic read time; the
        expiry judgment below only ever compares against this."""
        rv = lease.get("metadata", {}).get("resourceVersion", "")
        with self._state_lock:
            transitions = int(
                lease.get("spec", {}).get("leaseTransitions", 0) or 0
            )
            self._max_seen_transitions = max(
                self._max_seen_transitions, transitions
            )
            if rv != self._obs.resource_version:
                spec = lease.get("spec", {})
                self._obs = _Observation(
                    resource_version=rv,
                    holder=spec.get("holderIdentity", "") or "",
                    transitions=transitions,
                    seen_at=time.monotonic(),
                )

    def _observed_expired(self) -> bool:
        with self._state_lock:
            obs = self._obs
        if not obs.resource_version:
            return False  # never seen it: creation path handles absence
        if not obs.holder:
            return True  # released: free for the taking
        return time.monotonic() - obs.seen_at > self.lease_duration_s

    def _acquire_once(self, stop: threading.Event) -> None:
        try:
            acquired = self._try_acquire()
        except Exception as e:  # noqa: BLE001 — transport faults (raw URLError
            # included: the real client only types HTTP-level failures) must
            # not kill the candidate loop; the backoff paces the retry.
            logger.warning("lease %s: acquire attempt failed: %s", self._name, e)
            self._wait(stop, self._failure_delay(e))
            return
        self._backoff.reset()
        if acquired:
            return
        # Someone else holds a live lease: poll again around the renew
        # cadence (jittered so N standbys don't GET in lockstep).
        self._wait(
            stop,
            self.renew_interval_s * (0.5 + 0.5 * self._rng.random()),
        )

    def _try_acquire(self) -> bool:
        """One acquisition attempt.  Returns True on success; raises
        ApiError on transport failure; False when a live holder stands."""
        try:
            lease = self._kube.get(gvr.LEASES, self._name, self._namespace)
        except errors.NotFound:
            lease = None
        if lease is None:
            # A deleted-and-recreated Lease must not restart the fencing
            # sequence: mint past everything this candidate ever saw.
            with self._state_lock:
                minted = self._max_seen_transitions + 1
            body = {
                "apiVersion": gvr.LEASES.api_version,
                "kind": gvr.LEASES.kind,
                "metadata": {"name": self._name, "namespace": self._namespace},
                "spec": {
                    "holderIdentity": self.identity,
                    "leaseDurationSeconds": int(
                        max(1, round(self.lease_duration_s))
                    ),
                    "acquireTime": _now_rfc3339(),
                    "renewTime": _now_rfc3339(),
                    "leaseTransitions": minted,
                },
            }
            try:
                created = self._kube.create(
                    gvr.LEASES, body, self._namespace
                )
            except errors.AlreadyExists:
                return False  # lost the creation race; observe next pass
            self._observe(created)
            self._promote(minted)
            return True
        self._observe(lease)
        spec = lease.get("spec", {})
        holder = spec.get("holderIdentity", "") or ""
        if holder != self.identity and not self._observed_expired():
            return False
        # Free, expired, or already ours (a restart re-finding its own
        # lease takes a FRESH term: the old incarnation's journaled term
        # must not fence the new one out).  Floored on the highest count
        # this candidate ever observed — a recreated lease's restarted
        # numbering never regresses a term.
        with self._state_lock:
            floor = self._max_seen_transitions
        transitions = max(int(spec.get("leaseTransitions", 0) or 0), floor) + 1
        spec.update(
            {
                "holderIdentity": self.identity,
                "leaseDurationSeconds": int(
                    max(1, round(self.lease_duration_s))
                ),
                "acquireTime": _now_rfc3339(),
                "renewTime": _now_rfc3339(),
                "leaseTransitions": transitions,
            }
        )
        try:
            updated = self._kube.update(gvr.LEASES, lease, self._namespace)
        except errors.Conflict:
            return False  # a rival's write landed first; observe next pass
        self._observe(updated)
        self._promote(transitions)
        return True

    # -------------------------------------------------------------- renew

    def _renew_once(self, stop: threading.Event) -> None:
        """One renew CYCLE: wait the interval, then renew — retrying on
        the backoff alone until it lands or the grace window closes.  The
        retries must NOT pay the interval again (a failure already spent
        time): stacking interval + backoff per attempt would burn a grace
        window sized for backoff-paced retries and demote on outages the
        grace was meant to absorb."""
        self._wait(stop, self.renew_interval_s)
        while not stop.is_set() and not self._crashed.is_set():
            try:
                lease = self._kube.get(gvr.LEASES, self._name, self._namespace)
                spec = lease.get("spec", {})
                if (spec.get("holderIdentity", "") or "") != self.identity:
                    # Someone took it (our grace lapsed during an outage and
                    # a rival acquired): demote NOW, before anything runs.
                    self._observe(lease)
                    self._demote(reason="lease taken by " + (
                        spec.get("holderIdentity") or "nobody"
                    ))
                    metrics.LEADER_ELECTIONS_TOTAL.labels("lost").inc()
                    return
                spec["renewTime"] = _now_rfc3339()
                updated = self._kube.update(gvr.LEASES, lease, self._namespace)
                self._observe(updated)
                with self._state_lock:
                    self._last_renew = time.monotonic()
                self._backoff.reset()
                return
            except errors.NotFound:
                # The Lease object is GONE — the operator's force-failover
                # move (kubectl delete lease).  A standby recreates it and
                # leads within one poll; riding the grace window here would
                # keep TWO actors dispatching unfenced writes for up to
                # lease_duration_s.  Demote NOW and let the candidate loop
                # re-acquire (the recreated-lease term floor keeps the
                # fencing sequence monotonic either way).
                self._demote(reason="lease deleted out from under the holder")
                metrics.LEADER_ELECTIONS_TOTAL.labels("lost").inc()
                return
            except Exception as e:  # noqa: BLE001 — transport faults (raw
                # URLError included) must not kill the loop; the grace
                # arithmetic owns whether the failure costs leadership.
                metrics.LEADER_ELECTIONS_TOTAL.labels("renew-failed").inc()
                with self._state_lock:
                    grace_left = self.lease_duration_s - (
                        time.monotonic() - self._last_renew
                    )
                if grace_left <= 0:
                    # The instant a rival could legitimately acquire: stop
                    # acting.  (The fence catches us if we misjudge.)
                    logger.warning(
                        "lease %s: renew failing past the grace window (%s); "
                        "demoting", self._name, e,
                    )
                    self._demote(reason=f"grace expired during outage: {e}")
                    metrics.LEADER_ELECTIONS_TOTAL.labels("lost").inc()
                    return
                delay = min(self._failure_delay(e), max(0.05, grace_left / 2))
                logger.info(
                    "lease %s: renew failed (%s); %0.1fs grace left, "
                    "retrying in %.2fs", self._name, e, grace_left, delay,
                )
                self._wait(stop, delay)

    # ----------------------------------------------------------- internals

    def _failure_delay(self, e: Exception) -> float:
        delay = self._backoff.next_delay()
        retry_after = errors.retry_after_of(e)
        if retry_after is not None:
            delay = max(delay, retry_after)
        return delay

    def _wait(self, stop: threading.Event, seconds: float) -> None:
        deadline = time.monotonic() + max(0.0, seconds)
        while not stop.is_set() and not self._crashed.is_set():
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return
            stop.wait(min(0.05, remaining))

    def _promote(self, term: int) -> None:
        if self._crashed.is_set():
            # crash() landed while the acquire verb was in flight and the
            # write won the race: the lease IS held by this identity —
            # exactly a process dying right after its write hit the wire,
            # so the standby pays the full expiry window — but a "dead"
            # incarnation must not start acting, fire callbacks, or raise
            # the leadership gauge back to 1 for a gone identity.
            return
        with self._state_lock:
            self._is_leader = True
            self._term = term
            self._last_renew = time.monotonic()
        self._gauge.set(1)
        metrics.LEADER_ELECTIONS_TOTAL.labels("acquired").inc()
        logger.info(
            "lease %s: %s acquired leadership (term %d)",
            self._name, self.identity, term,
        )
        if self._on_started is not None:
            self._on_started(term)

    def _demote(self, reason: str) -> bool:
        """Flip to follower; returns whether we WERE leader (callbacks and
        metrics fire only on the edge)."""
        with self._state_lock:
            was = self._is_leader
            self._is_leader = False
        if not was:
            return False
        self._gauge.set(0)
        logger.warning(
            "lease %s: %s lost leadership (%s)",
            self._name, self.identity, reason,
        )
        if self._on_stopped is not None:
            self._on_stopped()
        return True

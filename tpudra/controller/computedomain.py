"""ComputeDomain reconciliation.

The analog of compute-domain-controller/computedomain.go + cdstatus.go:

- add/update: add finalizer → ensure daemon RCT → ensure DaemonSet → ensure
  workload RCT (in the CD's namespace) → aggregate status from
  ComputeDomainClique CRs (computedomain.go:298-374, cdstatus.go:135-265)
- delete: teardown chain with assert-removed ordering — workload RCT →
  DaemonSet → daemon RCT → node labels → cliques → drop finalizer
  (computedomain.go:314-348); each step must be observed gone before the
  next, so partial teardowns converge across controller restarts
- global status: Ready iff the CD has at least spec.numNodes nodes and every
  node reports Ready (computedomain.go:251-265)
"""

from __future__ import annotations

import logging
from typing import Optional

from tpudra.api.computedomain import (
    COMPUTE_DOMAIN_STATUS_NOT_READY,
    COMPUTE_DOMAIN_STATUS_READY,
)
from tpudra import featuregates
from tpudra.controller.daemonset import MultiNamespaceDaemonSetManager
from tpudra.controller.node import NodeManager
from tpudra.controller.resourceclaimtemplate import (
    CD_UID_LABEL,
    DaemonResourceClaimTemplateManager,
    WorkloadResourceClaimTemplateManager,
)
from tpudra.kube import gvr
from tpudra.kube.client import KubeAPI
from tpudra.kube.errors import Conflict, NotFound

logger = logging.getLogger(__name__)

CD_FINALIZER = "resource.tpu.google.com/computeDomain"


class RetryLater(Exception):
    """Reconcile step not yet satisfied; requeue the key."""


class ComputeDomainManager:
    def __init__(
        self,
        kube: KubeAPI,
        driver_namespace: str,
        image: str = "tpudra:latest",
        max_nodes_per_domain: int = 0,
        additional_namespaces: tuple[str, ...] = (),
        log_verbosity: int = 0,
    ):
        self._kube = kube
        self._ns = driver_namespace
        self._max_nodes = max_nodes_per_domain
        self.daemonsets = MultiNamespaceDaemonSetManager(
            kube,
            driver_namespace,
            additional_namespaces=additional_namespaces,
            image=image,
            log_verbosity=log_verbosity,
        )
        self.daemon_rcts = DaemonResourceClaimTemplateManager(kube, driver_namespace)
        self.workload_rcts = WorkloadResourceClaimTemplateManager(kube)
        self.nodes = NodeManager(kube, self.cd_exists)
        self._cd_informer = None
        self._clique_informer = None
        self._pod_informer = None

    def use_informers(self, cd_informer, clique_informer, pod_informer=None) -> None:
        """Route existence checks, clique aggregation, and non-fabric pod
        membership through informer caches instead of per-call LISTs (the
        reference's uid-indexed informer + mutation cache,
        computedomain.go:117-125, and the daemonsetpods.go pod informer).
        Reads fall back to the API until each informer has synced."""
        cd_informer.add_index("uid", lambda o: o.get("metadata", {}).get("uid"))
        clique_informer.add_index(
            "cdUID", lambda o: o.get("spec", {}).get("computeDomainUID")
        )
        self._cd_informer = cd_informer
        self._clique_informer = clique_informer
        if pod_informer is not None:
            pod_informer.add_index(
                "cdUID",
                lambda o: o.get("metadata", {}).get("labels", {}).get(CD_UID_LABEL),
            )
            self._pod_informer = pod_informer

    # ------------------------------------------------------------- helpers

    def cd_exists(self, uid: str) -> bool:
        inf = self._cd_informer
        if inf is not None and inf.has_synced:
            return bool(inf.by_index("uid", uid))
        for item in self._kube.list(gvr.COMPUTE_DOMAINS).get("items", []):
            if item["metadata"]["uid"] == uid:
                return True
        return False

    def _cliques_for(self, cd_uid: str) -> list[dict]:
        inf = self._clique_informer
        if inf is not None and inf.has_synced:
            return inf.by_index("cdUID", cd_uid)
        return [
            c
            for c in self._kube.list(gvr.COMPUTE_DOMAIN_CLIQUES, self._ns).get(
                "items", []
            )
            if c.get("spec", {}).get("computeDomainUID") == cd_uid
        ]

    def get(self, namespace: str, name: str) -> Optional[dict]:
        try:
            return self._kube.get(gvr.COMPUTE_DOMAINS, name, namespace)
        except NotFound:
            return None

    # ----------------------------------------------------------- reconcile

    def reconcile(self, namespace: str, name: str) -> None:
        cd = self.get(namespace, name)
        if cd is None:
            return
        if cd["metadata"].get("deletionTimestamp"):
            self._teardown(cd)
            return
        if self._max_nodes and cd.get("spec", {}).get("numNodes", 0) > self._max_nodes:
            logger.error(
                "CD %s/%s requests %d nodes > max %d; not deploying",
                namespace, name, cd["spec"]["numNodes"], self._max_nodes,
            )
            return
        cd = self._ensure_finalizer(cd)
        rct = self.daemon_rcts.ensure(cd)
        self.daemonsets.ensure(cd, rct["metadata"]["name"])
        self.workload_rcts.ensure(cd)
        self.sync_status(cd)

    def _ensure_finalizer(self, cd: dict) -> dict:
        finalizers = cd["metadata"].setdefault("finalizers", [])
        if CD_FINALIZER in finalizers:
            return cd
        finalizers.append(CD_FINALIZER)
        try:
            return self._kube.update(gvr.COMPUTE_DOMAINS, cd, cd["metadata"]["namespace"])
        except Conflict as e:
            raise RetryLater(f"finalizer conflict: {e}") from e

    def _teardown(self, cd: dict) -> None:
        """Deletion choreography (computedomain.go:314-348).  Each phase
        issues deletes, then *verifies absence* before continuing; raises
        RetryLater until the chain completes, then drops the finalizer."""
        uid = cd["metadata"]["uid"]
        self.workload_rcts.remove(cd)
        if not self.workload_rcts.assert_removed(cd):
            raise RetryLater("workload RCT still present")
        self.daemonsets.remove(uid)
        if not self.daemonsets.assert_removed(uid):
            raise RetryLater("DaemonSet still present")
        self.daemon_rcts.remove(uid)
        if not self.daemon_rcts.assert_removed(uid):
            raise RetryLater("daemon RCT still present")
        self.nodes.remove_labels_for(uid)
        self._delete_cliques(uid)
        finalizers = [f for f in cd["metadata"].get("finalizers", []) if f != CD_FINALIZER]
        cd["metadata"]["finalizers"] = finalizers
        try:
            self._kube.update(gvr.COMPUTE_DOMAINS, cd, cd["metadata"]["namespace"])
        except (Conflict, NotFound):
            pass
        logger.info("ComputeDomain %s torn down", uid)

    def _delete_cliques(self, cd_uid: str) -> None:
        for clique in self._kube.list(gvr.COMPUTE_DOMAIN_CLIQUES, self._ns).get("items", []):
            if clique.get("spec", {}).get("computeDomainUID") == cd_uid:
                try:
                    self._kube.delete(
                        gvr.COMPUTE_DOMAIN_CLIQUES,
                        clique["metadata"]["name"],
                        self._ns,
                    )
                except NotFound:
                    pass

    # -------------------------------------------------------------- status

    def build_nodes_from_cliques(self, cd_uid: str) -> list[dict]:
        """Aggregate clique daemon entries into cd.status.nodes
        (buildNodesFromCliques, cdstatus.go:242)."""
        nodes: list[dict] = []
        for clique in self._cliques_for(cd_uid):
            for daemon in clique.get("status", {}).get("daemons", []):
                nodes.append(
                    {
                        "name": daemon.get("nodeName", ""),
                        "ipAddress": daemon.get("ipAddress", ""),
                        "cliqueID": daemon.get("cliqueID", ""),
                        "index": daemon.get("index", 0),
                        "status": daemon.get("status", COMPUTE_DOMAIN_STATUS_NOT_READY),
                    }
                )
        nodes.sort(key=lambda n: (n["cliqueID"], n["index"]))
        return nodes

    def build_non_fabric_nodes(self, cd_uid: str, fabric_nodes: set[str]) -> list[dict]:
        """Nodes whose daemon has no ICI clique — they never appear in any
        ComputeDomainClique CR, so membership comes from the per-CD
        DaemonSet pod itself: present + Ready pod = Ready node (the
        daemonsetpods.go informer path of the reference controller).
        Without this, a CD containing a non-fabric node could never reach
        Ready."""
        out: list[dict] = []
        inf = self._pod_informer
        if inf is not None and inf.has_synced:
            pods = inf.by_index("cdUID", cd_uid)
        else:
            try:
                pods = self._kube.list(
                    gvr.PODS, self._ns, label_selector=f"{CD_UID_LABEL}={cd_uid}"
                ).get("items", [])
            except Exception as e:  # noqa: BLE001
                # Publishing a shrunken node list on a transient list error
                # would flip the CD NOT_READY with no diagnostic; retry.
                raise RetryLater(f"listing CD daemon pods: {e}") from e
        for pod in pods:
            node = pod.get("spec", {}).get("nodeName", "")
            if not node or node in fabric_nodes:
                continue
            conditions = pod.get("status", {}).get("conditions", [])
            pod_ready = any(
                c.get("type") == "Ready" and c.get("status") == "True"
                for c in conditions
            )
            out.append(
                {
                    "name": node,
                    "ipAddress": pod.get("status", {}).get("podIP", ""),
                    "cliqueID": "",
                    "index": 0,
                    "status": COMPUTE_DOMAIN_STATUS_READY
                    if pod_ready
                    else COMPUTE_DOMAIN_STATUS_NOT_READY,
                }
            )
        out.sort(key=lambda n: n["name"])
        return out

    def calculate_global_status(self, cd: dict, nodes: list[dict]) -> str:
        """Ready iff enough nodes and all Ready (computedomain.go:251-265)."""
        num_nodes = cd.get("spec", {}).get("numNodes", 0)
        if num_nodes <= 0 or len(nodes) < num_nodes:
            return COMPUTE_DOMAIN_STATUS_NOT_READY
        if any(n["status"] != COMPUTE_DOMAIN_STATUS_READY for n in nodes):
            return COMPUTE_DOMAIN_STATUS_NOT_READY
        return COMPUTE_DOMAIN_STATUS_READY

    def sync_status(self, cd: dict) -> None:
        if featuregates.enabled(featuregates.COMPUTE_DOMAIN_CLIQUES):
            nodes = self.build_nodes_from_cliques(cd["metadata"]["uid"])
            seen = {n["name"] for n in nodes}
            nodes += self.build_non_fabric_nodes(cd["metadata"]["uid"], seen)
        else:
            # Legacy direct-status mode: the daemons own status.nodes
            # (cdstatus.go:55); the controller only recomputes the
            # aggregate without touching their entries.
            nodes = cd.get("status", {}).get("nodes", [])
        status = {
            "status": self.calculate_global_status(cd, nodes),
            "nodes": nodes,
        }
        if cd.get("status") == status:
            return
        cd = dict(cd)
        cd["status"] = status
        try:
            self._kube.update_status(
                gvr.COMPUTE_DOMAINS, cd, cd["metadata"]["namespace"]
            )
        except Conflict as e:
            raise RetryLater(f"status conflict: {e}") from e
        except NotFound:
            pass

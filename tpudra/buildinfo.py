"""Build-time version stamping (internal/info/version.go analog).

The reference stamps version/commit via -ldflags at `go build` time.  The
Python analog has three sources, in precedence order:

1. ``TPUDRA_VERSION`` / ``TPUDRA_GIT_COMMIT`` environment variables —
   dev overrides beat everything;
2. ``tpudra/_buildstamp.py`` — generated at image build time (see
   ``deployments/container/Dockerfile``), the ldflags equivalent;
3. package ``__version__`` with commit "unknown".
"""

from __future__ import annotations

import os

from tpudra import __version__


def _stamped() -> tuple[str, str]:
    try:
        from tpudra import _buildstamp  # type: ignore[attr-defined]

        return (
            getattr(_buildstamp, "VERSION", __version__),
            getattr(_buildstamp, "GIT_COMMIT", "unknown"),
        )
    except ImportError:
        return __version__, "unknown"


def version() -> str:
    stamped_version, _ = _stamped()
    return os.environ.get("TPUDRA_VERSION", stamped_version)


def git_commit() -> str:
    _, stamped_commit = _stamped()
    return os.environ.get("TPUDRA_GIT_COMMIT", stamped_commit)


def version_string() -> str:
    """One-line build identity, logged by every binary at startup
    (the reference's version metric / -version output)."""
    return f"tpudra {version()} (commit {git_commit()})"

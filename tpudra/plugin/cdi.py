"""CDI (Container Device Interface) spec management for TPU claims.

The analog of gpu-kubelet-plugin/cdi.go: for every prepared claim we write a
transient CDI spec file into the CDI root (/var/run/cdi), which the container
runtime (containerd with enable_cdi) resolves into device nodes, env vars, and
mounts inside the workload container (reference cdi.go:194-304).

TPU container wiring is env-first: libtpu discovers chips from /dev/accel* and
is *restricted* via env (no nvidia-cdi-hook binary needed, SURVEY.md §2 native
boundary table):

Chip claims (this plugin's chip_edits, :218) emit:

- TPU_VISIBLE_DEVICES=<host-local chip indices>   restrict to granted chips
- TPUDRA_CHIP_COORDS=<x,y,z;...>                  ICI coords of granted chips
- TPUDRA_CLIQUE_ID=<sliceUuid.partition>          fabric identity
- TPUDRA_GENERATION=<v4|v5e|v5p|v6e>              generation for the workload

ComputeDomain channel claims (cdplugin/state.py:_apply_channel_config) emit,
on top of the rendezvous env, the libtpu worker-bootstrap contract —
TPU_WORKER_ID, TPU_WORKER_HOSTNAMES, TPU_SKIP_MDS_QUERY, TPU_HOST_BOUNDS,
TPU_CHIPS_PER_HOST_BOUNDS (cdplugin/libtpuenv.py) — which libtpu itself
reads to form the multi-host ICI mesh.

So a JAX process in the container sees exactly the granted chips in
jax.devices(), with topology attributes for mesh construction.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from tpudra import metrics, storage, walwitness

CDI_VERSION = "0.6.0"

# Vendor/class for transient per-claim specs (reference cdi.go:
# "k8s.gpu.nvidia.com/claim").
CDI_VENDOR = "k8s.tpu.google.com"
CDI_CLASS = "claim"
CDI_KIND = f"{CDI_VENDOR}/{CDI_CLASS}"


@dataclass
class ContainerEdits:
    """A subset of CDI containerEdits that our devices need."""

    env: list[str] = field(default_factory=list)
    device_nodes: list[str] = field(default_factory=list)
    mounts: list[tuple[str, str]] = field(default_factory=list)  # (host, container)
    hooks: list[dict] = field(default_factory=list)

    def merge(self, other: "ContainerEdits") -> "ContainerEdits":
        return ContainerEdits(
            env=self.env + other.env,
            device_nodes=self.device_nodes + other.device_nodes,
            mounts=self.mounts + other.mounts,
            hooks=self.hooks + other.hooks,
        )

    def copy(self) -> "ContainerEdits":
        return ContainerEdits(
            env=list(self.env),
            device_nodes=list(self.device_nodes),
            mounts=list(self.mounts),
            hooks=list(self.hooks),
        )

    def to_cdi(self) -> dict:
        out: dict = {}
        if self.env:
            out["env"] = list(self.env)
        if self.device_nodes:
            out["deviceNodes"] = [{"path": p} for p in self.device_nodes]
        if self.mounts:
            out["mounts"] = [
                {
                    "hostPath": h,
                    "containerPath": c,
                    "options": ["rw", "nosuid", "nodev", "bind"],
                }
                for h, c in self.mounts
            ]
        if self.hooks:
            out["hooks"] = list(self.hooks)
        return out


class DeviceEditsCache:
    """Expiring per-device container-edits cache with startup warmup
    (the reference's 5-minute dev-spec cache, cdi.go:65,151).

    Today's builders are cheap string formatting, so this is a parity
    feature, not a measured win: it exists so that a future native backend
    whose ``dev_paths`` actually probes sysfs/devfs inherits the
    reference's cost model (bounded to once per device per TTL, first
    prepare pre-warmed) without a redesign.  Entries are copied in and out
    so callers can mutate freely.
    """

    DEFAULT_TTL = 300.0  # reference cdi.go:65

    def __init__(self, ttl: float = DEFAULT_TTL, clock: Callable[[], float] = time.monotonic):
        self._ttl = ttl
        self._clock = clock
        self._lock = threading.Lock()
        self._entries: dict[str, tuple[float, ContainerEdits]] = {}

    def get(self, device_name: str, build: Callable[[], ContainerEdits]) -> ContainerEdits:
        now = self._clock()
        with self._lock:
            hit = self._entries.get(device_name)
            if hit is not None and now - hit[0] <= self._ttl:
                return hit[1].copy()
        edits = build()
        with self._lock:
            self._entries[device_name] = (now, edits.copy())
        return edits

    def warmup(self, builders: dict[str, Callable[[], ContainerEdits]]) -> None:
        """Precompute edits for every known device (reference WarmupDevSpecCache,
        cdi.go:151)."""
        now = self._clock()
        built = {name: (now, build().copy()) for name, build in builders.items()}
        with self._lock:
            self._entries.update(built)



class CDIHandler:
    """Writes/removes per-claim transient CDI spec files
    (reference CDIHandler, cdi.go:50)."""

    def __init__(self, cdi_root: str, driver_root: str = "/"):
        self._cdi_root = cdi_root
        self._driver_root = driver_root.rstrip("/") or "/"
        os.makedirs(cdi_root, exist_ok=True)

    # -- naming -------------------------------------------------------------

    @staticmethod
    def claim_device_name(claim_uid: str, device_name: str) -> str:
        return f"{claim_uid}-{device_name}"

    @staticmethod
    def qualified_device_id(claim_uid: str, device_name: str) -> str:
        """The CDI device ID returned to kubelet (reference cdi.go:321)."""
        return f"{CDI_KIND}={CDIHandler.claim_device_name(claim_uid, device_name)}"

    def spec_path(self, claim_uid: str) -> str:
        return os.path.join(self._cdi_root, f"{CDI_VENDOR}-claim_{claim_uid}.json")

    def host_path(self, path: str) -> str:
        """Translate a device path for a containerized driver root
        (reference driver-root transform, cdi.go/cdioptions.go)."""
        if self._driver_root == "/":
            return path
        return self._driver_root + path

    # -- spec files ---------------------------------------------------------

    def create_claim_spec_file(
        self,
        claim_uid: str,
        device_edits: dict[str, ContainerEdits],
        common_edits: Optional[ContainerEdits] = None,
    ) -> list[str]:
        """Write the transient spec for a claim; returns qualified CDI IDs.

        ``device_edits`` maps device name → its edits; ``common_edits`` apply
        to every container consuming any device of the claim (claim-wide env
        like the clique ID; reference cdi.go:194-304).
        """
        walwitness.note_effect("cdi:spec-write")
        t0 = time.monotonic()
        devices = []
        ids = []
        for device_name, edits in device_edits.items():
            devices.append(
                {
                    "name": self.claim_device_name(claim_uid, device_name),
                    "containerEdits": edits.to_cdi(),
                }
            )
            ids.append(self.qualified_device_id(claim_uid, device_name))
        spec = {
            "cdiVersion": CDI_VERSION,
            "kind": CDI_KIND,
            "devices": devices,
        }
        if common_edits is not None:
            spec["containerEdits"] = common_edits.to_cdi()
        # Durable atomic write through the storage seam: tmp fsync →
        # rename → directory fsync.  The pre-seam version renamed an
        # UNSYNCED tmp file with no directory fsync, so a crash after an
        # acknowledged prepare could lose or tear the grant's spec — the
        # container runtime would then fail (or mis-wire) a pod whose
        # claim the checkpoint says is PrepareCompleted.  Fsyncs are
        # counted under site="cdi" (tpudra_storage_fsyncs_total) and
        # pinned by test_cdi_spec_write_is_durable.
        storage.atomic_replace(
            self.spec_path(claim_uid),
            json.dumps(spec, indent=2).encode(),
            site="cdi",
            tmp_path=self.spec_path(claim_uid) + ".tmp",
        )
        metrics.observe_phase(metrics.PHASE_CDI_WRITE, time.monotonic() - t0)
        return ids

    def delete_claim_spec_file(self, claim_uid: str) -> None:
        try:
            os.unlink(self.spec_path(claim_uid))
        except FileNotFoundError:
            pass

    def read_claim_spec(self, claim_uid: str) -> Optional[dict]:
        try:
            with open(self.spec_path(claim_uid)) as f:
                return json.load(f)
        except FileNotFoundError:
            return None

    def list_claim_uids(self) -> list[str]:
        """Claim UIDs that currently have spec files (startup GC input)."""
        prefix = f"{CDI_VENDOR}-claim_"
        out = []
        for name in os.listdir(self._cdi_root):
            if name.startswith(prefix) and name.endswith(".json"):
                out.append(name[len(prefix) : -len(".json")])
        return out


def chip_edits(chips: list, driver_root_transform=None) -> ContainerEdits:
    """Container edits granting a set of TpuChip objects: device nodes plus
    the env that restricts libtpu/JAX to exactly those chips."""
    transform = driver_root_transform or (lambda p: p)
    indices = sorted(c.index for c in chips)
    coords = [c.coords for c in sorted(chips, key=lambda c: c.index)]
    edits = ContainerEdits(
        env=[
            "TPU_VISIBLE_DEVICES=" + ",".join(str(i) for i in indices),
            "TPUDRA_CHIP_COORDS=" + ";".join(",".join(map(str, xyz)) for xyz in coords),
        ],
        device_nodes=[transform(p) for c in chips for p in c.dev_paths()],
    )
    if chips:
        edits.env.append(f"TPUDRA_CLIQUE_ID={chips[0].clique_id}")
        edits.env.append(f"TPUDRA_GENERATION={chips[0].generation}")
    return edits

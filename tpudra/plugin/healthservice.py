"""v1alpha1.DRAResourceHealth: kubelet-facing device-health streaming.

Beyond-reference: the official k8s helper registers this gRPC service on the
plugin socket when the plugin implements it (vendored
kubeletplugin/draplugin.go:623-663 — service detection at :624 appends
``v1alpha1.DRAResourceHealth`` to supported services, registration at
:660-663), but the reference driver never implements it.  We already run the
health monitor that republishes ResourceSlices without failed silicon
(plugin/driver.py:256-294); this module streams the same truth to kubelet so
pods using an affected device get a ResourceHealthStatus signal instead of
silently computing on a sick chip.

Contract (protos/dra_health_v1alpha1.proto, pinned against the official file
by tests/test_proto_conformance.py): every ``NodeWatchResourcesResponse`` is
a COMPLETE snapshot of the driver's devices — kubelet reconciles against its
cache and ages devices missing from the snapshot to Unknown after a timeout,
so the stream also re-sends periodically as a keepalive.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass
from typing import Callable, Iterator

import grpc

from tpudra.drapb import dra_health_v1alpha1_pb2 as healthpb

logger = logging.getLogger(__name__)

HEALTH_SERVICE = "v1alpha1.DRAResourceHealth"

#: Re-send the full snapshot at least this often so kubelet's staleness
#: timeout never fires while the stream is healthy.
DEFAULT_KEEPALIVE_S = 60.0

#: Flap-coalescing window: after a notify wakes a stream, trailing
#: notifies inside this window ride the same snapshot — a chip taking its
#: partitions down one event at a time (or a tight healthy→unhealthy
#: cascade) costs kubelet ONE reconcile, not one per event.  Mirrors the
#: slice publisher's debounce (driver.publish_debounce_s).
DEFAULT_COALESCE_S = 0.05


@dataclass(frozen=True)
class DeviceHealthInfo:
    """One device's health as the snapshot provider reports it."""

    pool_name: str
    device_name: str
    healthy: bool
    #: Unix seconds when the plugin last (re)determined this status.
    last_updated: int


# Returns the complete current device-health snapshot.
SnapshotFn = Callable[[], list[DeviceHealthInfo]]


class HealthBroadcaster:
    """Fans one health-snapshot source out to any number of kubelet streams.

    ``notify()`` wakes every open stream to re-read the snapshot; each stream
    additionally re-sends on ``keepalive_s`` idle so kubelet's reconcile
    cache never ages our devices to Unknown.  Streams exit when the client
    hangs up or ``stop()`` is called (server shutdown).
    """

    def __init__(
        self,
        snapshot: SnapshotFn,
        keepalive_s: float = DEFAULT_KEEPALIVE_S,
        coalesce_s: float = DEFAULT_COALESCE_S,
    ):
        self._snapshot = snapshot
        self._keepalive_s = keepalive_s
        self._coalesce_s = coalesce_s
        self._cond = threading.Condition()
        self._seq = 0
        self._stopped = False

    def notify(self) -> None:
        with self._cond:
            self._seq += 1
            self._cond.notify_all()

    def stop(self) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify_all()

    def _build_response(self) -> healthpb.NodeWatchResourcesResponse:
        resp = healthpb.NodeWatchResourcesResponse()
        for info in self._snapshot():
            d = resp.devices.add()
            d.device.pool_name = info.pool_name
            d.device.device_name = info.device_name
            d.health = healthpb.HEALTHY if info.healthy else healthpb.UNHEALTHY
            d.last_updated_time = info.last_updated
        return resp

    def watch(self, request, context) -> Iterator[healthpb.NodeWatchResourcesResponse]:
        """The NodeWatchResources handler: initial complete snapshot, then a
        fresh snapshot per notify burst (``coalesce_s`` window) and on
        keepalive expiry.  A stream opened after a plugin restart gets the
        restarted driver's CURRENT state in its first response — resume is
        a replay of truth, not of history (every response is a complete
        snapshot by the proto contract)."""
        logger.info("kubelet opened a DRAResourceHealth watch")
        with self._cond:
            seen = self._seq
        yield self._build_response()
        while context.is_active():
            with self._cond:
                if self._stopped:
                    return
                if self._seq == seen:
                    self._cond.wait(timeout=self._keepalive_s)
                if self._stopped:
                    return
                notified = self._seq != seen
            if notified and self._coalesce_s > 0:
                # Coalescing window, outside the condition: trailing flaps
                # land in _seq and are absorbed by the re-read below.
                time.sleep(self._coalesce_s)
            with self._cond:
                if self._stopped:
                    return
                seen = self._seq
            if not context.is_active():
                return
            yield self._build_response()

    def handler(self) -> grpc.GenericRpcHandler:
        return grpc.method_handlers_generic_handler(
            HEALTH_SERVICE,
            {
                "NodeWatchResources": grpc.unary_stream_rpc_method_handler(
                    self.watch,
                    request_deserializer=healthpb.NodeWatchResourcesRequest.FromString,
                    response_serializer=(
                        healthpb.NodeWatchResourcesResponse.SerializeToString
                    ),
                )
            },
        )


class HealthWatchClient:
    """The kubelet side of the stream (tests, e2e, bench)."""

    def __init__(self, path: str):
        import os

        self._channel = grpc.insecure_channel("unix:" + os.path.abspath(path))

    def watch(self, timeout: float | None = None) -> Iterator[dict]:
        """Yields snapshots as {device_name: {"healthy": bool, "pool": str,
        "ts": int}} dicts; raises grpc.RpcError on stream errors."""
        rpc = self._channel.unary_stream(
            f"/{HEALTH_SERVICE}/NodeWatchResources",
            request_serializer=healthpb.NodeWatchResourcesRequest.SerializeToString,
            response_deserializer=healthpb.NodeWatchResourcesResponse.FromString,
        )
        for resp in rpc(healthpb.NodeWatchResourcesRequest(), timeout=timeout):
            yield {
                d.device.device_name: {
                    "healthy": d.health == healthpb.HEALTHY,
                    "pool": d.device.pool_name,
                    "ts": d.last_updated_time,
                }
                for d in resp.devices
            }

    def close(self) -> None:
        self._channel.close()


def snapshot_from_driver_state(
    allocatable: Callable[[], dict],
    unhealthy: Callable[[], set[str]],
    changed_at: Callable[[], dict],
    start_ts: int,
    pool: str,
) -> SnapshotFn:
    """Builds the Driver's snapshot function: every allocatable device,
    HEALTHY unless the health monitor marked it, timestamped with the last
    status-change time (startup time until a first event)."""

    def snapshot() -> list[DeviceHealthInfo]:
        bad = unhealthy()
        stamps = changed_at()
        return [
            DeviceHealthInfo(
                pool_name=pool,
                device_name=name,
                healthy=name not in bad,
                last_updated=int(stamps.get(name, start_ts)),
            )
            for name in sorted(allocatable())
        ]

    return snapshot

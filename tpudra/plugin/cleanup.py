"""Stale-claim garbage collection.

The analog of gpu-kubelet-plugin/cleanup.go: kubelet can die between our
Prepare and its own bookkeeping, leaving claims checkpointed here that no
longer exist (or were re-created with a new UID) in the API server.  On
startup and every ``period`` seconds, every checkpointed claim is validated by
name+UID against the API server; stale ones are unprepared
(reference cleanup.go:41-213, 10-minute period).

Clock discipline (tpudra/clock.py): every time-based decision here runs on
the MONOTONIC clock through the injectable ``Clock`` seam — staleness is
decided by apiserver evidence (NotFound / UID mismatch / terminating
without allocation), never by subtracting wall-clock timestamps, so an NTP
step of ±minutes (the chaos soak's ``clock_skew`` fault) can neither
trigger a premature unprepare nor defer GC forever.  The optional
``stale_grace`` requires a claim to be *continuously* observed stale for
that many monotonic seconds before teardown — a hedge against acting on a
single observation during an apiserver wobble (a relist window where a GET
can race a delete-and-recreate), measured by this process's own
observation time (``MonotonicAger``), which wall skew cannot touch.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Optional

from tpudra.clock import Clock, MonotonicAger, SYSTEM
from tpudra.kube import gvr
from tpudra.kube.client import KubeAPI
from tpudra.kube.errors import NotFound
from tpudra.plugin.device_state import DeviceState

logger = logging.getLogger(__name__)

DEFAULT_PERIOD = 600.0


class CheckpointCleanupManager:
    def __init__(
        self,
        kube: KubeAPI,
        state: DeviceState,
        period: float = DEFAULT_PERIOD,
        unprepare: Optional[Callable[[str], None]] = None,
        clock: Optional[Clock] = None,
        stale_grace: float = 0.0,
    ):
        self._kube = kube
        self._state = state
        self._period = period
        # Both drivers pass a serialized unprepare: the TPU plugin its
        # per-claim-uid-locked one (a GC teardown must not interleave with
        # a kubelet retry of the same uid at the effects phase), the
        # cdplugin its node-locked one (the post-RMW label removal must not
        # interleave with a concurrent channel prepare's labeling).  The
        # bare state.unprepare default exists for tests and simple callers.
        self._unprepare = unprepare if unprepare is not None else state.unprepare
        self._clock = clock if clock is not None else SYSTEM
        # > 0: a claim must be seen stale on passes spanning >= this many
        # MONOTONIC seconds before it is unprepared.  0 (the default, and
        # the reference driver's behavior) acts on the first validated
        # observation — the validation itself is apiserver evidence, not
        # time math, so immediate action is sound; the grace exists for
        # operators who want two-pass confirmation under apiserver churn.
        self._stale_grace = stale_grace
        self._stale_ager = MonotonicAger(self._clock)
        self._thread: threading.Thread | None = None

    def start(self, stop: threading.Event) -> None:
        self._thread = threading.Thread(
            target=self._run, args=(stop,), daemon=True, name="checkpoint-cleanup"
        )
        self._thread.start()

    def _run(self, stop: threading.Event) -> None:
        while not stop.is_set():
            try:
                self.cleanup_once()
            except Exception:  # noqa: BLE001 — periodic GC must survive
                logger.exception("checkpoint cleanup pass failed")
            stop.wait(self._period)

    # tpudra-wal: recovers=claim the periodic GC pass converges claim records whose owner died out from under us — each stale record is unprepared through the plugin's own rollback path
    def cleanup_once(self) -> int:
        """One validation pass; returns number of stale claims unprepared."""
        stale = 0
        claims = self._state.prepared_claim_uids()
        for uid, (namespace, name, status) in claims.items():
            if not self._is_stale(uid, namespace, name):
                # Valid again (or unvalidatable this pass): any staleness
                # observation restarts from zero.
                self._stale_ager.forget(uid)
                continue
            age = self._stale_ager.age(uid, ("stale", namespace, name))
            if age < self._stale_grace:
                logger.info(
                    "claim %s/%s:%s stale for %.1fs (< %.1fs grace): "
                    "deferring unprepare to a later pass",
                    namespace, name, uid, age, self._stale_grace,
                )
                continue
            logger.info(
                "unpreparing stale claim %s/%s:%s (status=%s)",
                namespace, name, uid, status,
            )
            self._unprepare(uid)
            self._stale_ager.forget(uid)
            stale += 1
        # Claims that left the checkpoint between passes (a clean kubelet
        # unprepare) must not pin ager entries forever.
        self._stale_ager.prune(claims.keys())
        return stale

    def _is_stale(self, uid: str, namespace: str, name: str) -> bool:
        if not namespace or not name:
            # Pre-V2 checkpoint entries lack identity; leave them for manual
            # cleanup rather than guessing (reference skips those too).
            return False
        try:
            claim = self._kube.get(gvr.RESOURCE_CLAIMS, name, namespace)
        except NotFound:
            return True
        except Exception as e:  # noqa: BLE001 — apiserver blip: do not GC
            logger.warning("cannot validate claim %s/%s: %s", namespace, name, e)
            return False
        if claim.get("metadata", {}).get("uid") != uid:
            return True  # same name, different object
        if claim.get("metadata", {}).get("deletionTimestamp") and not claim.get(
            "status", {}
        ).get("allocation"):
            return True  # deallocated and terminating
        return False

"""Per-partition checkpoint records: the partition lifecycle's WAL truth.

The fractional-chip subsystem (docs/partitioning.md) makes dynamically
created TensorCore partitions first-class *journaled* state, not just an
attribute buried in a claim record.  Every dynamic partition a prepare is
about to carve gets its own record in the plugin checkpoint — keyed
``partition/<canonical-device-name>`` in the same ``prepared_claims`` map
the journal already knows how to delta-encode (the gang subsystem's
``gang/<id>`` idiom: one WAL upsert per record, ~70 B through the PR 5
journal) — and the record's phase tracks the hardware:

======================  =====================================================
phase                   meaning
======================  =====================================================
``Creating``            journaled intent: the bind's effects phase is about
                        to call ``devicelib.create_partition`` (the
                        ``mid-partition-create`` crash window sits between
                        the journal append and the hardware mutation)
``Live``                the partition exists and is owned by the claim in
                        ``claimUID``; ``partitionUUID`` is the hardware id
``Destroying``          journaled intent to destroy: unprepare's begin phase
                        flips the record before the effects phase deletes
                        the hardware (the ``mid-partition-destroy`` window)
======================  =====================================================

Recovery is a two-sided sweep (``DeviceState.destroy_unknown_partitions``):
live partitions unexplained by checkpoint truth are destroyed, and records
unexplained by live hardware + live claims are dropped — the partition-leak
invariant the chaos soak holds in quiet windows (no live partition without
a record, no ``Live`` record without a partition).

Records ride the claim map but are NOT claims: every claim-scan in the
plugin (stale-claim GC, overlap validation, health escalation) must skip
``is_partition_record`` uids — they have no namespace/name, no devices,
and no apiserver object to validate against.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from tpudra.devicelib import PartitionSpec
from tpudra.plugin.checkpoint import (
    PREPARE_COMPLETED,
    PREPARE_STARTED,
    Checkpoint,
    PreparedClaim,
    PreparedDeviceGroup,
)

PARTITION_RECORD_PREFIX = "partition/"

PHASE_CREATING = "Creating"
PHASE_LIVE = "Live"
PHASE_DESTROYING = "Destroying"


def record_uid(partition_name: str) -> str:
    """Checkpoint key for one partition placement: the canonical device
    name is unique per placement (overlap validation guarantees at most
    one claim ever plans it), so create/destroy cycles reuse the key and
    an idempotent retry's re-upsert emits zero delta records."""
    return PARTITION_RECORD_PREFIX + partition_name


def is_partition_record(uid: str) -> bool:
    return uid.startswith(PARTITION_RECORD_PREFIX)


@dataclass
class PartitionRecord:
    """Decoded view of one ``partition/<name>`` checkpoint record."""

    name: str  # canonical device name, e.g. tpu-0-part-1c.4hbm-0-0
    phase: str
    claim_uid: str
    spec: Optional[PartitionSpec] = None
    partition_uuid: str = ""

    @property
    def uid(self) -> str:
        return record_uid(self.name)


def make_record(
    name: str,
    phase: str,
    claim_uid: str,
    spec: PartitionSpec,
    partition_uuid: str = "",
) -> PreparedClaim:
    """Encode a partition record as a PreparedClaim-shaped checkpoint
    entry (the v2 string schema: everything in configState).  The status
    field mirrors the phase — ``Live`` records read as completed, the
    transient phases as started — so a pre-partition driver's generic
    status scan degrades sanely instead of misreading them."""
    from tpudra.plugin.device_state import _encode_specs

    config_state = {
        "partitionPhase": phase,
        "claimUID": claim_uid,
        "partitionSpec": _encode_specs([spec]),
    }
    if partition_uuid:
        config_state["partitionUUID"] = partition_uuid
    return PreparedClaim(
        uid=record_uid(name),
        status=PREPARE_COMPLETED if phase == PHASE_LIVE else PREPARE_STARTED,
        groups=[PreparedDeviceGroup(devices=[], config_state=config_state)],
    )


def parse_record(uid: str, claim: PreparedClaim) -> Optional[PartitionRecord]:
    """Decode one checkpoint entry; None when it is not a (well-formed)
    partition record — a malformed one is skipped loudly by the sweep,
    never a crash on the recovery path."""
    from tpudra.plugin.device_state import _decode_specs

    if not is_partition_record(uid) or not claim.groups:
        return None
    state = claim.groups[0].config_state
    phase = state.get("partitionPhase", "")
    if phase not in (PHASE_CREATING, PHASE_LIVE, PHASE_DESTROYING):
        return None
    try:
        specs = _decode_specs(state.get("partitionSpec", ""))
    except ValueError:
        specs = []  # garbled spec: the sweep still converges by uuid
    return PartitionRecord(
        name=uid[len(PARTITION_RECORD_PREFIX):],
        phase=phase,
        claim_uid=state.get("claimUID", ""),
        spec=specs[0] if specs else None,
        partition_uuid=state.get("partitionUUID", ""),
    )


def records_in(cp: Checkpoint) -> dict[str, PartitionRecord]:
    """All well-formed partition records of a checkpoint, by record uid."""
    out: dict[str, PartitionRecord] = {}
    for uid, claim in cp.prepared_claims.items():
        rec = parse_record(uid, claim)
        if rec is not None:
            out[uid] = rec
    return out

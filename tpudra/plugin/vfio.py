"""VFIO passthrough for TPU PCI functions.

The analog of gpu-kubelet-plugin/vfio-device.go: unbind the chip's PCI
function from the TPU driver and bind it to vfio-pci during Prepare (sysfs
``driver_override`` dance), reverse on Unprepare, and inject the
``/dev/vfio/<iommu_group>`` node for VM workloads.  The sysfs root is
injectable so the whole flow runs against a mock tree in CI.
"""

from __future__ import annotations

import logging
import os
import threading

from tpudra import walwitness
from tpudra.devicelib import TpuChip
from tpudra.plugin.cdi import ContainerEdits

logger = logging.getLogger(__name__)

VFIO_PCI = "vfio-pci"
TPU_DRIVER = "tpu"  # the in-kernel accel driver name


class VfioError(Exception):
    pass


class PerDeviceMutex:
    """Lazily-created mutex per PCI address (reference mutex.go:23
    PerGPUMutex): the sysfs unbind/override/bind dance below is a
    multi-write sequence with no kernel-side atomicity, so two in-process
    paths touching the SAME function (a prepare racing the health
    monitor's enumeration refresh, or unprepare racing a retried prepare)
    must serialize — while operations on different devices proceed
    concurrently."""

    def __init__(self):
        self._lock = threading.Lock()
        self._submutex: dict[str, threading.Lock] = {}

    def get(self, device: str) -> threading.Lock:
        with self._lock:
            if device not in self._submutex:
                # tpudra-lock: id=vfio.per-device family one mutex per PCI address, keyed in self._submutex
                self._submutex[device] = threading.Lock()
            return self._submutex[device]


per_device_lock = PerDeviceMutex()


class VfioManager:
    def __init__(self, sysfs_root: str = "/sys", dev_root: str = "/dev"):
        self._sysfs = sysfs_root
        self._dev = dev_root

    # -- paths --------------------------------------------------------------

    def _device_dir(self, pci_address: str) -> str:
        return os.path.join(self._sysfs, "bus/pci/devices", pci_address)

    def _driver_dir(self, driver: str) -> str:
        return os.path.join(self._sysfs, "bus/pci/drivers", driver)

    # -- validation ---------------------------------------------------------

    def validate_host(self) -> None:
        """IOMMU + vfio-pci module present (reference vfio-device.go:
        validates IOMMU enablement and vfio-pci availability)."""
        if not os.path.isdir(os.path.join(self._sysfs, "kernel/iommu_groups")) or not os.listdir(
            os.path.join(self._sysfs, "kernel/iommu_groups")
        ):
            raise VfioError("IOMMU is not enabled on this host")
        if not os.path.isdir(self._driver_dir(VFIO_PCI)):
            raise VfioError("vfio-pci driver is not loaded")

    # -- state --------------------------------------------------------------

    def current_driver(self, chip: TpuChip) -> str | None:
        link = os.path.join(self._device_dir(chip.pci_address), "driver")
        if not os.path.islink(link) and not os.path.isdir(link):
            return None
        return os.path.basename(os.path.realpath(link))

    # tpudra-lock: nonblocking sysfs attribute read — a bounded in-kernel store lookup, not I/O latency; serializing it under the device mutex is the point
    def iommu_group(self, chip: TpuChip) -> str:
        path = os.path.join(self._device_dir(chip.pci_address), "iommu_group")
        if os.path.islink(path) or os.path.isdir(path):
            return os.path.basename(os.path.realpath(path))
        # Mock trees store the group number as a plain file.
        try:
            with open(path) as f:
                return f.read().strip()
        except FileNotFoundError:
            raise VfioError(f"no iommu_group for {chip.pci_address}") from None

    # -- configure / unconfigure -------------------------------------------

    # tpudra-lock: nonblocking sysfs attribute store — the multi-write rebind dance is exactly what the per-device mutex serializes (reference PerGPUMutex), and each store is a bounded in-kernel write, not disk/network latency
    def _write(self, path: str, value: str) -> None:
        # tpudra-lint: disable=DURABLE-WRITE sysfs attribute store: a single in-kernel control write with nothing to fsync or rename — atomicity/durability are meaningless for it; the per-device mutex (not the storage seam) is the safety mechanism here
        with open(path, "w") as f:
            f.write(value)

    def configure(self, chip: TpuChip) -> str:
        """Rebind to vfio-pci; returns the iommu group
        (reference Configure, vfio-device.go:176-178 — incl. taking the
        device's mutex around the rebind sequence)."""
        walwitness.note_effect("vfio:configure")
        # tpudra-lock: id=vfio.per-device keyed per PCI address — rebinds of distinct chips never contend
        with per_device_lock.get(chip.pci_address):
            dev_dir = self._device_dir(chip.pci_address)
            if not os.path.isdir(dev_dir):
                raise VfioError(f"PCI device {chip.pci_address} not found")
            current = self.current_driver(chip)
            if current == VFIO_PCI:
                return self.iommu_group(chip)  # idempotent
            self._write(os.path.join(dev_dir, "driver_override"), VFIO_PCI)
            if current is not None:
                self._write(
                    os.path.join(self._driver_dir(current), "unbind"), chip.pci_address
                )
            self._write(os.path.join(self._driver_dir(VFIO_PCI), "bind"), chip.pci_address)
            logger.info("bound %s to vfio-pci", chip.pci_address)
            return self.iommu_group(chip)

    def unconfigure(self, chip: TpuChip) -> None:
        """Return the function to the TPU driver
        (reference Unconfigure, vfio-device.go:207-209)."""
        # tpudra-lock: id=vfio.per-device same per-PCI-address key as configure, so the two rebind directions serialize
        with per_device_lock.get(chip.pci_address):
            dev_dir = self._device_dir(chip.pci_address)
            if not os.path.isdir(dev_dir):
                return
            current = self.current_driver(chip)
            self._write(os.path.join(dev_dir, "driver_override"), "\n")
            if current == VFIO_PCI:
                self._write(os.path.join(self._driver_dir(VFIO_PCI), "unbind"), chip.pci_address)
            if os.path.isdir(self._driver_dir(TPU_DRIVER)):
                self._write(os.path.join(self._driver_dir(TPU_DRIVER), "bind"), chip.pci_address)
            logger.info("returned %s to the %s driver", chip.pci_address, TPU_DRIVER)

    def get_cdi_edits(self, chip: TpuChip, iommu_group: str) -> ContainerEdits:
        """Inject the VFIO group + control nodes
        (reference GetVfioCDIContainerEdits, vfio-device.go:286)."""
        return ContainerEdits(
            device_nodes=[
                os.path.join(self._dev, "vfio", iommu_group),
                os.path.join(self._dev, "vfio", "vfio"),
            ]
        )

"""ResourceSlice generation: flat and KEP-4815 partitionable forms.

The analog of gpu-kubelet-plugin/driver.go:402-554 + partitions.go:

- Flat form (pre-1.33 clusters, or DynamicPartitioning off): one pool per
  node carrying every allocatable device as an independent entry.
- Partitionable form (KEP-4815): each chip contributes a CounterSet with a
  ``tensorcores`` counter and one counter per HBM slice; the full-chip device
  consumes all of them and every abstract dynamic partition consumes its
  proportional share, giving the scheduler the arithmetic to co-allocate
  disjoint partitions of one chip and to refuse a partition once the full
  chip is taken (reference partitions.go:85-307).
- Split vs combined publication by k8s version: ≥1.35 servers accept devices
  and counter sets in separate slices of one pool; older servers need the
  combined single-slice form (reference driver.go:507-540).

Unhealthy devices are filtered out before publication — the republish path
for health events (reference driver.go:462-502).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from tpudra import TPU_DRIVER_NAME
from tpudra.devicelib import HBM_SLICES_PER_CHIP
from tpudra.plugin import allocatable as alloc
from tpudra.plugin.allocatable import AllocatableDevice


def counter_set_name(chip_index: int) -> str:
    return f"tpu-{chip_index}-counters"


def _hbm_slice_counter(i: int) -> str:
    return f"hbm-slice-{i}"


def chip_counters(chip) -> dict[str, dict]:
    """Full capacity of one chip as a counter map: all cores, every slice."""
    counters = {"tensorcores": {"value": str(chip.tensorcores)}}
    for i in range(HBM_SLICES_PER_CHIP):
        counters[_hbm_slice_counter(i)] = {"value": "1"}
    return counters


def device_consumed_counters(dev: AllocatableDevice) -> list[dict]:
    """What this device drains from its chip's CounterSet
    (PartConsumesCounters analog, partitions.go:96,263)."""
    chip = dev.chip
    if dev.is_partition:
        spec = dev.partition_spec
        cores, hbm_slices = alloc._profile_counts(spec.profile)
        counters = {"tensorcores": {"value": str(cores)}}
        for i in range(spec.hbm_start, spec.hbm_start + hbm_slices):
            counters[_hbm_slice_counter(i)] = {"value": "1"}
    else:
        counters = chip_counters(chip)
    return [{"counterSet": counter_set_name(chip.index), "counters": counters}]


#: Slice annotation carrying the count of devices withheld for HEALTH on
#: this node (sibling-withholds excluded).  Unhealthy silicon is absent
#: from the device list by design, which leaves consumers unable to tell
#: "small node" from "sick node"; the gang remediation's spare-node
#: selection (controller/gang.py) filters on this without having to know
#: every node's expected chip count.
SLICE_UNHEALTHY_ANNOTATION = "tpu.google.com/unhealthy-device-count"

#: Slice annotation flagging that this node's plugin is in STORAGE-DEGRADED
#: mode (its checkpoint cannot persist; bind work is being shed with a
#: typed retryable error — docs/bind-path.md "Storage fault contract").
#: Present with value "true" only while degraded; the controller's gang
#: placement (controller/gang.py published_slice_health) filters such
#: nodes out of spare selection, because a gang member bound there would
#: sit un-journaled behind the shed gate until the disk heals.
SLICE_STORAGE_DEGRADED_ANNOTATION = "tpu.google.com/storage-degraded"


@dataclass
class DriverResources:
    """One pool's worth of publication data for this node."""

    pool_name: str
    devices: list[dict] = field(default_factory=list)
    shared_counters: list[dict] = field(default_factory=list)
    partitionable: bool = False
    #: Devices withheld for health (not sibling visibility) — published as
    #: SLICE_UNHEALTHY_ANNOTATION on every built slice.
    unhealthy_count: int = 0
    #: Plugin checkpoint storage is degraded (binds shed) — published as
    #: SLICE_STORAGE_DEGRADED_ANNOTATION on every built slice when True.
    storage_degraded: bool = False


def generate_driver_resources(
    allocatable: dict[str, AllocatableDevice],
    unhealthy: set[str] | None = None,
    withheld: set[str] | None = None,
    partitionable: bool = False,
    node_name: str = "",
) -> DriverResources:
    """Build the node pool (GenerateDriverResources analog, driver.go:507).

    ``unhealthy`` holds canonical device names to withhold for health.  An
    unhealthy *chip* (or its vfio alias) also withholds every device sharing
    that silicon; an unhealthy *partition* withholds only itself, so healthy
    sibling partitions stay schedulable.  ``withheld`` names are dropped
    as-is (the bound-sibling set from passthrough prepares).
    """
    unhealthy = unhealthy or set()
    withheld = withheld or set()
    bad_chips = {
        allocatable[n].chip.index
        for n in unhealthy
        if n in allocatable and not allocatable[n].is_partition
    }
    res = DriverResources(
        pool_name=alloc.pool_name(node_name), partitionable=partitionable
    )
    seen_counter_chips: set[int] = set()
    for name in sorted(allocatable):
        dev = allocatable[name]
        if name in unhealthy or dev.chip.index in bad_chips:
            res.unhealthy_count += 1
            continue
        if name in withheld:
            continue
        entry = dev.to_resource_device()
        if partitionable:
            if dev.chip.index not in seen_counter_chips:
                seen_counter_chips.add(dev.chip.index)
                res.shared_counters.append(
                    {
                        "name": counter_set_name(dev.chip.index),
                        "counters": chip_counters(dev.chip),
                    }
                )
            entry["consumesCounters"] = device_consumed_counters(dev)
        res.devices.append(entry)
    return res


# -- ResourceSlice object assembly ------------------------------------------

MAX_DEVICES_PER_SLICE = 128


def build_resource_slices(
    res: DriverResources,
    node_name: str,
    k8s_minor: int = 35,
    generation: int = 1,
) -> list[dict]:
    """Render pool data into resource.k8s.io/v1 ResourceSlice objects.

    ≥1.35: counter sets ride in their own slice, devices chunked across
    further slices (the reference's "split" form, driver.go:513-527); older
    servers get one combined slice (driver.go:529-539).
    """
    pool = {
        "name": res.pool_name,
        "generation": generation,
        "resourceSliceCount": 1,
    }
    common_spec = {
        "driver": TPU_DRIVER_NAME,
        "nodeName": node_name,
        "pool": dict(pool),
    }

    slices: list[dict] = []

    annotations = {SLICE_UNHEALTHY_ANNOTATION: str(res.unhealthy_count)}
    if res.storage_degraded:
        # Presence-only: a healthy node publishes NO storage annotation,
        # so foreign tooling diffing slices sees degraded windows exactly.
        annotations[SLICE_STORAGE_DEGRADED_ANNOTATION] = "true"

    def add(name_suffix: str, spec_extra: dict) -> None:
        spec = {k: (dict(v) if isinstance(v, dict) else v) for k, v in common_spec.items()}
        spec.update(spec_extra)
        slices.append(
            {
                "apiVersion": "resource.k8s.io/v1",
                "kind": "ResourceSlice",
                "metadata": {
                    "name": f"{node_name}-{TPU_DRIVER_NAME}-{name_suffix}",
                    "annotations": dict(annotations),
                },
                "spec": spec,
            }
        )

    chunks = [
        res.devices[i : i + MAX_DEVICES_PER_SLICE]
        for i in range(0, len(res.devices), MAX_DEVICES_PER_SLICE)
    ] or [[]]
    split = res.partitionable and k8s_minor >= 35
    if split:
        add("counters", {"sharedCounters": res.shared_counters, "devices": []})
        for i, chunk in enumerate(chunks):
            add(f"devices-{i}", {"devices": chunk})
    else:
        # Combined form: counters (if any) ride the first device chunk; the
        # device list is still chunked to respect the 128-devices-per-slice
        # API cap (resource.k8s.io validation).
        for i, chunk in enumerate(chunks):
            spec_extra: dict = {"devices": chunk}
            if res.partitionable and i == 0:
                spec_extra["sharedCounters"] = res.shared_counters
            add(f"devices-{i}", spec_extra)

    for s in slices:
        s["spec"]["pool"]["resourceSliceCount"] = len(slices)
    return slices

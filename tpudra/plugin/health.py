"""Liveness healthcheck service.

The analog of gpu-kubelet-plugin/health.go:52-150: an HTTP endpoint (the
reference uses gRPC health v1; the contract — a kubelet liveness probe target —
is the same) that reports healthy only when the plugin's own sockets actually
answer:

- the registration socket responds to GetInfo with the right driver name, and
- the DRA service socket completes a no-op NodePrepareResources.

Probing our own sockets rather than returning a static 200 catches wedged
RPC threads, a deleted socket file, or a plugin that silently stopped serving.
"""

from __future__ import annotations

import http.server
import json
import logging
import threading
from typing import Optional

from tpudra.plugin.grpcserver import DRAClient, PluginSockets, RegistrationClient

logger = logging.getLogger(__name__)


class Healthcheck:
    def __init__(
        self,
        sockets: PluginSockets,
        port: int = 0,
        probe_timeout: float = 5.0,
        host: str = "0.0.0.0",
    ):
        """port 0 picks an ephemeral port (reference: healthcheck disabled
        with port < 0, main.go flag healthcheck-port).  Binds all
        interfaces by default: kubelet probes and Prometheus both hit the
        pod IP, not loopback."""
        self._sockets = sockets
        self._probe_timeout = probe_timeout
        self._server: Optional[http.server.ThreadingHTTPServer] = None
        self._port = port
        self._host = host

    # -- probe logic --------------------------------------------------------

    def check(self) -> tuple[bool, str]:
        try:
            reg = RegistrationClient(
                self._sockets.registration_socket_path, timeout=self._probe_timeout
            )
            try:
                info = reg.get_info()
            finally:
                reg.close()
            if info.get("name") != self._sockets.driver_name:
                return False, f"registration socket serves {info.get('name')!r}"
        except Exception as e:  # noqa: BLE001 — any probe failure is unhealthy
            return False, f"registration socket: {e}"
        try:
            dra = DRAClient(self._sockets.dra_socket_path, timeout=self._probe_timeout)
            try:
                dra.prepare([])  # no-op batch, same as reference health.go:122
            finally:
                dra.close()
        except Exception as e:  # noqa: BLE001
            return False, f"DRA socket: {e}"
        return True, "ok"

    # -- HTTP surface -------------------------------------------------------

    @property
    def port(self) -> int:
        return self._port

    def start(self) -> None:
        check = self.check

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 — http.server API
                if self.path in ("/metrics", "/debug/stacks", "/debug/traces"):
                    # The plugins mount the observability routes on this
                    # listener instead of running a second HTTP server
                    # (controller equivalent: --http-endpoint).
                    from tpudra.metrics import handle_debug_request

                    handle_debug_request(self)
                    return
                if self.path not in ("/healthz", "/readyz"):
                    self.send_error(404)
                    return
                healthy, detail = check()
                body = json.dumps({"healthy": healthy, "detail": detail}).encode()
                self.send_response(200 if healthy else 503)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):  # noqa: D102
                logger.debug("healthcheck: " + fmt, *args)

        self._server = http.server.ThreadingHTTPServer((self._host, self._port), Handler)
        self._port = self._server.server_address[1]
        threading.Thread(
            target=self._server.serve_forever, daemon=True, name="healthcheck"
        ).start()
        logger.info("healthcheck serving on %s:%d", self._host, self._port)

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None

"""Append-only checkpoint journal (WAL): CRC framing + file plumbing.

The delta layer under ``CheckpointManager`` (checkpoint.py): instead of
re-encoding and fsyncing the whole dual-version snapshot on every mutation,
the manager appends CRC-framed JSON delta records (claim upsert / drop /
status transition) to ``checkpoint.wal`` and folds them back into the
snapshot only at compaction.  This module owns the byte-level concerns —
frame encode/decode with torn-tail detection, the append fd, truncation,
directory fsync — and knows nothing about checkpoint semantics (record
dicts go in, record dicts come out), so there is no import cycle with
checkpoint.py.

Frame format, chosen for torn-write detection rather than compactness::

    <u32 little-endian payload length> <u32 crc32(payload)> <payload bytes>

A record interrupted by a crash (short header, short payload, CRC or JSON
mismatch) ends the readable journal: ``decode_records`` returns everything
before it plus the byte offset of the last good frame, and the caller
truncates/ignores the tail.  Every complete frame written before the torn
one was fsynced by an earlier group commit, so nothing acknowledged is
lost.

Every write-side op goes through the storage seam (tpudra/storage.py) so
a fault plan can fail this file's writes, fsyncs, and truncations per
call site.  **Fail-stop contract (fsyncgate semantics):** a failed write
or fsync POISONS the append fd — the kernel may have dropped the dirty
pages and cleared the error, so retrying fsync on the same fd and
assuming the bytes landed would acknowledge a mutation the disk never
saw.  ``append_locked`` instead closes the fd, rolls the file back to the
pre-append frame boundary on a fresh fd (best-effort — if the rollback
itself fails, the CRC framing plus the next commit's good-frame repair
make the leftover tail harmless), and raises; the caller fails the whole
un-acknowledged batch and re-establishes state from known-durable bytes.

Concurrency contract: ``append_locked``/``truncate_locked``/
``_ensure_fd_locked`` require the caller to hold the checkpoint flock
(``cp.lock``) — they are the write half.  ``read_bytes``/``stat_key`` are
lock-free and may observe a concurrent append's partial frame; the reader
(checkpoint.py) distinguishes that from a real torn tail by re-statting.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import struct
import zlib
from typing import Optional

from tpudra import storage

logger = logging.getLogger(__name__)

_HEADER = struct.Struct("<II")

#: Sanity bound on one frame: a garbage length field must not make the
#: decoder treat megabytes of unrelated bytes as a pending record.
MAX_RECORD_BYTES = 1 << 22


def fsync_dir(path: str) -> None:
    """Directory fsync through the storage seam — kept under its original
    name because callers across the tree (checkpoint, tests) grew up on
    ``journal.fsync_dir``; the implementation and its rationale live in
    :func:`tpudra.storage.fsync_dir`."""
    storage.fsync_dir(path)


def encode_frame(payload: bytes) -> bytes:
    return _HEADER.pack(len(payload), zlib.crc32(payload)) + payload


def encode_record(record: dict) -> bytes:
    return encode_frame(json.dumps(record, sort_keys=True).encode())


def decode_records(data: bytes) -> tuple[list[dict], int, bool]:
    """(records, good_bytes, torn) — JSON records decoded frame by frame,
    stopping at the first incomplete/corrupt frame.  ``good_bytes`` is the
    offset just past the last good frame (a valid truncation/append
    point); ``torn`` is True when trailing bytes were dropped."""
    records: list[dict] = []
    pos, n = 0, len(data)
    while pos < n:
        if n - pos < _HEADER.size:
            return records, pos, True
        length, crc = _HEADER.unpack_from(data, pos)
        start = pos + _HEADER.size
        if length > MAX_RECORD_BYTES or start + length > n:
            return records, pos, True
        payload = data[start : start + length]
        if zlib.crc32(payload) != crc:
            return records, pos, True
        try:
            record = json.loads(payload)
        except ValueError:
            return records, pos, True
        if not isinstance(record, dict):
            return records, pos, True
        records.append(record)
        pos = start + length
    return records, pos, False


class Journal:
    """The ``checkpoint.wal`` file: lock-free reads, flock-guarded writes.

    The append fd is kept open across commits (O_APPEND, so every write
    lands at the current end) and re-opened when the path's inode no
    longer matches — a test tearing down the directory, never normal
    operation: compaction truncates in place (``ftruncate``), it does not
    replace the file, which is what keeps "snapshot stat unchanged ⇒
    journal grew append-only" true for the incremental readers."""

    def __init__(self, path: str):
        self._path = path
        self._fd: Optional[int] = None

    @property
    def path(self) -> str:
        return self._path

    def stat_key(self) -> Optional[tuple[int, int, int]]:
        """(mtime_ns, size, inode) of the journal, or None when absent."""
        try:
            st = os.stat(self._path)
        except FileNotFoundError:
            return None
        return (st.st_mtime_ns, st.st_size, st.st_ino)

    def read_bytes(self, offset: int = 0) -> bytes:
        """The journal's bytes from ``offset`` (lock-free; b"" if absent)."""
        try:
            with open(self._path, "rb") as f:
                if offset:
                    f.seek(offset)
                return f.read()
        except FileNotFoundError:
            return b""

    def _ensure_fd_locked(self) -> tuple[int, bool]:
        """(fd, created) — the append fd, re-opened if the path's inode
        changed under us.  Caller holds the checkpoint flock."""
        fd = self._fd
        if fd is not None:
            try:
                if os.fstat(fd).st_ino == os.stat(self._path).st_ino:
                    return fd, False
            except FileNotFoundError:
                # The file vanished (test teardown): fall through and
                # recreate on a fresh fd.
                ...
            storage.close(fd)
            self._fd = None
        parent = os.path.dirname(self._path) or "."
        os.makedirs(parent, exist_ok=True)
        created = not os.path.exists(self._path)
        fd = storage.open(
            self._path, os.O_CREAT | os.O_RDWR | os.O_APPEND, 0o600
        )
        self._fd = fd
        return fd, created

    def append_locked(self, payloads: list[bytes]) -> tuple[int, bool]:
        """Append pre-encoded frames as ONE write + ONE fsync (the group
        commit's whole durability cost); returns (bytes written, directory
        fsynced).  A first append also fsyncs the directory so the new
        file itself survives — reported to the caller so the fsync
        accounting (tpudra_checkpoint_fsyncs_total) stays truthful.

        Any OSError on the way — short write, ENOSPC mid-append, a failed
        fsync — poisons the fd (module docstring): the un-acknowledged
        bytes are rolled back to the pre-append frame boundary and the
        error propagates, so the caller never fsync-retries dirty pages
        whose fate the kernel no longer guarantees."""
        buf = b"".join(payloads)
        fd, created = self._ensure_fd_locked()
        pre_size = os.fstat(fd).st_size
        try:
            # Loop out short writes (ENOSPC-adjacent / interrupted):
            # fsyncing and acknowledging a partially-written frame would
            # hand the next replay a "torn tail" for a mutation the caller
            # was told is durable.
            view = memoryview(buf)
            while view:
                written = storage.write(fd, view)
                if written <= 0:
                    raise OSError(
                        f"short write appending {len(view)} byte(s) to "
                        f"{self._path}"
                    )
                view = view[written:]
            storage.fsync(fd)
            if created:
                storage.fsync_dir(os.path.dirname(self._path) or ".")
        except OSError:
            self._poison_locked(pre_size)
            raise
        return len(buf), created

    def _poison_locked(self, pre_size: int) -> None:
        """Fail-stop after a failed append: close the (possibly-lying) fd
        and cut the file back to the pre-append boundary on a FRESH fd, so
        bytes whose mutation was reported as failed cannot become durable
        via a later commit's fsync.  Best-effort: when the rollback itself
        fails (the disk is still refusing work), the leftover tail is
        either a partial frame (dropped by CRC at every replay) or whole
        frames that the next successful commit's good-frame repair pass —
        or the heal-time compaction — truncates."""
        fd, self._fd = self._fd, None
        if fd is not None:
            with contextlib.suppress(OSError):
                storage.close(fd)
        try:
            nfd = storage.open(self._path, os.O_RDWR)
            try:
                if os.fstat(nfd).st_size > pre_size:
                    storage.ftruncate(nfd, pre_size)
            finally:
                with contextlib.suppress(OSError):
                    storage.close(nfd)
        except OSError:
            logger.warning(
                "journal poison rollback to offset %d failed; the "
                "un-acknowledged tail is left for CRC/replay-time repair",
                pre_size,
            )

    def truncate_locked(self, size: int = 0) -> None:
        """Cut the journal to ``size`` bytes: 0 after a compaction folded
        it into the snapshot, or a good-frame boundary when repairing a
        torn tail.  No fsync — a crash that resurrects the dropped bytes
        re-drops them at the next replay (truncation is convergent)."""
        fd, _ = self._ensure_fd_locked()
        storage.ftruncate(fd, size)

    def close(self) -> None:
        # tpudra-race: handoff shutdown choreography: close() runs after the owning loops have stopped (the driver joins its workers and supervisors first); every live-path write holds the cp.lock flock
        fd, self._fd = self._fd, None
        if fd is not None:
            storage.close(fd)

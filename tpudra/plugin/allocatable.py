"""Allocatable-device model for the TPU kubelet plugin.

The analog of gpu-kubelet-plugin/{allocatable,deviceinfo,mig}.go: a tagged
union of everything this node can advertise —

- full TPU chips                      canonical name ``tpu-<index>``
- static TensorCore partitions        ``tpu-<index>-part-<profile>-<core>-<hbm>``
- dynamic (abstract) partitions       same name; created during Prepare
- VFIO passthrough functions          ``tpu-vfio-<index>``

plus conversion to resource.k8s.io Device entries with TPU-native attributes:
uuid, productName, tpuGeneration, ICI mesh coordinates (coordX/Y/Z), cliqueID,
and capacities (hbm, tensorcores, hbm-slice-* counters for partitioning).
The ICI coordinates are what let a workload (or scheduler CEL expression)
reason about fabric locality — the TPU analog of the reference's
pciBusID/architecture attributes (deviceinfo.go:159-269).
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Optional

from tpudra import TPU_DRIVER_NAME
from tpudra.devicelib import (
    HBM_SLICES_PER_CHIP,
    LivePartition,
    PartitionSpec,
    TpuChip,
)
from tpudra.devicelib.topology import PartitionPlacement

TYPE_CHIP = "chip"
TYPE_PARTITION_STATIC = "partition-static"
TYPE_PARTITION_DYNAMIC = "partition-dynamic"
TYPE_VFIO = "vfio"

_PART_NAME_RE = re.compile(
    r"^tpu-(?P<index>\d+)-part-(?P<cores>\d+)c\.(?P<hbm>\d+)hbm-(?P<core_start>\d+)-(?P<hbm_start>\d+)$"
)


def chip_name(index: int) -> str:
    return f"tpu-{index}"


def partition_name(spec: PartitionSpec) -> str:
    return (
        f"tpu-{spec.parent_index}-part-{spec.profile}-{spec.core_start}-{spec.hbm_start}"
    )


def vfio_name(index: int) -> str:
    return f"tpu-vfio-{index}"


def parse_partition_name(name: str) -> Optional[PartitionSpec]:
    m = _PART_NAME_RE.match(name)
    if not m:
        return None
    return PartitionSpec(
        parent_index=int(m.group("index")),
        profile=f"{m.group('cores')}c.{m.group('hbm')}hbm",
        core_start=int(m.group("core_start")),
        hbm_start=int(m.group("hbm_start")),
    )


@dataclass
class AllocatableDevice:
    """One advertisable device (allocatable.go:39 tagged-union analog)."""

    type: str
    name: str
    chip: TpuChip  # the chip itself, or the parent chip for partitions/vfio
    partition_spec: Optional[PartitionSpec] = None
    live_partition: Optional[LivePartition] = None  # static partitions only
    vfio_index: Optional[int] = None
    # Backend capability attestation, surfaced on chip devices so operators
    # can see whether advertised partitions are hardware-enforced or
    # file-backed simulation (DeviceLib.partitions_supported).
    partitions_supported: bool = True
    # Multi-process concurrency attestation (DeviceLib.multiprocess_mode):
    # concurrent | exclusive | unknown — whether a second process can open
    # the chip while one holds it (probed live on the native backend).
    multiprocess_mode: str = "unknown"

    @property
    def is_partition(self) -> bool:
        return self.type in (TYPE_PARTITION_STATIC, TYPE_PARTITION_DYNAMIC)

    # -- ResourceSlice conversion (deviceinfo.go GetDevice analog) ----------

    def attributes(self) -> dict[str, dict]:
        chip = self.chip
        attrs = {
            "type": {"string": self.type},
            "uuid": {"string": chip.uuid},
            "productName": {"string": f"tpu-{chip.generation}"},
            "tpuGeneration": {"string": chip.generation},
            "index": {"int": chip.index},
            "pcieAddress": {"string": chip.pci_address},
            "coordX": {"int": chip.coords[0]},
            "coordY": {"int": chip.coords[1]},
            "coordZ": {"int": chip.coords[2]},
            "cliqueID": {"string": chip.clique_id},
        }
        if self.type == TYPE_CHIP:
            attrs["partitionsSupported"] = {"bool": self.partitions_supported}
            attrs["multiprocessMode"] = {"string": self.multiprocess_mode}
        if self.partition_spec is not None:
            attrs["profile"] = {"string": self.partition_spec.profile}
            attrs["coreStart"] = {"int": self.partition_spec.core_start}
            attrs["hbmStart"] = {"int": self.partition_spec.hbm_start}
            # The packing surface (docs/partitioning.md): what fraction of
            # the parent chip's TensorCores this template grants, as an
            # integer PERCENT so a CEL selector can ask for "at least half
            # a chip" with an ordered comparison (a "1/2" string would
            # compare lexicographically) without knowing the generation's
            # core count.
            cores, hbm_slices = _profile_counts(self.partition_spec.profile)
            if chip.tensorcores:
                attrs["tensorcorePercent"] = {
                    "int": round(100 * cores / chip.tensorcores)
                }
            attrs["hbmSlices"] = {"int": hbm_slices}
            if self.live_partition is not None:
                attrs["uuid"] = {"string": self.live_partition.uuid}
                attrs["parentUUID"] = {"string": chip.uuid}
        if self.type == TYPE_VFIO:
            attrs["addressingMode"] = {"string": "vfio-pci"}
        return attrs

    def capacity(self) -> dict[str, dict]:
        chip = self.chip
        if self.is_partition:
            spec = self.partition_spec
            cores, hbm_slices = _profile_counts(spec.profile)
            hbm = chip.hbm_bytes * hbm_slices // HBM_SLICES_PER_CHIP
            return {
                "tensorcores": {"value": str(cores)},
                "hbm": {"value": str(hbm)},
            }
        return {
            "tensorcores": {"value": str(chip.tensorcores)},
            "hbm": {"value": str(chip.hbm_bytes)},
        }

    def to_resource_device(self) -> dict:
        """resource.k8s.io/v1 Device (flat, non-partitionable form)."""
        return {
            "name": self.name,
            "attributes": self.attributes(),
            "capacity": self.capacity(),
        }


def _profile_counts(profile: str) -> tuple[int, int]:
    cores_s, hbm_s = profile.split(".")
    return int(cores_s.rstrip("c")), int(hbm_s.rstrip("hbm"))


def build_allocatable(
    chips: list[TpuChip],
    static_partitions: list[LivePartition],
    dynamic_placements: dict[int, list[PartitionPlacement]] | None = None,
    with_vfio: bool = False,
    partitions_supported: bool = True,
    multiprocess_mode: str = "unknown",
) -> dict[str, AllocatableDevice]:
    """Assemble the full allocatable map (enumerateAllPossibleDevices analog,
    nvlib.go:170).

    Chips with *static* partitions advertise the partitions instead of the
    whole chip; with dynamic partitioning, abstract partitions are advertised
    alongside the full chip and the KEP-4815 counters arbitrate.  VFIO aliases
    advertise the same silicon for passthrough (siblings; only one of the
    alias pair is ever prepared, allocatable.go:238).
    """
    out: dict[str, AllocatableDevice] = {}
    chips_by_index = {c.index: c for c in chips}
    statically_partitioned = set()
    for live in static_partitions:
        chip = chips_by_index[live.spec.parent_index]
        statically_partitioned.add(chip.index)
        dev = AllocatableDevice(
            type=TYPE_PARTITION_STATIC,
            name=partition_name(live.spec),
            chip=chip,
            partition_spec=live.spec,
            live_partition=live,
        )
        out[dev.name] = dev
    for chip in chips:
        if chip.index in statically_partitioned:
            continue
        dev = AllocatableDevice(
            type=TYPE_CHIP,
            name=chip_name(chip.index),
            chip=chip,
            partitions_supported=partitions_supported,
            multiprocess_mode=multiprocess_mode,
        )
        out[dev.name] = dev
        for placement in (dynamic_placements or {}).get(chip.index, []):
            spec = PartitionSpec(
                parent_index=chip.index,
                profile=placement.profile.name,
                core_start=placement.core_start,
                hbm_start=placement.hbm_start,
            )
            pdev = AllocatableDevice(
                type=TYPE_PARTITION_DYNAMIC,
                name=partition_name(spec),
                chip=chip,
                partition_spec=spec,
            )
            out[pdev.name] = pdev
        if with_vfio:
            vdev = AllocatableDevice(
                type=TYPE_VFIO,
                name=vfio_name(chip.index),
                chip=chip,
                vfio_index=chip.index,
            )
            out[vdev.name] = vdev
    return out


def pool_name(node_name: str) -> str:
    return node_name


DRIVER_NAME = TPU_DRIVER_NAME

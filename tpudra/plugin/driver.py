"""The TPU kubelet-plugin driver: claim fan-in, slice publication, health.

The analog of gpu-kubelet-plugin/driver.go:52-554:

- ``prepare_resource_claims``/``unprepare_resource_claims`` run a kubelet
  batch through the pipelined claim-bind path (docs/bind-path.md): the
  node-global ``pu.lock`` flock is held only around the two batched
  checkpoint RMW phases (begin/finish), and per-claim side effects run
  concurrently across a bounded pool for claims whose silicon footprints
  are disjoint — with per-stage wall-time instrumentation (t_prep_lock_acq
  / t_prep log lines plus the tpudra_bind_phase_seconds histogram, the
  BASELINE bind-latency hooks).
- ``publish_resources`` pushes this node's pool as ResourceSlice objects,
  flat or KEP-4815 partitionable (driver.go:402-554).
- a health monitor consumes device-lib events and republishes the pool
  without unhealthy silicon; there is deliberately no auto-reheal — a chip
  comes back only on plugin restart (driver.go:462-502).
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Callable, Optional

from tpudra import TPU_DRIVER_NAME, featuregates, metrics
from tpudra.devicelib import DeviceLib, HealthEvent, HealthEventKind
from tpudra.flock import Flock
from tpudra.kube.apply import next_pool_generation, publish_slices
from tpudra.kube.client import KubeAPI
from tpudra.plugin import allocatable as alloc
from tpudra.plugin.cdi import CDIHandler
from tpudra.plugin.checkpoint import CheckpointManager
from tpudra.plugin.cleanup import CheckpointCleanupManager
from tpudra.plugin.device_state import DeviceState, PermanentError
from tpudra.plugin.grpcserver import PluginSockets, kube_claim_resolver
from tpudra.plugin.resourceslice import build_resource_slices, generate_driver_resources
from tpudra.plugin.sharing import MultiProcessManager
from tpudra.plugin.vfio import VfioManager

logger = logging.getLogger(__name__)

PU_LOCK = "pu.lock"
PU_LOCK_TIMEOUT = 10.0  # reference driver.go:341


@dataclass
class DriverConfig:
    node_name: str
    plugin_dir: str  # /var/lib/kubelet/plugins/tpu.google.com
    registry_dir: str  # /var/lib/kubelet/plugins_registry
    cdi_root: str  # /var/run/cdi
    driver_root: str = "/"
    k8s_minor: int = 35
    device_backend: str = "mock"
    device_backend_options: dict = field(default_factory=dict)
    health_ignored_kinds: tuple = HealthEventKind.DEFAULT_IGNORED
    # Bound on concurrent per-claim side-effect work within one kubelet
    # batch (footprint-disjoint claims only; see prepare_resource_claims).
    prepare_concurrency: int = 8


class Driver:
    def __init__(
        self,
        config: DriverConfig,
        kube: KubeAPI,
        devicelib: DeviceLib,
        mp_manager: Optional[MultiProcessManager] = None,
        vfio_manager: Optional[VfioManager] = None,
    ):
        self._config = config
        self._kube = kube
        self._lib = devicelib
        os.makedirs(config.plugin_dir, exist_ok=True)
        self._pu_lock_path = os.path.join(config.plugin_dir, PU_LOCK)
        self.state = DeviceState(
            devicelib,
            CDIHandler(config.cdi_root, config.driver_root),
            CheckpointManager(config.plugin_dir),
            config.node_name,
            mp_manager=mp_manager,
            vfio_manager=vfio_manager,
        )
        self._unhealthy: set[str] = set()
        self._unhealthy_lock = threading.Lock()
        # Per-device last-status-change unix time for the DRAResourceHealth
        # stream; devices absent here report the startup timestamp.
        self._health_changed_at: dict[str, float] = {}
        self._health_start_ts = time.time()
        # Serializes the whole snapshot→build→apply publication path: the
        # health thread and prepare RPC threads both publish, and an
        # interleaving could re-advertise silicon just marked unhealthy.
        self._publish_lock = threading.Lock()
        # Seeded from live slices so a restart outranks previous publishes.
        self._pool_generation = next_pool_generation(
            kube, config.node_name, alloc.pool_name(config.node_name)
        )
        self._stop = threading.Event()
        self._sockets = PluginSockets(
            TPU_DRIVER_NAME,
            config.plugin_dir,
            config.registry_dir,
            prepare=self.prepare_resource_claims,
            unprepare=self.unprepare_resource_claims,
            resolve_claim=kube_claim_resolver(kube),
        )
        self.cleanup = CheckpointCleanupManager(
            kube, self.state, unprepare=self._unprepare_serialized
        )
        self._health_thread: Optional[threading.Thread] = None
        # Side-effect fan-out pool.  Threads spawn lazily on first multi-
        # claim batch; single-claim batches run inline on the RPC thread
        # (no hop, no pool wakeup — the common kubelet case).
        self._effects_pool = ThreadPoolExecutor(
            max_workers=max(1, config.prepare_concurrency),
            thread_name_prefix="claim-effects",
        )
        # Per-claim-uid serialization: with the node lock narrowed to the
        # RMW phases, a prepare and an unprepare of the SAME uid could
        # otherwise interleave at the effects phase (prepare returning a
        # grant whose CDI spec a concurrent unprepare just deleted).  One
        # flock file per uid so the guard holds across processes (a
        # restart-overlap sibling driver) as well as threads; unprepare
        # unlinks the file while holding it (see _acquire_claim_lock).
        self._claim_locks_dir = os.path.join(config.plugin_dir, "claims")

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        """Startup order mirrors the reference (driver.go:66-170): destroy
        unknown partitions, serve sockets, start health + GC, publish."""
        if featuregates.enabled(featuregates.DYNAMIC_PARTITIONING):
            n = self.state.destroy_unknown_partitions()
            if n:
                logger.warning("startup reconciliation destroyed %d unknown partitions", n)
        if featuregates.enabled(featuregates.DRA_RESOURCE_HEALTH_SERVICE):
            # Implements-it-then-serve: the broadcaster must exist before the
            # socket starts so the service is registered and advertised
            # (helper semantics, draplugin.go:623-663).
            from tpudra.plugin.healthservice import (
                HealthBroadcaster,
                snapshot_from_driver_state,
            )

            self._sockets.health_broadcaster = HealthBroadcaster(
                snapshot_from_driver_state(
                    allocatable=lambda: self.state.allocatable,
                    unhealthy=self.unhealthy_devices,
                    changed_at=self._health_timestamps,
                    start_ts=int(self._health_start_ts),
                    pool=alloc.pool_name(self._config.node_name),
                )
            )
        self._sockets.start()
        if featuregates.enabled(featuregates.TPU_DEVICE_HEALTH_CHECK):
            self._health_thread = threading.Thread(
                target=self._health_loop, daemon=True, name="device-health"
            )
            self._health_thread.start()
        self.cleanup.start(self._stop)
        self.publish_resources()

    def stop(self) -> None:
        self._stop.set()
        self._sockets.stop()
        self._effects_pool.shutdown(wait=False)
        self._lib.close()

    @property
    def sockets(self) -> PluginSockets:
        return self._sockets

    # ------------------------------------------------------ prepare/unprepare

    def prepare_resource_claims(self, claims: list[dict]) -> dict:
        if not claims:
            # The health monitor pings with an empty batch (health.py,
            # reference health.go:122) — it must stay lock- and disk-free.
            return {"claims": {}}
        t0 = time.monotonic()
        out: dict[str, dict] = {}
        # Any prepare can flip sibling visibility in either direction (a vfio
        # grant withholds the chip; a chip grant withholds the vfio alias) —
        # republish once per batch when the withheld set changed
        # (driver.go:361).  bound_sibling_devices is empty-and-free with
        # passthrough disabled.
        withheld_before = self.state.bound_sibling_devices()
        uids = [c.get("metadata", {}).get("uid", "") for c in claims]
        try:
            with self._claims_serialized(uids):
                # Phase 1 under the node lock: ONE checkpoint RMW records
                # PrepareStarted (+ rollback/validation) for the whole batch.
                with self._locked_pu():
                    t_lock = time.monotonic() - t0
                    batch = self.state.begin_prepare(claims)
                # Phase 2 outside the lock: per-claim side effects,
                # concurrent across footprint-disjoint claims.
                self._run_effects(
                    batch.pending(),
                    self.state.run_prepare_effects,
                    "prepare effects",
                )
                # Phase 3 under the node lock: ONE checkpoint RMW completes
                # every claim whose effects succeeded.
                with self._locked_pu():
                    self.state.finish_prepare(batch)
                for item in batch.items:
                    if item.error is not None:
                        # Failed claims may never see an unprepare (kubelet
                        # only unprepares what prepared), so their lock file
                        # would leak; unlink-while-held is always safe and a
                        # retry recreates it on demand.
                        self._gc_claim_lock(item.uid)
        except Exception as e:  # noqa: BLE001 — lock timeout / checkpoint IO
            self._republish_if_withheld_changed(withheld_before)
            return self._batch_failure(claims, e, "prepare", t0)
        t_prep = time.monotonic() - t0
        # One sample per NodePrepareResources call: with phases batched,
        # claims of a batch have no individual wall time to observe, and
        # N batch-wide samples would inflate the histogram ~N-fold.
        metrics.PREPARE_SECONDS.labels(TPU_DRIVER_NAME).observe(t_prep)
        # Once per CALL, like the histogram sample: these are batch-wide
        # wall times, and a line per claim would overstate per-claim
        # latency ~N-fold to anyone grepping the t_prep hook.
        logger.info(
            "t_prep_lock_acq=%.4fs t_prep=%.4fs claims=%s",
            t_lock, t_prep,
            ",".join(it.uid or "<no uid>" for it in batch.items),
        )
        for item in batch.items:
            if item.error is not None:
                logger.error(
                    "prepare failed for claim %s", item.uid or "<no uid>",
                    exc_info=item.error,
                )
                metrics.PREPARE_ERRORS.labels(TPU_DRIVER_NAME).inc()
                out[item.uid] = {
                    "error": str(item.error),
                    "permanent": isinstance(item.error, PermanentError),
                }
                continue
            out[item.uid] = {
                "devices": [
                    {
                        "requestNames": d.request_names,
                        "poolName": d.pool_name,
                        "deviceName": d.device_name,
                        "cdiDeviceIDs": d.cdi_device_ids,
                    }
                    for d in item.device_results()
                ]
            }
        self._republish_if_withheld_changed(withheld_before)
        return {"claims": out}

    def unprepare_resource_claims(self, claims: list[dict]) -> dict:
        if not claims:
            return {"claims": {}}
        t0 = time.monotonic()
        out: dict[str, dict] = {}
        withheld_before = self.state.bound_sibling_devices()
        uids = [
            ref.get("uid") or ref.get("metadata", {}).get("uid", "")
            for ref in claims
        ]
        try:
            with self._claims_serialized(uids):
                with self._locked_pu():
                    batch = self.state.begin_unprepare(uids)
                self._run_effects(
                    batch.pending(),
                    self.state.run_unprepare_effects,
                    "unprepare effects",
                )
                with self._locked_pu():
                    self.state.finish_unprepare(batch)
                for item in batch.items:
                    if item.done:  # record dropped; lock file is garbage
                        self._gc_claim_lock(item.uid)
        except Exception as e:  # noqa: BLE001 — lock timeout / checkpoint IO
            self._republish_if_withheld_changed(withheld_before)
            return self._batch_failure(claims, e, "unprepare", t0)
        t_unprep = time.monotonic() - t0
        metrics.UNPREPARE_SECONDS.labels(TPU_DRIVER_NAME).observe(t_unprep)
        logger.info(
            "t_unprep=%.4fs claims=%s",
            t_unprep,
            ",".join(it.uid or "<no uid>" for it in batch.items),
        )
        for item in batch.items:
            if item.error is not None:
                logger.error(
                    "unprepare failed for claim %s", item.uid or "<no uid>",
                    exc_info=item.error,
                )
                out[item.uid] = {"error": str(item.error)}
            else:
                out[item.uid] = {}
        self._republish_if_withheld_changed(withheld_before)
        return {"claims": out}

    def _republish_if_withheld_changed(self, withheld_before: set) -> None:
        """Republish when sibling visibility changed — on EVERY exit path:
        even a failed batch may have written PrepareStarted records that
        flip visibility, and the retry samples withheld_before after those
        records exist, so a skipped republish would never self-heal."""
        try:
            if self.state.bound_sibling_devices() != withheld_before:
                self.publish_resources()
        except Exception:  # noqa: BLE001 — never mask the RPC result
            logger.exception("republish after prepare/unprepare failed")

    def _unprepare_serialized(self, uid: str) -> None:
        """Single-claim unprepare under the per-uid lock — the GC's entry
        point, so its teardown serializes against kubelet RPCs for the
        same claim."""
        with self._claims_serialized([uid]):
            self.state.unprepare(uid)
            self._gc_claim_lock(uid)

    def _run_effects(self, items: list, effect: Callable, what: str) -> None:
        """Run per-item side effects, fanning footprint-disjoint items
        across the bounded pool.  Failures land in ``item.error`` (per-claim
        fault barrier); items sharing silicon run serially within a group."""
        items = [it for it in items if it.error is None]
        if not items:
            return
        groups = self.state.effect_groups(
            [(it, it.device_names()) for it in items]
        )

        def run_group(group: list) -> None:
            for it in group:
                try:
                    effect(it)
                except Exception as e:  # noqa: BLE001 — per-claim barrier
                    it.error = e

        if len(groups) == 1:
            run_group(groups[0])
            return
        futures = [self._effects_pool.submit(run_group, g) for g in groups]
        for f in futures:
            try:
                f.result()
            except Exception:  # noqa: BLE001 — pool plumbing only
                logger.exception("%s worker failed", what)

    def _batch_failure(
        self, claims: list[dict], e: Exception, op: str, t0: float
    ) -> dict:
        """A batch-wide fault (node lock timeout, unreadable checkpoint):
        every claim of the batch gets the same retryable error (kubelet
        re-calls).  The latency histogram still gets its sample — lock
        timeouts ARE the tail a bind dashboard exists to catch."""
        logger.error("%s batch failed", op, exc_info=e)
        hist = (
            metrics.PREPARE_SECONDS if op == "prepare"
            else metrics.UNPREPARE_SECONDS
        )
        hist.labels(TPU_DRIVER_NAME).observe(time.monotonic() - t0)
        out: dict[str, dict] = {}
        for ref in claims:
            uid = ref.get("uid") or ref.get("metadata", {}).get("uid", "")
            if op == "prepare":
                metrics.PREPARE_ERRORS.labels(TPU_DRIVER_NAME).inc()
            out[uid] = {"error": f"node {op}: {e}", "permanent": False}
        return {"claims": out}

    def _claim_lock_path(self, uid: str) -> str:
        return os.path.join(self._claim_locks_dir, f"{uid}.lock")

    def _acquire_claim_lock(self, uid: str, deadline: float) -> Flock:
        """Acquire one claim-uid flock, surviving concurrent GC of the lock
        file: after acquiring, re-stat the path — if the file was unlinked
        or replaced between our open and our flock (an unpreparing holder
        unlinks while holding), release and retry on the fresh file."""
        while True:
            lock = Flock(self._claim_lock_path(uid), metric_label="claim")
            lock.acquire(timeout=max(0.0, deadline - time.monotonic()))
            try:
                st = os.stat(lock.path)
            except FileNotFoundError:
                st = None
            if st is not None and os.fstat(lock.fileno()).st_ino == st.st_ino:
                return lock
            lock.release()

    @contextlib.contextmanager
    def _claims_serialized(self, uids):
        """Hold a per-claim-uid flock for the whole phased operation, so
        concurrent prepare/unprepare of the same claim — in this process or
        a sibling driver process — serialize exactly as the old full-width
        node lock made them.  Distinct uids never contend.  Locks are taken
        in sorted order (no deadlock between batches sharing uids) with the
        node-flock timeout: a wedged effects phase must fail same-uid
        retries after PU_LOCK_TIMEOUT, not absorb a gRPC worker thread per
        retry forever."""
        deadline = time.monotonic() + PU_LOCK_TIMEOUT
        locks = []
        try:
            for uid in sorted({u for u in uids if u}):
                locks.append(self._acquire_claim_lock(uid, deadline))
            yield
        finally:
            for lock in reversed(locks):
                lock.release()

    def _gc_claim_lock(self, uid: str) -> None:
        """Unlink a claim's lock file; call ONLY while holding its lock
        (the unlink-while-held + re-stat-after-acquire protocol keeps
        racing acquirers correct, and the dir from growing with every
        claim the node has ever seen)."""
        with contextlib.suppress(OSError):
            os.unlink(self._claim_lock_path(uid))

    def _pu_lock(self):
        """A fresh Flock per operation: one shared instance cannot be
        acquired twice, but kubelet issues concurrent prepare RPCs — each
        call gets its own fd and the kernel serializes across both threads
        and processes."""
        return Flock(self._pu_lock_path)

    @contextlib.contextmanager
    def _locked_pu(self):
        """Acquire the node-global lock for one RMW phase, feeding the wait
        into the per-phase bind histogram."""
        lock = self._pu_lock()
        with lock(timeout=PU_LOCK_TIMEOUT):
            metrics.observe_phase(metrics.PHASE_LOCK_WAIT, lock.last_wait)
            yield lock

    # ---------------------------------------------------------- publication

    def publish_resources(self) -> list[dict]:
        with self._publish_lock:
            partitionable = featuregates.enabled(featuregates.DYNAMIC_PARTITIONING)
            with self._unhealthy_lock:
                unhealthy = set(self._unhealthy)
            res = generate_driver_resources(
                self.state.allocatable,
                unhealthy=unhealthy,
                withheld=self.state.bound_sibling_devices(),
                partitionable=partitionable,
                node_name=self._config.node_name,
            )
            slices = build_resource_slices(
                res,
                self._config.node_name,
                k8s_minor=self._config.k8s_minor,
                generation=self._pool_generation,
            )
            self._pool_generation += 1
            publish_slices(
                self._kube,
                slices,
                self._config.node_name,
                f"{self._config.node_name}-{TPU_DRIVER_NAME}-",
            )
            metrics.SLICE_PUBLISH_TOTAL.labels(TPU_DRIVER_NAME).inc()
            metrics.UNHEALTHY_DEVICES.labels(TPU_DRIVER_NAME).set(len(unhealthy))
            logger.info(
                "published %d ResourceSlice(s), %d devices, %d unhealthy",
                len(slices), len(res.devices), len(unhealthy),
            )
            return slices

    # --------------------------------------------------------------- health

    def _health_loop(self) -> None:
        for event in self._lib.health_events(self._stop):
            try:
                self._handle_health_event(event)
            except Exception:  # noqa: BLE001
                logger.exception("handling health event %s", event)

    def _handle_health_event(self, event: HealthEvent) -> None:
        if event.kind in self._config.health_ignored_kinds:
            logger.info("ignoring health event %s on %s", event.kind, event.chip_uuid)
            return
        names = self._devices_for_event(event)
        if not names:
            logger.warning("health event %s for unknown silicon %s", event.kind, event.chip_uuid)
            return
        with self._unhealthy_lock:
            before = set(self._unhealthy)
            self._unhealthy.update(names)
            changed = self._unhealthy != before
            if changed:
                now = time.time()
                for name in self._unhealthy - before:
                    self._health_changed_at[name] = now
        if changed:
            logger.error(
                "marking unhealthy after %s (%s): %s — republishing without them",
                event.kind, event.detail, sorted(names),
            )
            self.publish_resources()
            if self._sockets.health_broadcaster is not None:
                self._sockets.health_broadcaster.notify()

    def _devices_for_event(self, event: HealthEvent) -> set[str]:
        if event.partition_uuid:
            for name, dev in self.state.allocatable.items():
                if (
                    dev.live_partition is not None
                    and dev.live_partition.uuid == event.partition_uuid
                ):
                    return {name}
        return {
            name
            for name, dev in self.state.allocatable.items()
            if dev.chip.uuid == event.chip_uuid
        }

    def unhealthy_devices(self) -> set[str]:
        with self._unhealthy_lock:
            return set(self._unhealthy)

    def _health_timestamps(self) -> dict[str, float]:
        with self._unhealthy_lock:
            return dict(self._health_changed_at)

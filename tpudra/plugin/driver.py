"""The TPU kubelet-plugin driver: claim fan-in, slice publication, health.

The analog of gpu-kubelet-plugin/driver.go:52-554:

- ``prepare_resource_claims``/``unprepare_resource_claims`` fan a kubelet
  batch into per-claim operations under the node-global ``pu.lock`` flock
  (driver.go:298-400), with per-stage wall-time instrumentation
  (t_prep_lock_acq / t_prep — the BASELINE bind-latency hooks).
- ``publish_resources`` pushes this node's pool as ResourceSlice objects,
  flat or KEP-4815 partitionable (driver.go:402-554).
- a health monitor consumes device-lib events and republishes the pool
  without unhealthy silicon; there is deliberately no auto-reheal — a chip
  comes back only on plugin restart (driver.go:462-502).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from tpudra import TPU_DRIVER_NAME, featuregates, metrics
from tpudra.devicelib import DeviceLib, HealthEvent, HealthEventKind
from tpudra.flock import Flock, FlockTimeout
from tpudra.kube.apply import next_pool_generation, publish_slices
from tpudra.kube.client import KubeAPI
from tpudra.plugin import allocatable as alloc
from tpudra.plugin.cdi import CDIHandler
from tpudra.plugin.checkpoint import CheckpointManager
from tpudra.plugin.cleanup import CheckpointCleanupManager
from tpudra.plugin.device_state import DeviceState, PermanentError
from tpudra.plugin.grpcserver import PluginSockets, kube_claim_resolver
from tpudra.plugin.resourceslice import build_resource_slices, generate_driver_resources
from tpudra.plugin.sharing import MultiProcessManager
from tpudra.plugin.vfio import VfioManager

logger = logging.getLogger(__name__)

PU_LOCK = "pu.lock"
PU_LOCK_TIMEOUT = 10.0  # reference driver.go:341


@dataclass
class DriverConfig:
    node_name: str
    plugin_dir: str  # /var/lib/kubelet/plugins/tpu.google.com
    registry_dir: str  # /var/lib/kubelet/plugins_registry
    cdi_root: str  # /var/run/cdi
    driver_root: str = "/"
    k8s_minor: int = 35
    device_backend: str = "mock"
    device_backend_options: dict = field(default_factory=dict)
    health_ignored_kinds: tuple = HealthEventKind.DEFAULT_IGNORED


class Driver:
    def __init__(
        self,
        config: DriverConfig,
        kube: KubeAPI,
        devicelib: DeviceLib,
        mp_manager: Optional[MultiProcessManager] = None,
        vfio_manager: Optional[VfioManager] = None,
    ):
        self._config = config
        self._kube = kube
        self._lib = devicelib
        os.makedirs(config.plugin_dir, exist_ok=True)
        self._pu_lock_path = os.path.join(config.plugin_dir, PU_LOCK)
        self.state = DeviceState(
            devicelib,
            CDIHandler(config.cdi_root, config.driver_root),
            CheckpointManager(config.plugin_dir),
            config.node_name,
            mp_manager=mp_manager,
            vfio_manager=vfio_manager,
        )
        self._unhealthy: set[str] = set()
        self._unhealthy_lock = threading.Lock()
        # Per-device last-status-change unix time for the DRAResourceHealth
        # stream; devices absent here report the startup timestamp.
        self._health_changed_at: dict[str, float] = {}
        self._health_start_ts = time.time()
        # Serializes the whole snapshot→build→apply publication path: the
        # health thread and prepare RPC threads both publish, and an
        # interleaving could re-advertise silicon just marked unhealthy.
        self._publish_lock = threading.Lock()
        # Seeded from live slices so a restart outranks previous publishes.
        self._pool_generation = next_pool_generation(
            kube, config.node_name, alloc.pool_name(config.node_name)
        )
        self._stop = threading.Event()
        self._sockets = PluginSockets(
            TPU_DRIVER_NAME,
            config.plugin_dir,
            config.registry_dir,
            prepare=self.prepare_resource_claims,
            unprepare=self.unprepare_resource_claims,
            resolve_claim=kube_claim_resolver(kube),
        )
        self.cleanup = CheckpointCleanupManager(kube, self.state)
        self._health_thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        """Startup order mirrors the reference (driver.go:66-170): destroy
        unknown partitions, serve sockets, start health + GC, publish."""
        if featuregates.enabled(featuregates.DYNAMIC_PARTITIONING):
            n = self.state.destroy_unknown_partitions()
            if n:
                logger.warning("startup reconciliation destroyed %d unknown partitions", n)
        if featuregates.enabled(featuregates.DRA_RESOURCE_HEALTH_SERVICE):
            # Implements-it-then-serve: the broadcaster must exist before the
            # socket starts so the service is registered and advertised
            # (helper semantics, draplugin.go:623-663).
            from tpudra.plugin.healthservice import (
                HealthBroadcaster,
                snapshot_from_driver_state,
            )

            self._sockets.health_broadcaster = HealthBroadcaster(
                snapshot_from_driver_state(
                    allocatable=lambda: self.state.allocatable,
                    unhealthy=self.unhealthy_devices,
                    changed_at=self._health_timestamps,
                    start_ts=int(self._health_start_ts),
                    pool=alloc.pool_name(self._config.node_name),
                )
            )
        self._sockets.start()
        if featuregates.enabled(featuregates.TPU_DEVICE_HEALTH_CHECK):
            self._health_thread = threading.Thread(
                target=self._health_loop, daemon=True, name="device-health"
            )
            self._health_thread.start()
        self.cleanup.start(self._stop)
        self.publish_resources()

    def stop(self) -> None:
        self._stop.set()
        self._sockets.stop()
        self._lib.close()

    @property
    def sockets(self) -> PluginSockets:
        return self._sockets

    # ------------------------------------------------------ prepare/unprepare

    def prepare_resource_claims(self, claims: list[dict]) -> dict:
        out: dict[str, dict] = {}
        # Any prepare can flip sibling visibility in either direction (a vfio
        # grant withholds the chip; a chip grant withholds the vfio alias) —
        # republish once per batch when the withheld set changed
        # (driver.go:361).  bound_sibling_devices is empty-and-free with
        # passthrough disabled.
        withheld_before = self.state.bound_sibling_devices()
        for claim in claims:
            uid = claim.get("metadata", {}).get("uid", "")
            t0 = time.monotonic()
            try:
                out[uid] = self._prepare_one(claim)
            except Exception as e:  # noqa: BLE001 — per-claim fault barrier
                logger.exception("prepare failed for claim %s", uid)
                metrics.PREPARE_ERRORS.labels(TPU_DRIVER_NAME).inc()
                out[uid] = {"error": str(e), "permanent": isinstance(e, PermanentError)}
            finally:
                metrics.PREPARE_SECONDS.labels(TPU_DRIVER_NAME).observe(
                    time.monotonic() - t0
                )
        if self.state.bound_sibling_devices() != withheld_before:
            self.publish_resources()
        return {"claims": out}

    def unprepare_resource_claims(self, claims: list[dict]) -> dict:
        out: dict[str, dict] = {}
        withheld_before = self.state.bound_sibling_devices()
        for ref in claims:
            uid = ref.get("uid") or ref.get("metadata", {}).get("uid", "")
            t0 = time.monotonic()
            try:
                self._unprepare_one(uid)
                out[uid] = {}
            except Exception as e:  # noqa: BLE001
                logger.exception("unprepare failed for claim %s", uid)
                out[uid] = {"error": str(e)}
            finally:
                metrics.UNPREPARE_SECONDS.labels(TPU_DRIVER_NAME).observe(
                    time.monotonic() - t0
                )
        if self.state.bound_sibling_devices() != withheld_before:
            self.publish_resources()  # siblings became visible again
        return {"claims": out}

    def _pu_lock(self):
        """A fresh Flock per operation: one shared instance cannot be
        acquired twice, but kubelet issues concurrent prepare RPCs — each
        call gets its own fd and the kernel serializes across both threads
        and processes."""
        return Flock(self._pu_lock_path)

    def _prepare_one(self, claim: dict) -> dict:
        t0 = time.monotonic()
        try:
            with self._pu_lock()(timeout=PU_LOCK_TIMEOUT):
                t_lock = time.monotonic() - t0
                devices = self.state.prepare(claim)
        except FlockTimeout as e:
            raise RuntimeError(f"node prepare lock: {e}") from e
        logger.info(
            "t_prep_lock_acq=%.4fs t_prep=%.4fs claim=%s",
            t_lock, time.monotonic() - t0, claim.get("metadata", {}).get("uid"),
        )
        return {
            "devices": [
                {
                    "requestNames": d.request_names,
                    "poolName": d.pool_name,
                    "deviceName": d.device_name,
                    "cdiDeviceIDs": d.cdi_device_ids,
                }
                for d in devices
            ]
        }

    def _unprepare_one(self, uid: str) -> None:
        if not uid:
            raise PermanentError("claim reference has no uid")
        t0 = time.monotonic()
        try:
            with self._pu_lock()(timeout=PU_LOCK_TIMEOUT):
                self.state.unprepare(uid)
        except FlockTimeout as e:
            raise RuntimeError(f"node unprepare lock: {e}") from e
        logger.info("t_unprep=%.4fs claim=%s", time.monotonic() - t0, uid)

    # ---------------------------------------------------------- publication

    def publish_resources(self) -> list[dict]:
        with self._publish_lock:
            partitionable = featuregates.enabled(featuregates.DYNAMIC_PARTITIONING)
            with self._unhealthy_lock:
                unhealthy = set(self._unhealthy)
            res = generate_driver_resources(
                self.state.allocatable,
                unhealthy=unhealthy,
                withheld=self.state.bound_sibling_devices(),
                partitionable=partitionable,
                node_name=self._config.node_name,
            )
            slices = build_resource_slices(
                res,
                self._config.node_name,
                k8s_minor=self._config.k8s_minor,
                generation=self._pool_generation,
            )
            self._pool_generation += 1
            publish_slices(
                self._kube,
                slices,
                self._config.node_name,
                f"{self._config.node_name}-{TPU_DRIVER_NAME}-",
            )
            metrics.SLICE_PUBLISH_TOTAL.labels(TPU_DRIVER_NAME).inc()
            metrics.UNHEALTHY_DEVICES.labels(TPU_DRIVER_NAME).set(len(unhealthy))
            logger.info(
                "published %d ResourceSlice(s), %d devices, %d unhealthy",
                len(slices), len(res.devices), len(unhealthy),
            )
            return slices

    # --------------------------------------------------------------- health

    def _health_loop(self) -> None:
        for event in self._lib.health_events(self._stop):
            try:
                self._handle_health_event(event)
            except Exception:  # noqa: BLE001
                logger.exception("handling health event %s", event)

    def _handle_health_event(self, event: HealthEvent) -> None:
        if event.kind in self._config.health_ignored_kinds:
            logger.info("ignoring health event %s on %s", event.kind, event.chip_uuid)
            return
        names = self._devices_for_event(event)
        if not names:
            logger.warning("health event %s for unknown silicon %s", event.kind, event.chip_uuid)
            return
        with self._unhealthy_lock:
            before = set(self._unhealthy)
            self._unhealthy.update(names)
            changed = self._unhealthy != before
            if changed:
                now = time.time()
                for name in self._unhealthy - before:
                    self._health_changed_at[name] = now
        if changed:
            logger.error(
                "marking unhealthy after %s (%s): %s — republishing without them",
                event.kind, event.detail, sorted(names),
            )
            self.publish_resources()
            if self._sockets.health_broadcaster is not None:
                self._sockets.health_broadcaster.notify()

    def _devices_for_event(self, event: HealthEvent) -> set[str]:
        if event.partition_uuid:
            for name, dev in self.state.allocatable.items():
                if (
                    dev.live_partition is not None
                    and dev.live_partition.uuid == event.partition_uuid
                ):
                    return {name}
        return {
            name
            for name, dev in self.state.allocatable.items()
            if dev.chip.uuid == event.chip_uuid
        }

    def unhealthy_devices(self) -> set[str]:
        with self._unhealthy_lock:
            return set(self._unhealthy)

    def _health_timestamps(self) -> dict[str, float]:
        with self._unhealthy_lock:
            return dict(self._health_changed_at)

"""The TPU kubelet-plugin driver: claim fan-in, slice publication, health.

The analog of gpu-kubelet-plugin/driver.go:52-554:

- ``prepare_resource_claims``/``unprepare_resource_claims`` run a kubelet
  batch through the pipelined claim-bind path (docs/bind-path.md): the
  node-global ``pu.lock`` flock is held only around the two batched
  checkpoint RMW phases (begin/finish), and per-claim side effects run
  concurrently across a bounded pool for claims whose silicon footprints
  are disjoint — with per-stage wall-time instrumentation (t_prep_lock_acq
  / t_prep log lines plus the tpudra_bind_phase_seconds histogram, the
  BASELINE bind-latency hooks).
- ``publish_resources`` pushes this node's pool as ResourceSlice objects,
  flat or KEP-4815 partitionable (driver.go:402-554).  Since the
  apiserver-off-the-hot-path work, RPC and health threads only *signal*
  (``_request_publish``); a dedicated publisher thread debounces bursts
  into one rebuild and a content hash skips no-op API writes.
- claim references are resolved through a watch-backed informer cache
  with singleflight GET fallback (claimresolver.py) instead of one
  synchronous apiserver GET per claim.
- a health monitor consumes device-lib events and republishes the pool
  without unhealthy silicon; there is deliberately no auto-reheal — a chip
  comes back only on plugin restart (driver.go:462-502).
"""

from __future__ import annotations

import contextlib
import contextvars
import hashlib
import json
import logging
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Callable, Optional

from tpudra import (
    CLAIM_UNHEALTHY_CONDITION,
    TPU_DRIVER_NAME,
    featuregates,
    lockwitness,
    metrics,
    racewitness,
    storage,
    trace,
)
from tpudra.backoff import Backoff
from tpudra.clock import Clock
from tpudra.devicelib import DeviceLib, HealthEvent, HealthEventKind
from tpudra.flock import Flock
from tpudra.kube import gvr
from tpudra.kube.apply import next_pool_generation, publish_slices
from tpudra.kube.client import KubeAPI
from tpudra.kube.informer import Informer
from tpudra.plugin import allocatable as alloc
from tpudra.plugin.cdi import CDIHandler
from tpudra.plugin.checkpoint import CheckpointManager
from tpudra.plugin.claimresolver import CachedClaimResolver
from tpudra.plugin.cleanup import CheckpointCleanupManager
from tpudra.plugin.device_state import DeviceState, PermanentError
from tpudra.plugin.grpcserver import PluginSockets, kube_claim_resolver
from tpudra.plugin.resourceslice import build_resource_slices, generate_driver_resources
from tpudra.plugin.sharing import MultiProcessManager
from tpudra.plugin.vfio import VfioManager

logger = logging.getLogger(__name__)

PU_LOCK = "pu.lock"
PU_LOCK_TIMEOUT = 10.0  # reference driver.go:341

# The escalation writes CLAIM_UNHEALTHY_CONDITION (tpudra package root —
# shared with the controller's claim-health watch): a device granted to a
# claim went unhealthy AFTER binding.  Withholding sick silicon from
# future ResourceSlices (the health loop's original job) is invisible to
# a claim that already holds it; the condition is the claim-holder-facing
# half, mirroring the reference's claim-status device-health surfacing.


def escalate_claim_condition(
    kube: KubeAPI,
    namespace: str,
    name: str,
    uid: str,
    devices: list[dict],
    reason: str,
    message: str,
) -> bool:
    """Write the device-unhealthy escalation onto one claim's status:
    a claim-level condition (the controller's watch signal) plus per-device
    entries under ``status.devices`` with a ``Healthy=False`` condition
    (the DRA v1 per-device health shape).  Returns False — without raising
    — when the live claim is gone or its uid moved on (a deleted claim
    needs no escalation; a recreated one never held this silicon).  A 409
    Conflict (another status writer won the optimistic-concurrency race)
    re-reads and retries — the unhealthy transition fires ONCE, so a
    single lost write would silence the escalation forever.  Any other
    error (an apiserver blip) propagates: the caller must count it as a
    FAILED escalation, not mistake it for claim-absent."""
    from tpudra.kube.errors import Conflict, NotFound

    for attempt in range(4):
        try:
            claim = kube.get(gvr.RESOURCE_CLAIMS, name, namespace)
        except NotFound:
            return False
        if claim.get("metadata", {}).get("uid") != uid:
            return False
        status = claim.setdefault("status", {})
        now = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        condition = {
            "type": CLAIM_UNHEALTHY_CONDITION,
            "status": "True",
            "reason": reason,
            "message": message,
            "lastTransitionTime": now,
        }
        conditions = [
            c for c in status.get("conditions", [])
            if c.get("type") != CLAIM_UNHEALTHY_CONDITION
        ]
        conditions.append(condition)
        status["conditions"] = conditions
        dev_entries = status.setdefault("devices", [])
        for dev in devices:
            key = (dev["driver"], dev["pool"], dev["device"])
            entry = next(
                (
                    e
                    for e in dev_entries
                    if (e.get("driver"), e.get("pool"), e.get("device")) == key
                ),
                None,
            )
            if entry is None:
                entry = {"driver": key[0], "pool": key[1], "device": key[2]}
                dev_entries.append(entry)
            entry["conditions"] = [
                {
                    "type": "Healthy",
                    "status": "False",
                    "reason": reason,
                    "message": message,
                    "lastTransitionTime": now,
                }
            ]
        try:
            kube.update_status(gvr.RESOURCE_CLAIMS, claim, namespace)
        except Conflict:
            if attempt == 3:
                raise
            continue  # re-read at the fresh resourceVersion and retry
        return True
    return True  # unreachable: the loop returns or raises


@dataclass
class DriverConfig:
    node_name: str
    plugin_dir: str  # /var/lib/kubelet/plugins/tpu.google.com
    registry_dir: str  # /var/lib/kubelet/plugins_registry
    cdi_root: str  # /var/run/cdi
    driver_root: str = "/"
    k8s_minor: int = 35
    device_backend: str = "mock"
    device_backend_options: dict = field(default_factory=dict)
    health_ignored_kinds: tuple = HealthEventKind.DEFAULT_IGNORED
    # Bound on concurrent per-claim side-effect work within one kubelet
    # batch (footprint-disjoint claims only; see prepare_resource_claims).
    prepare_concurrency: int = 8
    # Watch-backed claim resolution (claimresolver.py): False resolves
    # every claim reference with a direct apiserver GET, the pre-cache
    # behavior (the bench A/B arm and an escape hatch).
    claim_cache: bool = True
    # Periodic claim-informer resync: re-dispatches MODIFIED to handlers
    # on the period (client-go semantics — it replays the CACHE, it does
    # not refresh it from the apiserver).  The resolver registers no
    # handlers and its correctness does not depend on resync (uid guard +
    # read-through fallbacks + watch-health gate), so the default is
    # DISABLED — a nonzero period only makes sense once something
    # subscribes to the claim informer.  <= 0 disables.
    claim_informer_resync_s: float = 0.0
    # Journaled checkpoint persistence (docs/bind-path.md "Checkpoint
    # storage"): mutations append O(delta) records to checkpoint.wal with
    # group commit, compacted into the dual-version snapshot on thresholds
    # and clean shutdown.  False restores the per-mutate full-snapshot
    # write (the bench A/B baseline arm, and the escape hatch for
    # mixed-version windows — an old driver never reads the journal).
    journal: bool = True
    # Coalescing window of the async slice publisher: a burst of health /
    # withheld-set events inside one window costs one rebuild+write.
    publish_debounce_s: float = 0.05
    # Write-through age for the content-hash gate: slices older than this
    # are re-asserted (a real write) even when the rebuilt content is
    # unchanged, so slices lost out-of-band (kubectl delete, an etcd
    # restore) heal within the interval instead of only on restart.
    # <= 0 disables reassertion (every identical rebuild is skipped).
    publish_reassert_s: float = 300.0
    # Seed for the ResourceSlice pool generation.  None (production) lists
    # live slices to outrank a previous process's leftovers; the cluster
    # harness passes 1 against a fresh fake so constructing N hundred
    # drivers costs zero LISTs instead of N scans of a growing slice set.
    initial_pool_generation: Optional[int] = None
    # Clock for the stale-claim GC (tpudra/clock.py seam).  None = the
    # system clock; the chaos soak injects a SkewedClock so its clock_skew
    # fault can step the wall reading under live GC passes and prove the
    # monotonic staleness discipline holds.
    gc_clock: Optional[Clock] = None


class Driver:
    def __init__(
        self,
        config: DriverConfig,
        kube: KubeAPI,
        devicelib: DeviceLib,
        mp_manager: Optional[MultiProcessManager] = None,
        vfio_manager: Optional[VfioManager] = None,
    ):
        self._config = config
        self._kube = kube
        self._lib = devicelib
        os.makedirs(config.plugin_dir, exist_ok=True)
        self._pu_lock_path = os.path.join(config.plugin_dir, PU_LOCK)
        self._checkpoints = CheckpointManager(
            config.plugin_dir, journal=config.journal
        )
        self.state = DeviceState(
            devicelib,
            CDIHandler(config.cdi_root, config.driver_root),
            self._checkpoints,
            config.node_name,
            mp_manager=mp_manager,
            vfio_manager=vfio_manager,
        )
        self._unhealthy: set[str] = set()
        self._unhealthy_lock = lockwitness.make_lock("driver.unhealthy_lock")
        # Per-device last-status-change unix time for the DRAResourceHealth
        # stream; devices absent here report the startup timestamp.
        self._health_changed_at: dict[str, float] = {}
        self._health_start_ts = time.time()
        # Serializes the whole snapshot→build→apply publication path: the
        # health thread and prepare RPC threads both publish, and an
        # interleaving could re-advertise silicon just marked unhealthy.
        self._publish_lock = lockwitness.make_lock("driver.publish_lock")
        # Async publisher state: RPC/health threads bump _publish_seq and
        # notify; the publisher thread debounces, rebuilds once, and
        # advances _publish_done.  Content-hash gate for no-op rebuilds.
        self._publish_cond = lockwitness.make_condition("driver.publish_cond")
        self._publish_seq = 0
        self._publish_done = 0
        self._publisher_thread: Optional[threading.Thread] = None
        self._published_hash: Optional[str] = None
        self._published_slices: list[dict] = []
        self._published_at: Optional[float] = None  # monotonic of last WRITE
        # Seeded from live slices so a restart outranks previous publishes
        # (or from the config when the caller already knows the answer).
        self._pool_generation = (
            config.initial_pool_generation
            if config.initial_pool_generation is not None
            else next_pool_generation(
                kube, config.node_name, alloc.pool_name(config.node_name)
            )
        )
        self._stop = threading.Event()
        # Claim-reference resolution: watch-backed cache with read-through
        # GET fallback and singleflight (claimresolver.py), or the plain
        # per-reference GET when the cache is disabled.
        self._claim_informer: Optional[Informer] = None
        if config.claim_cache:
            self._claim_informer = Informer(
                kube,
                gvr.RESOURCE_CLAIMS,
                resync_period=max(0.0, config.claim_informer_resync_s),
                # The apiserver has no server-side selector for "claims
                # allocated to this driver", so bound the cache client-side:
                # only claims carrying an allocation result for OUR driver
                # are stored (the resolver can only cache-hit those anyway).
                # This also EVICTS a claim the moment it is deallocated, so
                # a later same-uid reallocation can never be served from a
                # pre-deallocation copy.
                cache_filter=self._claim_is_ours,
            )
            resolve_claim = CachedClaimResolver(kube, self._claim_informer)
        else:
            resolve_claim = kube_claim_resolver(kube)
        self._sockets = PluginSockets(
            TPU_DRIVER_NAME,
            config.plugin_dir,
            config.registry_dir,
            prepare=self.prepare_resource_claims,
            unprepare=self.unprepare_resource_claims,
            resolve_claim=resolve_claim,
            # Degraded-mode shed at the wire: the gRPC handlers probe this
            # BEFORE resolving claim references, so a shed costs zero
            # apiserver work even on the kubelet path.
            shed_probe=self.storage_shed_message,
        )
        self.cleanup = CheckpointCleanupManager(
            kube, self.state, unprepare=self._unprepare_serialized,
            clock=config.gc_clock,
        )
        self._health_thread: Optional[threading.Thread] = None
        # Degraded-mode supervisor (started in start()): watches the
        # checkpoint manager's storage-degraded flag, announces the
        # transition (gauge + storage-degraded slice annotation) and
        # drives the heal probe + convergent compaction on a backoff.
        self._storage_heal_thread: Optional[threading.Thread] = None
        # Serializes supervisor (re)starts: the sim and the soak's fault
        # injector both call start_storage_supervisor, and an unguarded
        # alive-check-then-spawn could double the heal loop
        # (tpudra-racegraph pins the lockset).
        self._storage_heal_lock = lockwitness.make_lock("driver.storage_heal_lock")
        # Side-effect fan-out pool.  Threads spawn lazily on first multi-
        # claim batch; single-claim batches run inline on the RPC thread
        # (no hop, no pool wakeup — the common kubelet case).
        self._effects_pool = ThreadPoolExecutor(
            max_workers=max(1, config.prepare_concurrency),
            thread_name_prefix="claim-effects",
        )
        # Per-claim-uid serialization: with the node lock narrowed to the
        # RMW phases, a prepare and an unprepare of the SAME uid could
        # otherwise interleave at the effects phase (prepare returning a
        # grant whose CDI spec a concurrent unprepare just deleted).  One
        # flock file per uid so the guard holds across processes (a
        # restart-overlap sibling driver) as well as threads; unprepare
        # unlinks the file while holding it (see _acquire_claim_lock).
        self._claim_locks_dir = os.path.join(config.plugin_dir, "claims")

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        """Startup order mirrors the reference (driver.go:66-170): destroy
        unknown partitions, serve sockets, start health + GC, publish."""
        if featuregates.enabled(featuregates.DYNAMIC_PARTITIONING):
            n = self.state.destroy_unknown_partitions()
            if n:
                logger.warning("startup reconciliation destroyed %d unknown partitions", n)
        if featuregates.enabled(featuregates.DRA_RESOURCE_HEALTH_SERVICE):
            # Implements-it-then-serve: the broadcaster must exist before the
            # socket starts so the service is registered and advertised
            # (helper semantics, draplugin.go:623-663).
            from tpudra.plugin.healthservice import (
                HealthBroadcaster,
                snapshot_from_driver_state,
            )

            self._sockets.health_broadcaster = HealthBroadcaster(
                snapshot_from_driver_state(
                    allocatable=lambda: self.state.allocatable,
                    unhealthy=self.unhealthy_devices,
                    changed_at=self._health_timestamps,
                    start_ts=int(self._health_start_ts),
                    pool=alloc.pool_name(self._config.node_name),
                )
            )
        self._sockets.start()
        if self._claim_informer is not None:
            # Claim resolution falls back to direct GETs until the initial
            # LIST lands (has_synced) — startup never blocks on the cache.
            self._claim_informer.start(self._stop)
        self._publisher_thread = threading.Thread(
            target=self._publish_loop, daemon=True, name="slice-publisher"
        )
        self._publisher_thread.start()
        if featuregates.enabled(featuregates.TPU_DEVICE_HEALTH_CHECK):
            self._health_thread = threading.Thread(
                target=self._health_loop, daemon=True, name="device-health"
            )
            self._health_thread.start()
        self.start_storage_supervisor()
        self.cleanup.start(self._stop)
        self.publish_resources()

    def stop(self) -> None:
        self._stop.set()
        with self._publish_cond:
            self._publish_cond.notify_all()
        self._sockets.stop()
        self._effects_pool.shutdown(wait=False)
        # The heal supervisor must be OUT before the checkpoint manager
        # closes: a try_recover racing close() could re-open (recreate)
        # checkpoint.wal after the downgrade-gate compaction declared
        # checkpoint.json complete.  Bounded join — the loop polls _stop
        # every ≤2 s and try_recover's flock waits are themselves bounded.
        self._join_storage_supervisor()
        # Clean-shutdown compaction: fold the checkpoint journal into the
        # dual-version snapshot — the downgrade gate (an old driver never
        # reads checkpoint.wal).  Best-effort inside close().
        self._checkpoints.close()
        self._lib.close()

    def crash_stop(self) -> None:
        """Abandon this driver the way a SIGKILL would, minus the process
        death: threads are told to stop and sockets close, but the
        checkpoint journal is NOT compacted (``CheckpointManager.abandon``)
        — on-disk state stays frozen at whatever boundary the last commit
        reached.  The chaos soak (sim/chaos.py) pairs this with
        ``checkpoint.armed_crash`` to kill one simulated node among N in
        one process, then builds a fresh Driver over the same plugin dir,
        which must converge through the REAL recovery path (snapshot +
        journal replay + torn-tail truncation + startup GC), exactly like
        the subprocess crash sweeps prove for a whole plugin process."""
        self._stop.set()
        with self._publish_cond:
            self._publish_cond.notify_all()
        self._sockets.stop()
        self._effects_pool.shutdown(wait=False)
        # A real SIGKILL takes the heal thread with the process; in this
        # in-process stand-in it would live on and a late try_recover
        # could COMPACT the on-disk state the crash froze — join it out
        # before abandoning.
        self._join_storage_supervisor()
        self._checkpoints.abandon()
        self._lib.close()

    @property
    def sockets(self) -> PluginSockets:
        return self._sockets

    @property
    def claim_informer(self) -> Optional[Informer]:
        """The ResourceClaim informer backing claim resolution (None when
        the cache is disabled) — bench/tests wait on its sync."""
        return self._claim_informer

    def wait_for_claim_cache(self, timeout: float = 30.0) -> bool:
        """Block until the claim informer has synced (immediately False
        when the cache is disabled) — steady-state benches start here."""
        if self._claim_informer is None:
            return False
        return self._claim_informer.wait_for_sync(timeout)

    def _claim_is_ours(self, claim: dict) -> bool:
        """Cache-filter predicate: the claim carries an allocation result
        for this driver ON THIS NODE's pool (allocation results carry the
        pool name, which is the node name — alloc.pool_name).  Node
        scoping keeps each plugin's cache O(claims on this node), not
        O(driver claims cluster-wide)."""
        pool = alloc.pool_name(self._config.node_name)
        results = (
            claim.get("status", {})
            .get("allocation", {})
            .get("devices", {})
            .get("results", [])
        )
        return any(
            r.get("driver") == TPU_DRIVER_NAME and r.get("pool") == pool
            for r in results
        )

    # ------------------------------------------------------ prepare/unprepare

    def prepare_resource_claims(self, claims: list[dict]) -> dict:
        if not claims:
            # The health monitor pings with an empty batch (health.py,
            # reference health.go:122) — it must stay lock- and disk-free.
            return {"claims": {}}
        shed = self._shed_if_degraded(claims, "prepare")
        if shed is not None:
            return shed
        t0 = time.monotonic()
        out: dict[str, dict] = {}
        # Any prepare can flip sibling visibility in either direction (a vfio
        # grant withholds the chip; a chip grant withholds the vfio alias) —
        # republish once per batch when the withheld set changed
        # (driver.go:361).  bound_sibling_devices is empty-and-free with
        # passthrough disabled.
        withheld_before = self.state.bound_sibling_devices()
        uids = [c.get("metadata", {}).get("uid", "") for c in claims]
        try:
            with trace.start_span(
                "plugin.prepare",
                attrs={"node": self._config.node_name, "claims": len(claims)},
            ), self._claims_serialized(uids):
                try:
                    # Phase 1 under the node lock: ONE checkpoint RMW
                    # records PrepareStarted (+ rollback/validation) for
                    # the whole batch.
                    with trace.start_span("bind.rmw-begin") as sp, self._locked_pu():
                        t_lock = time.monotonic() - t0
                        sp.set_attr("lock_wait_s", round(t_lock, 6))
                        batch = self.state.begin_prepare(claims)
                    # Phase 2 outside the lock: per-claim side effects,
                    # concurrent across footprint-disjoint claims.
                    with trace.start_span("bind.effects"):
                        self._run_effects(
                            batch.pending(),
                            self.state.run_prepare_effects,
                            "prepare effects",
                        )
                    # Phase 3 under the node lock: ONE checkpoint RMW
                    # completes every claim whose effects succeeded.
                    with trace.start_span("bind.rmw-finish"), self._locked_pu():
                        self.state.finish_prepare(batch)
                except Exception:
                    # Wholesale batch failure with the uid locks still
                    # held: unlink the lock files of claims that never
                    # reached the checkpoint — nothing (no kubelet retry
                    # obligation, no GC record) would ever visit them.
                    self._gc_failed_batch_locks(uids)
                    raise
                for item in batch.items:
                    if item.error is not None:
                        # Failed claims may never see an unprepare (kubelet
                        # only unprepares what prepared), so their lock file
                        # would leak; unlink-while-held is always safe and a
                        # retry recreates it on demand.
                        self._gc_claim_lock(item.uid)
        except Exception as e:  # noqa: BLE001 — lock timeout / checkpoint IO
            self._republish_if_withheld_changed(withheld_before)
            return self._batch_failure(claims, e, "prepare", t0)
        t_prep = time.monotonic() - t0
        # One sample per NodePrepareResources call: with phases batched,
        # claims of a batch have no individual wall time to observe, and
        # N batch-wide samples would inflate the histogram ~N-fold.
        metrics.PREPARE_SECONDS.labels(TPU_DRIVER_NAME).observe(t_prep)
        # Once per CALL, like the histogram sample: these are batch-wide
        # wall times, and a line per claim would overstate per-claim
        # latency ~N-fold to anyone grepping the t_prep hook.
        logger.info(
            "t_prep_lock_acq=%.4fs t_prep=%.4fs claims=%s",
            t_lock, t_prep,
            ",".join(it.uid or "<no uid>" for it in batch.items),
        )
        for item in batch.items:
            if item.error is not None:
                logger.error(
                    "prepare failed for claim %s", item.uid or "<no uid>",
                    exc_info=item.error,
                )
                metrics.PREPARE_ERRORS.labels(TPU_DRIVER_NAME).inc()
                out[item.uid] = {
                    "error": str(item.error),
                    "permanent": isinstance(item.error, PermanentError),
                }
                continue
            out[item.uid] = {
                "devices": [
                    {
                        "requestNames": d.request_names,
                        "poolName": d.pool_name,
                        "deviceName": d.device_name,
                        "cdiDeviceIDs": d.cdi_device_ids,
                    }
                    for d in item.device_results()
                ]
            }
        self._republish_if_withheld_changed(withheld_before)
        return {"claims": out}

    def unprepare_resource_claims(self, claims: list[dict]) -> dict:
        if not claims:
            return {"claims": {}}
        shed = self._shed_if_degraded(claims, "unprepare")
        if shed is not None:
            return shed
        t0 = time.monotonic()
        out: dict[str, dict] = {}
        withheld_before = self.state.bound_sibling_devices()
        uids = [
            ref.get("uid") or ref.get("metadata", {}).get("uid", "")
            for ref in claims
        ]
        try:
            with trace.start_span(
                "plugin.unprepare",
                attrs={"node": self._config.node_name, "claims": len(claims)},
            ), self._claims_serialized(uids):
                try:
                    with trace.start_span("bind.rmw-begin"), self._locked_pu():
                        batch = self.state.begin_unprepare(uids)
                    with trace.start_span("bind.effects"):
                        self._run_effects(
                            batch.pending(),
                            self.state.run_unprepare_effects,
                            "unprepare effects",
                        )
                    with trace.start_span("bind.rmw-finish"), self._locked_pu():
                        self.state.finish_unprepare(batch)
                except Exception:
                    self._gc_failed_batch_locks(uids)
                    raise
                for item in batch.items:
                    if item.done:  # record dropped; lock file is garbage
                        self._gc_claim_lock(item.uid)
        except Exception as e:  # noqa: BLE001 — lock timeout / checkpoint IO
            self._republish_if_withheld_changed(withheld_before)
            return self._batch_failure(claims, e, "unprepare", t0)
        t_unprep = time.monotonic() - t0
        metrics.UNPREPARE_SECONDS.labels(TPU_DRIVER_NAME).observe(t_unprep)
        logger.info(
            "t_unprep=%.4fs claims=%s",
            t_unprep,
            ",".join(it.uid or "<no uid>" for it in batch.items),
        )
        for item in batch.items:
            if item.error is not None:
                logger.error(
                    "unprepare failed for claim %s", item.uid or "<no uid>",
                    exc_info=item.error,
                )
                out[item.uid] = {"error": str(item.error)}
            else:
                out[item.uid] = {}
        self._republish_if_withheld_changed(withheld_before)
        return {"claims": out}

    def _republish_if_withheld_changed(self, withheld_before: set) -> None:
        """Signal a republish when sibling visibility changed — on EVERY
        exit path: even a failed batch may have written PrepareStarted
        records that flip visibility, and the retry samples withheld_before
        after those records exist, so a skipped republish would never
        self-heal.  The RPC thread only signals; the publisher thread owns
        the rebuild+write (no apiserver traffic on the bind hot path)."""
        try:
            if self.state.bound_sibling_devices() != withheld_before:
                self._request_publish()
        except Exception:  # noqa: BLE001 — never mask the RPC result
            logger.exception("republish after prepare/unprepare failed")

    def _unprepare_serialized(self, uid: str) -> None:
        """Single-claim unprepare under the per-uid lock — the GC's entry
        point, so its teardown serializes against kubelet RPCs for the
        same claim."""
        with self._claims_serialized([uid]):
            self.state.unprepare(uid)
            self._gc_claim_lock(uid)

    def _run_effects(self, items: list, effect: Callable, what: str) -> None:
        """Run per-item side effects, fanning footprint-disjoint items
        across the bounded pool.  Failures land in ``item.error`` (per-claim
        fault barrier); items sharing silicon run serially within a group."""
        items = [it for it in items if it.error is None]
        if not items:
            return
        groups = self.state.effect_groups(
            [(it, it.device_names()) for it in items]
        )

        def run_group(group: list) -> None:
            for it in group:
                try:
                    effect(it)
                except Exception as e:  # noqa: BLE001 — per-claim barrier
                    it.error = e

        if len(groups) == 1:
            run_group(groups[0])
            return
        # Pool workers run under a COPY of the calling context so the
        # active trace span's lineage travels into the fan-out (contextvars
        # do not cross executor threads on their own — the resolver pool
        # does the same, grpcserver._resolve_all).
        ctx = contextvars.copy_context()
        futures = [
            self._effects_pool.submit(ctx.copy().run, run_group, g)
            for g in groups
        ]
        for f in futures:
            try:
                f.result()
            except Exception:  # noqa: BLE001 — pool plumbing only
                logger.exception("%s worker failed", what)

    def _batch_failure(
        self, claims: list[dict], e: Exception, op: str, t0: float
    ) -> dict:
        """A batch-wide fault (node lock timeout, unreadable checkpoint):
        every claim of the batch gets the same retryable error (kubelet
        re-calls).  The latency histogram still gets its sample — lock
        timeouts ARE the tail a bind dashboard exists to catch."""
        logger.error("%s batch failed", op, exc_info=e)
        hist = (
            metrics.PREPARE_SECONDS if op == "prepare"
            else metrics.UNPREPARE_SECONDS
        )
        hist.labels(TPU_DRIVER_NAME).observe(time.monotonic() - t0)
        out: dict[str, dict] = {}
        for ref in claims:
            uid = ref.get("uid") or ref.get("metadata", {}).get("uid", "")
            if op == "prepare":
                metrics.PREPARE_ERRORS.labels(TPU_DRIVER_NAME).inc()
            out[uid] = {"error": f"node {op}: {e}", "permanent": False}
        return {"claims": out}

    # ------------------------------------------------- storage-degraded mode

    @property
    def storage_degraded(self) -> bool:
        """True while the checkpoint cannot persist (bind work is shed)."""
        return self._checkpoints.storage_degraded

    def start_storage_supervisor(self) -> None:
        """Start just the storage-heal supervisor (``start()`` includes
        it).  Harnesses that drive a driver without ``start()`` — the
        cluster sim runs hundreds of drivers with no socket/publisher
        threads — call this directly so degraded-mode announce/heal runs
        there exactly as in production.  Idempotent."""
        with self._storage_heal_lock:
            t = self._storage_heal_thread
            if t is not None and t.is_alive():
                return
            self._storage_heal_thread = threading.Thread(
                target=self._storage_heal_loop, daemon=True, name="storage-heal"
            )
            self._storage_heal_thread.start()

    def _join_storage_supervisor(self, timeout: float = 10.0) -> None:
        """Wait the heal supervisor out (``_stop`` must already be set).
        Bounded: an overrunning try_recover (a wedged flock) is logged and
        left to die with the process rather than wedging shutdown."""
        t = self._storage_heal_thread
        if t is None or not t.is_alive():
            return
        t.join(timeout)
        if t.is_alive():
            logger.warning(
                "storage-heal supervisor did not exit within %.0fs; "
                "proceeding with shutdown", timeout,
            )

    def storage_shed_message(self, op: str) -> Optional[str]:
        """The typed degraded-mode refusal for one would-be batch, or None
        while healthy.  A non-None return counts one shed
        (``tpudra_storage_shed_total{op}``) — callers refuse the whole
        batch with it.  Probed by the gRPC handlers BEFORE claim
        resolution and by the in-process batch entry points."""
        detail = self._checkpoints.storage_fault_detail
        if detail is None:
            return None
        metrics.STORAGE_SHED_TOTAL.labels(op).inc()
        return (
            f"{storage.DEGRADED_ERROR_PREFIX} node "
            f"{self._config.node_name}: checkpoint storage cannot persist "
            f"({detail}); shedding {op} until the disk heals (retryable)"
        )

    def _shed_if_degraded(self, refs: list[dict], op: str) -> Optional[dict]:
        """Degraded-mode bind shedding (docs/bind-path.md "Storage fault
        contract"): while the checkpoint storage cannot persist, every
        NodePrepare/NodeUnprepare batch is refused UP FRONT — before any
        flock, checkpoint read, or effect — with a typed, retryable
        per-claim error.  Kubelet retries on its own cadence, nothing
        half-binds against a disk that cannot record it, and the refusal
        is O(1) per claim (the fail-fast p99 the bench's degraded arm
        measures).  Read paths, health, GC scans, and slice publication
        stay up; the storage-heal loop clears the flag."""
        msg = self.storage_shed_message(op)
        if msg is None:
            return None
        logger.info(
            "shedding %s batch of %d claim(s): storage degraded",
            op, len(refs),
        )
        out: dict[str, dict] = {}
        for ref in refs:
            uid = ref.get("uid") or ref.get("metadata", {}).get("uid", "")
            out[uid] = {"error": msg, "permanent": False}
        return {"claims": out}

    def _storage_heal_loop(self) -> None:
        """The degraded-mode supervisor: polls the checkpoint manager's
        storage flag; on the healthy→degraded edge it raises the gauge and
        republishes slices WITH the storage-degraded annotation (so the
        controller's gang placement avoids this node), then drives
        ``CheckpointManager.try_recover`` — heal probe + convergent
        compaction rewrite — on a capped full-jitter backoff; on the
        degraded→healthy edge (probe success, or an organic commit that
        proved the disk durable) it clears the gauge and republishes to
        drop the annotation.  The backoff cap is deliberately small: a
        probe is one tiny durable write, and heal DETECTION latency is
        what the storage-degraded-convergence budget measures."""
        backoff = Backoff(0.25, 2.0)
        announced = False
        while not self._stop.is_set():
            degraded = self._checkpoints.storage_degraded
            if degraded and not announced:
                announced = True
                metrics.STORAGE_DEGRADED.labels(self._config.node_name).set(1)
                logger.error(
                    "node %s entering storage-degraded mode: %s",
                    self._config.node_name,
                    self._checkpoints.storage_fault_detail,
                )
                self._request_publish()
            elif not degraded and announced:
                announced = False
                metrics.STORAGE_DEGRADED.labels(self._config.node_name).set(0)
                logger.warning(
                    "node %s leaving storage-degraded mode (healed)",
                    self._config.node_name,
                )
                backoff.reset()
                self._request_publish()
            if degraded:
                if self._stop.is_set():
                    return  # shutting down: never race close()/abandon()
                if self._checkpoints.try_recover():
                    continue  # next pass observes the flip and announces
                if self._stop.wait(backoff.next_delay()):
                    return
            elif self._stop.wait(1.0):
                return

    def _claim_lock_path(self, uid: str) -> str:
        return os.path.join(self._claim_locks_dir, f"{uid}.lock")

    # tpudra-lock: acquires=flock:claim-uid returns the still-held lock to _claims_serialized
    def _acquire_claim_lock(self, uid: str, deadline: float) -> Flock:
        """Acquire one claim-uid flock, surviving concurrent GC of the lock
        file: after acquiring, re-stat the path — if the file was unlinked
        or replaced between our open and our flock (an unpreparing holder
        unlinks while holding), release and retry on the fresh file."""
        while True:
            # tpudra-lock: id=flock:claim-uid family one lock file per claim uid
            lock = Flock(
                self._claim_lock_path(uid),
                metric_label="claim",
                witness_id="flock:claim-uid",
            )
            lock.acquire(timeout=max(0.0, deadline - time.monotonic()))
            try:
                st = os.stat(lock.path)
            except FileNotFoundError:
                st = None
            if st is not None and os.fstat(lock.fileno()).st_ino == st.st_ino:
                return lock
            lock.release()

    @contextlib.contextmanager
    def _claims_serialized(self, uids):
        """Hold a per-claim-uid flock for the whole phased operation, so
        concurrent prepare/unprepare of the same claim — in this process or
        a sibling driver process — serialize exactly as the old full-width
        node lock made them.  Distinct uids never contend.  Locks are taken
        in sorted order (no deadlock between batches sharing uids) with the
        node-flock timeout: a wedged effects phase must fail same-uid
        retries after PU_LOCK_TIMEOUT, not absorb a gRPC worker thread per
        retry forever."""
        deadline = time.monotonic() + PU_LOCK_TIMEOUT
        locks = []
        try:
            with trace.start_span("bind.flock-wait") as sp:
                for uid in sorted({u for u in uids if u}):
                    locks.append(self._acquire_claim_lock(uid, deadline))
                sp.set_attr("locks", len(locks))
            yield
        finally:
            for lock in reversed(locks):
                lock.release()

    def _gc_failed_batch_locks(self, uids) -> None:
        """Lock-file GC for a WHOLESALE batch failure (a storage-failed
        begin RMW, a checkpoint flock timeout): uids that never reached
        the checkpoint have no retry obligation (kubelet only unprepares
        what prepared) and no GC record, so nothing would ever unlink
        their per-uid lock files — the flock-leak the disk_fault soak
        caught.  Must run INSIDE ``_claims_serialized`` (the locks are
        held: unlink-while-held keeps racing acquirers correct).  Claims
        that DID land a record keep their files — the retry/GC paths own
        those."""
        try:
            recorded = set(self._checkpoints.read_view().prepared_claims)
        except Exception:  # noqa: BLE001 — unreadable checkpoint: keep the files
            logger.info(
                "failed-batch lock GC skipped: checkpoint unreadable",
                exc_info=True,
            )
            return
        for uid in {u for u in uids if u}:
            if uid not in recorded:
                self._gc_claim_lock(uid)

    def _gc_claim_lock(self, uid: str) -> None:
        """Unlink a claim's lock file; call ONLY while holding its lock
        (the unlink-while-held + re-stat-after-acquire protocol keeps
        racing acquirers correct, and the dir from growing with every
        claim the node has ever seen)."""
        with contextlib.suppress(OSError):
            os.unlink(self._claim_lock_path(uid))

    def _pu_lock(self):
        """A fresh Flock per operation: one shared instance cannot be
        acquired twice, but kubelet issues concurrent prepare RPCs — each
        call gets its own fd and the kernel serializes across both threads
        and processes."""
        return Flock(self._pu_lock_path)  # tpudra-lock: id=flock:pu.lock the node-global prepare/unprepare lock

    @contextlib.contextmanager
    def _locked_pu(self):
        """Acquire the node-global lock for one RMW phase, feeding the wait
        into the per-phase bind histogram.  The wait comes back from the
        acquire itself (not instance state): a concurrent same-path acquire
        through another Flock object can never clobber the sample."""
        lock = self._pu_lock()
        with lock(timeout=PU_LOCK_TIMEOUT) as waited:
            metrics.observe_phase(metrics.PHASE_LOCK_WAIT, waited)
            yield lock

    # ---------------------------------------------------------- publication

    def _request_publish(self) -> None:
        """Signal the publisher thread and return immediately.  Without a
        live publisher (a driver used directly, never start()ed — unit
        tests, bench harnesses) publication runs inline so the signal is
        never silently dropped."""
        thread = self._publisher_thread
        if thread is None or not thread.is_alive():
            self.publish_resources()
            return
        with self._publish_cond:
            self._publish_seq += 1
            if racewitness.enabled():
                racewitness.note_access("Driver._publish_seq")
                racewitness.note_hb_send("driver.publish_cond")
            # notify_all: drain_publishes waiters share this condition, and
            # a bare notify() could wake one of them instead of the
            # publisher, stalling the publish until the 1 s poll timeout.
            self._publish_cond.notify_all()

    def drain_publishes(self, timeout: float = 5.0) -> bool:
        """Block until every signalled publish has been absorbed by a
        rebuild (tests and orderly shutdown; True on drained)."""
        deadline = time.monotonic() + timeout
        with self._publish_cond:
            while self._publish_seq != self._publish_done:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._publish_cond.wait(remaining)
            if racewitness.enabled():
                racewitness.note_hb_recv("driver.publish_cond")
            return True

    def _needs_reassert(self) -> bool:
        """True when the last actual slice write is older than the
        reassert interval — published state lost out-of-band must not hide
        behind the content-hash gate forever."""
        interval = self._config.publish_reassert_s
        return (
            interval > 0
            and self._published_at is not None
            and time.monotonic() - self._published_at > interval
        )

    def _publish_loop(self) -> None:
        """The dedicated publisher: waits for a signal, debounces so a
        burst of health/withheld events coalesces into one rebuild, then
        publishes.  Signals landing during a rebuild trigger another pass,
        so the last event always reaches the apiserver.  A FAILED publish
        keeps its signals pending (``_publish_done`` does not advance) and
        retries after a capped-exponential full-jitter backoff (shared
        tpudra/backoff.py policy; reset by the next success) — one
        transient apiserver error must not eat a coalesced burst, and at
        cluster scale N nodes' publishers failing on one apiserver flap
        must not retry in lockstep.  Idle wakeups re-assert aged slices
        through the hash gate (``publish_reassert_s``)."""
        retry = Backoff(0.5, 15.0)
        while True:
            with self._publish_cond:
                while (
                    self._publish_seq == self._publish_done
                    and not self._stop.is_set()
                    and not self._needs_reassert()
                ):
                    self._publish_cond.wait(1.0)
                if racewitness.enabled():
                    racewitness.note_hb_recv("driver.publish_cond")
            if self._stop.is_set():
                return
            # Coalescing window — outside every lock (BLOCK-UNDER-LOCK).
            if self._stop.wait(self._config.publish_debounce_s):
                return  # shutting down: don't race teardown with a write
            with self._publish_cond:
                target = self._publish_seq
            try:
                self.publish_resources(force=self._needs_reassert())
            except Exception:  # noqa: BLE001 — publisher must survive API blips
                delay = retry.next_delay()
                logger.exception(
                    "async slice publication failed; retrying in %.1fs", delay
                )
                self._stop.wait(delay)
                continue  # signals stay pending: the loop retries them
            retry.reset()
            with self._publish_cond:
                absorbed = target - self._publish_done - 1
                self._publish_done = target
                if racewitness.enabled():
                    racewitness.note_access("Driver._publish_done")
                    racewitness.note_hb_send("driver.publish_cond")
                self._publish_cond.notify_all()  # wake drain_publishes waiters
            if absorbed > 0:
                metrics.SLICE_PUBLISH_COALESCED.labels(TPU_DRIVER_NAME).inc(
                    absorbed
                )

    def _slice_content_hash(self, res) -> str:
        """Digest of everything that determines the published slice set
        EXCEPT the pool generation (which changes every write by design —
        hashing it would defeat the no-op gate)."""
        content = json.dumps(
            {
                "pool": res.pool_name,
                "devices": res.devices,
                "sharedCounters": res.shared_counters,
                "partitionable": res.partitionable,
                "k8sMinor": self._config.k8s_minor,
                # The health annotation can change while the device list
                # does not (an already-withheld sibling going unhealthy) —
                # it must reach the apiserver either way.
                "unhealthyCount": res.unhealthy_count,
                # Same shape for the storage-degraded flag: an
                # annotation-only transition must still write.
                "storageDegraded": res.storage_degraded,
            },
            sort_keys=True,
        )
        return hashlib.sha256(content.encode()).hexdigest()

    def publish_resources(
        self,
        force: bool = False,
        applier: Optional[Callable[[list[dict], str, str], None]] = None,
    ) -> list[dict]:
        """Rebuild and publish this node's ResourceSlices.  A rebuild whose
        content hashes identical to the last successful publish skips the
        API write entirely (``tpudra_resourceslice_publish_noop_total``) —
        ``force=True`` writes regardless (restart-style reassertion).
        ``applier`` overrides the write step (slices, node_name,
        name_prefix → apiserver): the cluster harness passes a
        ``BulkSlicePublisher`` so hundreds of co-located drivers share one
        existence LIST instead of paying 3 requests per node; driver-side
        bookkeeping (generation, content hash) is identical either way."""
        # Storage-degraded flag read OUTSIDE the publish lock (it has its
        # own lock, and a mid-publish flip is indistinguishable from one
        # an instant later — the heal loop republishes on every edge).
        storage_degraded = self._checkpoints.storage_degraded
        # The span opens BEFORE the publish lock and closes after it: its
        # exit (a log append) must never run under the lock.
        with trace.start_span(
            "plugin.publish", attrs={"node": self._config.node_name}
        ), self._publish_lock:
            partitionable = featuregates.enabled(featuregates.DYNAMIC_PARTITIONING)
            with self._unhealthy_lock:
                unhealthy = set(self._unhealthy)
            res = generate_driver_resources(
                self.state.allocatable,
                unhealthy=unhealthy,
                # tpudra-lint: disable=BLOCK-UNDER-LOCK-IP publish_lock is the publisher thread's top-of-hierarchy lock — nothing on the bind path ever waits on it, and the withheld-set snapshot must be atomic with the build (docs/lock-order.md)
                withheld=self.state.bound_sibling_devices(),
                partitionable=partitionable,
                node_name=self._config.node_name,
            )
            # Storage-degraded flag rides every published slice so the
            # controller's spare selection can avoid this node without
            # node access (controller/gang.py published_slice_health).
            res.storage_degraded = storage_degraded
            # Gauge before the gate: the unhealthy SET can change without
            # changing slice content (an already-withheld sibling going
            # unhealthy), and monitoring must see it either way.
            metrics.UNHEALTHY_DEVICES.labels(TPU_DRIVER_NAME).set(len(unhealthy))
            content_hash = self._slice_content_hash(res)
            if not force and content_hash == self._published_hash:
                metrics.SLICE_PUBLISH_NOOP.labels(TPU_DRIVER_NAME).inc()
                logger.debug(
                    "slice publish skipped: content unchanged (%d devices)",
                    len(res.devices),
                )
                return self._published_slices
            slices = build_resource_slices(
                res,
                self._config.node_name,
                k8s_minor=self._config.k8s_minor,
                generation=self._pool_generation,
            )
            self._pool_generation += 1
            name_prefix = f"{self._config.node_name}-{TPU_DRIVER_NAME}-"
            if applier is not None:
                applier(slices, self._config.node_name, name_prefix)
            else:
                # tpudra-lint: disable=BLOCK-UNDER-LOCK-IP deliberate: publish_lock serializes snapshot→build→write so an interleaved publish can never re-advertise silicon just marked unhealthy; it is the top of the hierarchy (no lock is ever taken while it is held by another thread's bind path) and only the publisher thread holds it in steady state (docs/lock-order.md)
                publish_slices(
                    self._kube,
                    slices,
                    self._config.node_name,
                    name_prefix,
                )
            self._published_hash = content_hash
            self._published_slices = slices
            self._published_at = time.monotonic()
            metrics.SLICE_PUBLISH_TOTAL.labels(TPU_DRIVER_NAME).inc()
            logger.info(
                "published %d ResourceSlice(s), %d devices, %d unhealthy",
                len(slices), len(res.devices), len(unhealthy),
            )
            return slices

    # --------------------------------------------------------------- health

    def _health_loop(self) -> None:
        for event in self._lib.health_events(self._stop):
            try:
                self._handle_health_event(event)
            except Exception:  # noqa: BLE001
                logger.exception("handling health event %s", event)

    def _handle_health_event(self, event: HealthEvent) -> None:
        if event.kind in self._config.health_ignored_kinds:
            logger.info("ignoring health event %s on %s", event.kind, event.chip_uuid)
            return
        names = self._devices_for_event(event)
        if not names:
            logger.warning("health event %s for unknown silicon %s", event.kind, event.chip_uuid)
            return
        with self._unhealthy_lock:
            before = set(self._unhealthy)
            self._unhealthy.update(names)
            changed = self._unhealthy != before
            if changed:
                now = time.time()
                for name in self._unhealthy - before:
                    self._health_changed_at[name] = now
        if changed:
            logger.error(
                "marking unhealthy after %s (%s): %s — republishing without them",
                event.kind, event.detail, sorted(names),
            )
            # Signal, don't publish: a cascade of health events (a chip
            # taking its partitions down one event at a time) coalesces
            # into one rebuild inside the publisher's debounce window.
            self._request_publish()
            if self._sockets.health_broadcaster is not None:
                self._sockets.health_broadcaster.notify()
            # Escalate to BOUND claims: withholding from future slices does
            # nothing for a claim already holding the silicon.  Outside
            # every lock — this walks the checkpoint view and writes claim
            # status through the apiserver.
            self._escalate_unhealthy(names, event)

    def _escalate_unhealthy(self, names: set[str], event: HealthEvent) -> None:
        """Cross-reference freshly-unhealthy devices against the
        checkpoint's bound claims (copy-free ``read_view``) and surface the
        fault on each affected claim's status.  Failures are counted, not
        raised — the health loop must keep consuming events."""
        try:
            cp = self._checkpoints.read_view()
        except Exception:  # noqa: BLE001 — a torn checkpoint: publish already warned
            logger.exception("health escalation could not read the checkpoint")
            return
        for uid, rec in cp.prepared_claims.items():
            held = [
                d for d in rec.all_devices() if d.canonical_name in names
            ]
            if not held:
                continue
            devices = [
                {
                    "driver": TPU_DRIVER_NAME,
                    "pool": d.pool_name or alloc.pool_name(self._config.node_name),
                    "device": d.canonical_name,
                }
                for d in held
            ]
            message = (
                f"{event.kind}: device(s) "
                f"{', '.join(sorted(d.canonical_name for d in held))} "
                f"on node {self._config.node_name} went unhealthy under "
                "this bound claim"
            )
            try:
                written = escalate_claim_condition(
                    self._kube, rec.namespace, rec.name, uid, devices,
                    reason=event.kind, message=message,
                )
            except Exception:  # noqa: BLE001 — apiserver blip: count and move on
                logger.exception("health escalation failed for claim %s", uid)
                metrics.CLAIM_HEALTH_ESCALATIONS.labels("failed").inc()
                continue
            if written:
                logger.warning(
                    "escalated %s to bound claim %s/%s (%s)",
                    event.kind, rec.namespace, rec.name, uid,
                )
                metrics.CLAIM_HEALTH_ESCALATIONS.labels("written").inc()

    def _devices_for_event(self, event: HealthEvent) -> set[str]:
        if event.partition_uuid:
            for name, dev in self.state.allocatable.items():
                if (
                    dev.live_partition is not None
                    and dev.live_partition.uuid == event.partition_uuid
                ):
                    return {name}
        return {
            name
            for name, dev in self.state.allocatable.items()
            if dev.chip.uuid == event.chip_uuid
        }

    def unhealthy_devices(self) -> set[str]:
        with self._unhealthy_lock:
            return set(self._unhealthy)

    def _health_timestamps(self) -> dict[str, float]:
        with self._unhealthy_lock:
            return dict(self._health_changed_at)

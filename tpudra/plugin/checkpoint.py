"""Versioned, checksummed node-local checkpoint with a journaled delta layer.

The analog of gpu-kubelet-plugin/{checkpoint,checkpointv}.go: a JSON file that
is the node-local source of truth for idempotent prepare, partition teardown,
channel-conflict detection, and stale-claim GC.  Both V1 and V2 payloads are
written on every snapshot, each with its own checksum, so that *both* driver
upgrade and downgrade find a checkpoint they can read (reference
checkpoint.go:10-47, checkpointv.go:24-82).

- V1 (legacy shape): claim UID → prepared device list only.
- V2: adds per-claim prepare status (PrepareStarted/PrepareCompleted) and the
  claim's namespace/name (needed by the stale-claim GC to validate claims
  against the API server by name+UID, reference cleanup.go:150).

Reads prefer V2 and fall back to V1 — including when V2 is present but fails
its checksum (loudly: an error log plus the
``tpudra_checkpoint_version_fallbacks_total`` counter), which is the whole
point of the dual write: a torn/corrupt newer payload degrades to the older
one instead of wedging every prepare on the node.  Only when *no* version
passes its checksum does the read raise.  Unknown fields are tolerated
(non-strict) so checkpoints written by newer drivers parse (reference
api.go:54-58).

**Journaled persistence (docs/bind-path.md "Checkpoint storage").**  With the
journal enabled (the default), a mutation no longer re-encodes and fsyncs the
whole dual-version snapshot: ``mutate(fn, touched=[uids])`` applies the
mutator against the cached state and appends CRC-framed *delta* records
(claim upsert / drop / status transition) to ``checkpoint.wal`` — O(delta)
bytes and ONE fsync, regardless of how many claims are resident.  Concurrent
in-process mutators (RPC threads, the GC thread, the batch engine) **group
commit**: they enqueue their closures, one leader takes the ``cp.lock``
flock, applies the whole queue, and issues a single fsync for the batch.
Compaction — size/record-count thresholds, clean shutdown (``close()``), any
legacy ``touched=None`` mutate, and degraded-read finalization — folds the
journal into a fresh dual-version snapshot via ``write()`` (temp file fsync +
``os.replace`` + directory fsync) and truncates the journal *after* the
replace, so a crash anywhere between leaves a snapshot plus stale journal
records whose replay is idempotent.  Recovery replays the journal over the
snapshot, truncating at the first torn/CRC-bad tail record — loudly
(``tpudra_checkpoint_journal_truncations_total``).

**Downgrade contract.**  A journal written by this driver is invisible to
older drivers (they read only ``checkpoint.json``), so state is current for
them only after a compaction: downgrade requires the clean-shutdown compact
(``close()``, wired into both plugins' ``stop()``), or any prior threshold
compaction covering the final records.  ``--no-journal`` restores the
per-mutate full-snapshot behavior for mixed-version windows.

Reads are served from an in-memory cache validated by the stat triples
(mtime_ns, size, inode) of BOTH files: the bind path re-reads the checkpoint
several times per claim, and each disk read costs open + JSON decode + CRC +
journal replay.  Another process's write changes a stat triple and
invalidates the cache.  ``read()`` hands out deep copies (safe for mutating
callers); ``read_view()`` hands out an immutable shared view for scan-heavy
read-only callers (stale-claim GC, resourceslice rebuild).
"""

from __future__ import annotations

import contextlib
import copy
import errno
import json
import logging
import os
import threading
import time
import zlib
from dataclasses import dataclass, field
from types import MappingProxyType
from typing import Callable, Iterable, Optional

from tpudra import lockwitness, metrics, storage, trace, walwitness
from tpudra.api import serde
from tpudra.flock import Flock, FlockTimeout
from tpudra.plugin import journal as journal_mod

logger = logging.getLogger(__name__)

# Labelled counter children resolved once (labels() is registry-locked and
# the bind path reads the checkpoint several times per claim).
_READS_CACHE = metrics.CHECKPOINT_READS_TOTAL.labels("cache")
_READS_DISK = metrics.CHECKPOINT_READS_TOTAL.labels("disk")
_BYTES_JOURNAL = metrics.CHECKPOINT_BYTES_WRITTEN_TOTAL.labels("journal")
_BYTES_SNAPSHOT = metrics.CHECKPOINT_BYTES_WRITTEN_TOTAL.labels("snapshot")
_FSYNC_JOURNAL = metrics.CHECKPOINT_FSYNCS_TOTAL.labels("journal")
_FSYNC_SNAPSHOT = metrics.CHECKPOINT_FSYNCS_TOTAL.labels("snapshot")
_FSYNC_DIR = metrics.CHECKPOINT_FSYNCS_TOTAL.labels("dir")

PREPARE_STARTED = "PrepareStarted"
PREPARE_COMPLETED = "PrepareCompleted"

CHECKPOINT_FILE = "checkpoint.json"
CHECKPOINT_JOURNAL = "checkpoint.wal"
CHECKPOINT_LOCK = "cp.lock"

#: Journal compaction thresholds (env-overridable: the crash sweeps force a
#: compaction on the first commit via TPUDRA_JOURNAL_MAX_RECORDS=1, and an
#: operator can tune replay-at-recovery cost against write amplification).
DEFAULT_JOURNAL_MAX_BYTES = 256 * 1024
DEFAULT_JOURNAL_MAX_RECORDS = 1024


class SimulatedCrash(BaseException):
    """An in-process stand-in for SIGKILL at a checkpoint boundary.

    Deliberately a ``BaseException``: every ``except Exception`` fault
    barrier on the bind path (per-claim isolation, batch failure mapping)
    must let it through, exactly as a real SIGKILL runs no handlers — the
    harness that armed it catches it at the top of its own call and then
    abandons the driver instance (``Driver.crash_stop``), so on-disk
    state is frozen at the boundary just as a process death leaves it.
    ``finally`` blocks do still run (releasing flocks), which matches the
    kernel's behavior at process exit: flocks are released when the fds
    close, so recovery sees the same lock state either way."""

    def __init__(self, point: str):
        super().__init__(f"simulated crash at checkpoint boundary {point!r}")
        self.point = point


_crash_tls = threading.local()


@contextlib.contextmanager
def armed_crash(point: str):
    """Arm an IN-PROCESS crash for the current thread: the next time this
    thread reaches the named checkpoint boundary, ``_crashpoint`` raises
    :class:`SimulatedCrash` instead of SIGKILLing the process.  The chaos
    soak (sim/chaos.py) uses this to kill one simulated node's driver at
    a random boundary while the other N-1 nodes — same process — keep
    running; the subprocess crash sweeps keep the env-armed real SIGKILL.
    Thread-local by construction: a boundary reached by any other thread
    (another node's bind, a GC pass) never fires."""
    prev = getattr(_crash_tls, "point", None)
    _crash_tls.point = point
    try:
        yield
    finally:
        _crash_tls.point = prev


def _crashpoint(point: str) -> None:
    """Injectable SIGKILL for the process-level crash-consistency sweeps
    (tests/test_crash_sweep*.py): when TPUDRA_CRASHPOINT names this
    checkpoint boundary, die with no cleanup — the restarted plugin must
    converge from the checkpoint alone (SURVEY §3.4's three GC layers;
    reference device_state.go:223-242,337).  Two-key arming: the kill also
    requires TPUDRA_TEST_HOOKS=1, so a single leaked env var in a copied
    manifest cannot turn every production prepare into a crash loop.
    Unarmed cost: one env read and string compare per boundary (plus one
    thread-local read for the in-process arming, see ``armed_crash``)."""
    if getattr(_crash_tls, "point", None) == point:
        logger.warning("crashpoint %s armed in-process: simulating crash", point)
        raise SimulatedCrash(point)
    if (
        os.environ.get("TPUDRA_CRASHPOINT") == point
        and os.environ.get("TPUDRA_TEST_HOOKS") == "1"
    ):
        import signal

        logger.warning("crashpoint %s armed: SIGKILL self", point)
        os.kill(os.getpid(), signal.SIGKILL)


class CheckpointError(Exception):
    pass


class ChecksumMismatch(CheckpointError):
    pass


@dataclass
class PreparedDevice:
    """One granted device as recorded in the checkpoint (prepared.go:31)."""

    canonical_name: str = field(default="", metadata={"json": "canonicalName"})
    type: str = field(default="", metadata={"json": "type"})  # chip|partition|vfio|channel|daemon
    pool_name: str = field(default="", metadata={"json": "poolName"})
    request_names: list[str] = field(default_factory=list, metadata={"json": "requestNames"})
    cdi_device_ids: list[str] = field(default_factory=list, metadata={"json": "cdiDeviceIDs"})
    # Hardware identity needed for unprepare/rollback: chip uuid, live
    # partition uuid + spec, vfio PCI address, channel id.
    attributes: dict[str, str] = field(default_factory=dict, metadata={"json": "attributes"})


@dataclass
class PreparedDeviceGroup:
    """Devices sharing one resolved config (prepared.go:44), plus the config
    state needed to undo it (MPS daemon id, timeslice reset, CDI ids)."""

    devices: list[PreparedDevice] = field(default_factory=list, metadata={"json": "devices"})
    config_state: dict[str, str] = field(default_factory=dict, metadata={"json": "configState"})


@dataclass
class PreparedClaim:
    uid: str = field(default="", metadata={"json": "uid"})
    namespace: str = field(default="", metadata={"json": "namespace"})
    name: str = field(default="", metadata={"json": "name"})
    status: str = field(default=PREPARE_STARTED, metadata={"json": "status"})
    groups: list[PreparedDeviceGroup] = field(default_factory=list, metadata={"json": "groups"})
    # Traceparent of the bind that journaled this record (tpudra/trace.py):
    # crash recovery and retry-rollback emit their spans into the ORIGINAL
    # trace.  None (dropped by serde.encode) when the bind ran untraced,
    # so untraced checkpoints are byte-identical to pre-trace ones.
    traceparent: Optional[str] = field(default=None, metadata={"json": "traceparent"})

    def all_devices(self) -> list[PreparedDevice]:
        return [d for g in self.groups for d in g.devices]


@dataclass
class Checkpoint:
    prepared_claims: dict[str, PreparedClaim] = field(
        default_factory=dict, metadata={"json": "preparedClaims"}
    )


def _checksum(data: str) -> int:
    return zlib.crc32(data.encode())


def _encode_v2(cp: Checkpoint) -> str:
    return json.dumps(serde.encode(cp), sort_keys=True)


def _decode_v2(data: str) -> Checkpoint:
    return serde.decode(Checkpoint, json.loads(data), strict=False)


def _encode_v1(cp: Checkpoint) -> str:
    """Legacy shape: uid → flat device list, extended for fallback fidelity.

    The flat ``devices`` list is what legacy readers expect; alongside it
    ride ``namespace``/``name`` (without which the stale-claim GC can never
    reclaim a fallen-back claim) and per-group ``groups`` with their
    ``configState`` (without which a started claim's ``plannedPartitions``
    is lost — the retry's rollback becomes a silent no-op and crashed-
    prepare partitions leak — and a multi-group claim's teardown state,
    timeslice/mp UUIDs, is truncated to one group).  Legacy readers decode
    non-strict and ignore the extras."""
    claims = {}
    for uid, claim in cp.prepared_claims.items():
        entry: dict = {"devices": [serde.encode(d) for d in claim.all_devices()]}
        entry["status"] = claim.status
        if claim.namespace:
            entry["namespace"] = claim.namespace
        if claim.name:
            entry["name"] = claim.name
        if any(g.config_state for g in claim.groups) or len(claim.groups) > 1:
            entry["groups"] = [
                {
                    "devices": [serde.encode(d) for d in g.devices],
                    "configState": g.config_state,
                }
                for g in claim.groups
            ]
        claims[uid] = entry
    return json.dumps({"preparedClaims": claims}, sort_keys=True)


def _decode_v1(data: str) -> Checkpoint:
    raw = json.loads(data)
    cp = Checkpoint()
    for uid, entry in raw.get("preparedClaims", {}).items():
        if "groups" in entry:
            # This driver's fallback payload: faithful group structure.
            groups = [
                PreparedDeviceGroup(
                    devices=[
                        serde.decode(PreparedDevice, d, strict=False)
                        for d in g.get("devices", [])
                    ],
                    config_state=dict(g.get("configState", {})),
                )
                for g in entry["groups"]
            ]
        else:
            groups = [
                PreparedDeviceGroup(
                    devices=[
                        serde.decode(PreparedDevice, d, strict=False)
                        for d in entry.get("devices", [])
                    ]
                )
            ]
        devices = [d for g in groups for d in g.devices]
        # V1 written by THIS driver carries an explicit status (the claim-
        # level field covers started claims with empty device lists — the
        # cdplugin's shape — which no device-derived heuristic can).  V1
        # written by an OLD driver has none: every claim in it was fully
        # prepared — except that 'planned'-type devices only ever belong to
        # a PrepareStarted claim, which must take the retry/rollback path,
        # never be served as a completed cached grant (its devices have no
        # CDI ids and no spec file).
        status = entry.get("status") or (
            PREPARE_STARTED
            if any(d.type == "planned" for d in devices)
            else PREPARE_COMPLETED
        )
        cp.prepared_claims[uid] = PreparedClaim(
            uid=uid,
            namespace=entry.get("namespace", ""),
            name=entry.get("name", ""),
            status=status,
            groups=groups,
        )
    return cp


@dataclass
class _Mutation:
    """One enqueued mutate(): the closure, its touched-uid contract, and the
    completion flags the group-commit leader publishes under the commit
    condition."""

    fn: Callable[[Checkpoint], Optional[Checkpoint]]
    touched: Optional[list[str]]
    done: bool = False
    error: Optional[BaseException] = None


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        logger.warning("ignoring non-integer %s=%r", name, os.environ.get(name))
        return default


def _threshold(value: Optional[int], env: str, default: int) -> int:
    """A compaction threshold: explicit argument over env over default —
    `is None` (not falsy-or), so an explicit 0 is refused loudly instead
    of silently ignored (a zero threshold would compact on EVERY commit,
    an O(state) write per mutation that defeats the journal)."""
    if value is None:
        value = _env_int(env, default)
    if value <= 0:
        logger.warning(
            "%s=%r is not a positive threshold; using %d", env, value, default
        )
        return default
    return value


class CheckpointManager:
    """Atomic read/write of the dual-version checkpoint file, with a
    journaled, group-committed read-mutate-write helper (reference
    device_state.go:555-582) and a stat-validated in-memory read cache."""

    def __init__(
        self,
        plugin_dir: str,
        journal: Optional[bool] = None,
        journal_max_bytes: Optional[int] = None,
        journal_max_records: Optional[int] = None,
    ):
        self._path = os.path.join(plugin_dir, CHECKPOINT_FILE)
        self._lock_path = os.path.join(plugin_dir, CHECKPOINT_LOCK)
        os.makedirs(plugin_dir, exist_ok=True)
        if journal is None:
            journal = os.environ.get("TPUDRA_NO_JOURNAL", "").lower() not in (
                "1", "true",
            )
        self._journal_enabled = journal
        self._journal = journal_mod.Journal(
            os.path.join(plugin_dir, CHECKPOINT_JOURNAL)
        )
        self._journal_max_bytes = _threshold(
            journal_max_bytes, "TPUDRA_JOURNAL_MAX_BYTES",
            DEFAULT_JOURNAL_MAX_BYTES,
        )
        self._journal_max_records = _threshold(
            journal_max_records, "TPUDRA_JOURNAL_MAX_RECORDS",
            DEFAULT_JOURNAL_MAX_RECORDS,
        )
        # (stat-pair key, decoded checkpoint).  read() hands out copies;
        # read_view() shares the cached graph read-only — writers REPLACE
        # the cached object (copy-on-write per commit), never mutate it.
        self._cache: Optional[tuple[tuple, Checkpoint]] = None
        self._cache_lock = lockwitness.make_lock("checkpoint.cache_lock")
        # Group-commit queue: mutators enqueue under the condition; the
        # first to find no active leader leads — it takes the cp.lock
        # flock, drains the queue, persists the whole batch with one
        # fsync, and publishes per-entry results here.  The flock is NEVER
        # acquired while the condition is held (FLOCK-INVERSION).
        self._commit_cond = lockwitness.make_condition("checkpoint.commit_cond")
        self._commit_queue: list[_Mutation] = []
        self._commit_leader = False
        # Leader-only incremental view (touched only under the cp.lock
        # flock): the applied state plus the journal position it reflects,
        # so a steady-state commit replays a sibling process's few new
        # records instead of re-reading O(state) from disk.
        self._applied_state: Optional[Checkpoint] = None
        self._applied_snap_key: Optional[tuple] = None
        self._applied_jrn_ino: Optional[int] = None
        self._applied_jrn_offset = 0
        self._journal_records = 0
        #: Base snapshot lacks a version (old-driver file): the next
        #: commit forces a migrating dual-version snapshot write.
        self._snapshot_needs_migration = False
        # Storage-degraded state (docs/bind-path.md "Storage fault
        # contract"): set when a commit fails with a storage errno
        # (ENOSPC/EIO/EROFS/…), cleared when a durable write provably
        # succeeds again (an organic commit, or try_recover's probe +
        # compaction).  The Driver reads this to shed bind work.
        self._storage_lock = lockwitness.make_lock("checkpoint.storage_lock")
        self._storage_fault: Optional[str] = None

    @property
    def path(self) -> str:
        return self._path

    @property
    def journal_path(self) -> str:
        return self._journal.path

    # ------------------------------------------------- storage-degraded mode

    @property
    def storage_degraded(self) -> bool:
        with self._storage_lock:
            return self._storage_fault is not None

    @property
    def storage_fault_detail(self) -> Optional[str]:
        """Why persistence is degraded (None = healthy) — the detail the
        shed path's typed error carries back to kubelet."""
        with self._storage_lock:
            return self._storage_fault

    def _note_storage_failure(self, op: str, e: OSError) -> None:
        detail = f"{op}: [{errno.errorcode.get(e.errno or 0, e.errno)}] {e}"
        with self._storage_lock:
            first = self._storage_fault is None
            self._storage_fault = detail
        if first:
            logger.error(
                "checkpoint storage DEGRADED at %s — persistence is shed "
                "until a heal probe or a commit proves the disk durable "
                "again (%s)", self._path, detail,
            )

    def _mark_storage_ok(self) -> bool:
        with self._storage_lock:
            was, self._storage_fault = self._storage_fault, None
        if was:
            logger.warning(
                "checkpoint storage HEALED at %s (was: %s)", self._path, was
            )
        return was is not None

    def try_recover(self, timeout: float = 5.0) -> bool:
        """Heal detection + convergent recovery, the degraded-mode exit
        path: (1) probe — one durable atomic write of ``.storage-probe``
        in the checkpoint dir proves the disk takes fsynced writes again;
        (2) rewrite — under the cp.lock flock, reload state from byte
        zero (only known-durable bytes plus journal replay are trusted
        after a fail-stop poison) and compact it into a fresh dual-version
        snapshot, truncating the WAL.  Returns True when storage is (now)
        healthy; False keeps the caller's backoff loop going.  Safe to
        call concurrently with commits — everything runs under the same
        flock the group-commit leader takes."""
        if not self.storage_degraded:
            return True
        probe = os.path.join(
            os.path.dirname(self._path) or ".", ".storage-probe"
        )
        try:
            storage.atomic_replace(probe, b"ok\n", site="storage-probe")
        except OSError:
            return False  # still broken; detail stays as first noted
        try:
            with Flock(self._lock_path)(timeout=timeout):  # tpudra-lock: id=flock:cp.lock same per-store lock file as every commit
                # Full reload: the incremental base was discarded at
                # poison time; only a from-byte-zero parse may repair.
                self._applied_state = None
                state, degraded = self._load_locked()
                if degraded:
                    self._preserve_corrupt()
                self._compact_locked(state, "storage-heal")
        except (OSError, FlockTimeout, CheckpointError) as e:
            logger.warning(
                "storage heal compaction failed; staying degraded: %s", e
            )
            if isinstance(e, OSError) and storage.is_storage_error(e):
                self._note_storage_failure("heal compaction", e)
            return False
        return not self.storage_degraded

    def _stat_key(self) -> Optional[tuple[int, int, int]]:
        try:
            st = os.stat(self._path)
        except FileNotFoundError:
            return None
        # The inode guards against the mtime granularity of coarse
        # filesystems: every write lands via os.replace, so a new file
        # always means a new inode.
        return (st.st_mtime_ns, st.st_size, st.st_ino)

    # ----------------------------------------------------------------- reads

    def read(self) -> Checkpoint:
        return self._read_flagged()[0]

    def read_view(self) -> Checkpoint:
        """A read-only snapshot WITHOUT the per-read deep copy: the claims
        map is a ``MappingProxyType`` over the cached graph, shared with
        every other view of the same generation.  Safe because writers
        replace the cached object wholesale (copy-on-write per commit) and
        never mutate it in place.  Scan-heavy read-only callers (stale-
        claim GC, resourceslice sibling-visibility rebuild) use this;
        anything that mutates what it read must use ``read()``."""
        jkey = self._journal.stat_key()
        skey = self._stat_key()
        with self._cache_lock:
            cached = self._cache
        if cached is not None and cached[0] == (skey, jkey):
            _READS_CACHE.inc()
            return Checkpoint(
                prepared_claims=MappingProxyType(cached[1].prepared_claims)
            )
        cp, _ = self._read_flagged()
        return Checkpoint(prepared_claims=MappingProxyType(cp.prepared_claims))

    def _read_flagged(self, bypass_cache: bool = False) -> tuple[Checkpoint, bool]:
        """(checkpoint, degraded) — snapshot + journal replay; fresh
        checkpoint when neither file exists.  degraded means a corrupt
        newer snapshot version was skipped and an older payload served.

        Served from the in-memory cache when BOTH stat triples are
        unchanged since the last read/write (unless ``bypass_cache`` —
        the no-journal RMW needs disk-true freshness).  Stats are taken
        BEFORE the disk reads: if another process writes in between, the
        cache holds newer content under an older key and the next read
        simply misses — never the reverse (stale content under a new key).

        Consistency without a lock: the journal is read BEFORE the
        snapshot and accepted only if its stat is unchanged afterwards.
        Compaction replaces the snapshot FIRST and truncates the journal
        after, so a stable journal plus a possibly-newer snapshot is at
        worst "new snapshot + stale records", whose replay is idempotent
        (the snapshot already contains their effects); an empty journal
        means the replace it followed is already visible to our later
        snapshot read.  A moving journal stat (concurrent append's partial
        frame, or a compaction's truncate) triggers a retry — writers all
        serialize on the cp.lock flock, so the pair stabilizes; if churn
        outlasts the retries we serve the last pair with a warning (plain
        reads tolerate a transiently stale view; every state-WRITING read
        path runs under the flock and never gets here)."""
        result = None
        for attempt in range(8):
            jkey = self._journal.stat_key()
            skey = self._stat_key()
            if skey is None and jkey is None:
                return Checkpoint(), False
            key = (skey, jkey)
            if not bypass_cache:
                with self._cache_lock:
                    cached = self._cache
                if cached is not None and cached[0] == key:
                    _READS_CACHE.inc()
                    # Deepcopy outside the mutex: the cached object is
                    # never mutated in place (writers replace the tuple
                    # wholesale), so concurrent readers need not serialize
                    # on an O(size) copy.
                    return copy.deepcopy(cached[1]), False
            t0 = time.monotonic()
            jdata = self._journal.read_bytes()
            pair = self._read_disk()
            if bypass_cache or self._journal.stat_key() == jkey:
                result = (key, jdata, pair, t0)
                break
        if result is None:
            logger.warning(
                "checkpoint journal kept moving across %d read attempts; "
                "serving the last (possibly transiently stale) view", attempt + 1
            )
            result = (key, jdata, pair, t0)
        key, jdata, (cp, degraded, _versions), t0 = result
        torn = self._replay(cp, jdata)
        _READS_DISK.inc()
        metrics.observe_phase(
            metrics.PHASE_CHECKPOINT_READ, time.monotonic() - t0
        )
        if not degraded and not torn:
            # A version-fallback or torn-tail read is deliberately NOT
            # cached: caching it would make the corruption signal loud
            # exactly once and then silent — every read of a damaged file
            # must re-log and re-count until a commit repairs it.
            with self._cache_lock:
                self._cache = (key, copy.deepcopy(cp))
        # Recovery seeding: a record loaded from disk IS journaled intent —
        # without this, the post-restart sweep's effects would be witnessed
        # as journal-less and flagged as false ordering violations.
        walwitness.note_journal(cp.prepared_claims.keys())
        return cp, degraded

    @staticmethod
    def _apply_record(cp: Checkpoint, record: dict) -> None:
        """Apply one journal delta record in place (replay; ``cp`` must be
        a private object — the leader's incremental path copies first)."""
        op = record.get("op")
        uid = record.get("uid", "")
        if op == "upsert":
            cp.prepared_claims[uid] = serde.decode(
                PreparedClaim, record.get("claim", {}), strict=False
            )
        elif op == "drop":
            cp.prepared_claims.pop(uid, None)
        elif op == "status":
            claim = cp.prepared_claims.get(uid)
            if claim is None:
                logger.warning(
                    "journal status record for unknown claim %s: skipped", uid
                )
            else:
                claim.status = record.get("status", claim.status)
        else:
            # Forward compat: a newer driver's record kind degrades to a
            # loud skip, not a wedged node (mirrors non-strict decode).
            logger.warning("unknown journal record op %r: skipped", op)

    def _replay(self, cp: Checkpoint, jdata: bytes) -> bool:
        """Replay journal bytes over ``cp``; True when a torn tail was
        dropped (loud + counted — recovery semantics, docs/bind-path.md)."""
        if not jdata:
            return False
        records, good, torn = journal_mod.decode_records(jdata)
        if torn:
            logger.error(
                "checkpoint journal has a torn/corrupt tail: replaying %d "
                "record(s) (%d of %d bytes) and dropping the rest",
                len(records), good, len(jdata),
            )
            metrics.CHECKPOINT_JOURNAL_TRUNCATIONS_TOTAL.inc()
        for record in records:
            self._apply_record(cp, record)
        return torn

    def _read_disk(self) -> tuple[Checkpoint, bool, frozenset]:
        """Decode the newest snapshot version that passes its checksum.
        Returns (checkpoint, degraded, versions-present) — degraded means
        a newer corrupt version was skipped and an older payload served;
        the version set lets the commit path force a migrating snapshot
        when an old driver's file (v1-only) is the base."""
        try:
            with open(self._path) as f:
                envelope = json.load(f)
        except FileNotFoundError:
            return Checkpoint(), False, frozenset()
        except ValueError as e:
            raise CheckpointError(f"corrupt checkpoint envelope: {e}") from e
        versions = frozenset(envelope) & {"v1", "v2"}
        corrupt: list[str] = []
        for version, decode in (("v2", _decode_v2), ("v1", _decode_v1)):
            entry = envelope.get(version)
            if not entry:
                continue
            data, checksum = entry.get("data", ""), entry.get("checksum")
            if _checksum(data) != checksum:
                corrupt.append(version)
                logger.error(
                    "checkpoint %s checksum mismatch (got %s, want %s): "
                    "trying an older version",
                    version, checksum, _checksum(data),
                )
                continue
            cp = decode(data)
            if corrupt:
                # Loud fallback: the older payload may lack newer-version
                # state (V1 has no PrepareStarted claims), so an ACTUAL
                # successful fallback must be visible in logs and metrics —
                # counted only here, not when every version is corrupt and
                # the read raises below.
                logger.error(
                    "checkpoint fell back to %s (corrupt: %s)",
                    version, ", ".join(corrupt),
                )
                metrics.CHECKPOINT_FALLBACKS_TOTAL.inc()
            return cp, bool(corrupt), versions
        if corrupt:
            raise ChecksumMismatch(
                "checkpoint has no version with a valid checksum "
                f"(corrupt: {', '.join(corrupt)})"
            )
        raise CheckpointError("checkpoint has no readable version")

    # ---------------------------------------------------------------- writes

    def write(self, cp: Checkpoint) -> None:
        """Durably replace the dual-version snapshot, truncate the journal
        it supersedes, and prime the read cache.

        Durability order: temp-file fsync → ``os.replace`` → DIRECTORY
        fsync (without which a crash can lose the rename itself) → journal
        truncate.  A crash between the replace and the truncate leaves
        stale journal records whose replay over the new snapshot is
        idempotent (the snapshot already contains their effects) — the
        ``mid-compaction`` crash sweep proves the convergence.

        Cache contract: the cache holds ``cp`` by REFERENCE (a deepcopy per
        write was measurable on the bind path) — after write() the caller
        must not mutate ``cp``.  mutate() guarantees this (its return value
        is unused by design); read() hands out copies, never the cached
        object."""
        t0 = time.monotonic()
        v1, v2 = _encode_v1(cp), _encode_v2(cp)
        envelope = {
            "v1": {"data": v1, "checksum": _checksum(v1)},
            "v2": {"data": v2, "checksum": _checksum(v2)},
        }
        data = json.dumps(envelope)
        tmp = self._path + ".tmp"
        tf_wall, tf0 = time.time(), time.perf_counter()
        try:
            # The whole tmp-fsync → replace → dir-fsync idiom lives in the
            # storage seam: a failed tmp fsync raises BEFORE the replace,
            # so the good on-disk snapshot is never overwritten by bytes
            # whose durability the kernel just declined to promise (the
            # fail-stop snapshot contract; pinned by
            # test_failed_snapshot_fsync_never_replaces_good_file).
            storage.atomic_replace(
                self._path, data.encode(), site="checkpoint-snapshot",
                tmp_path=tmp,
            )
        except OSError as e:
            if storage.is_storage_error(e):
                self._note_storage_failure("snapshot write", e)
            raise
        trace.record_span(
            "checkpoint.fsync", tf_wall, time.perf_counter() - tf0,
            attrs={"kind": "snapshot", "bytes": len(data)},
        )
        _FSYNC_SNAPSHOT.inc()
        _FSYNC_DIR.inc()
        _BYTES_SNAPSHOT.inc(len(data))
        # Snapshot replace + dir fsync landed: every record in it is
        # durable intent (noted before the crashpoint below).
        walwitness.note_journal(cp.prepared_claims.keys())
        _crashpoint("mid-compaction")
        jkey = self._journal.stat_key()
        try:
            if jkey is not None and jkey[1] > 0:
                self._journal.truncate_locked(0)
        except OSError as e:
            # The snapshot IS durable (replace + dir fsync landed), so the
            # mutation this write carries is acknowledged correctly; the
            # stale journal records left behind replay idempotently over
            # it.  Storage stays flagged degraded — truncation failing
            # means the disk is still refusing work.
            logger.warning(
                "journal truncate after snapshot replace failed (replay "
                "stays idempotent): %s", e
            )
            if storage.is_storage_error(e):
                self._note_storage_failure("journal truncate", e)
        else:
            self._mark_storage_ok()
        # The stats are taken after the replace/truncate, so the key matches
        # exactly what a subsequent read would see for this content.
        key = (self._stat_key(), self._journal.stat_key())
        with self._cache_lock:
            self._cache = (key, cp) if key[0] is not None else None
        metrics.observe_phase(
            metrics.PHASE_CHECKPOINT_WRITE, time.monotonic() - t0
        )

    def mutate(
        self,
        fn: Callable[[Checkpoint], Optional[Checkpoint]],
        timeout: float = 10.0,
        touched: Optional[Iterable[str]] = None,
    ) -> None:
        """Group-committed read-mutate-write.  Returns nothing: the final
        object is cached by reference (write()'s contract), so handing it
        out would invite cache-poisoning mutations — re-``read()`` for a
        copy.

        ``touched`` is the delta contract: the uids (a superset is fine)
        whose claims ``fn`` may add, remove, or mutate — everything else it
        may only READ.  With it, persistence is O(delta): the commit
        appends upsert/drop/status records for the touched claims that
        actually changed.  Without it (``touched=None``), ``fn`` may do
        anything the old API allowed — mutate any claim in place or return
        a replacement — and the commit falls back to a full snapshot
        write.  With the journal disabled, every mutate takes the
        un-batched flock + full-write path regardless.

        A mutate over a degraded read FINALIZES the fallback — the commit
        re-encodes both versions with valid checksums from the fallback
        payload, after which the corruption signal stops firing and the
        newer-version-only state is gone.  So before overwriting, the
        corrupt original is preserved at ``<path>.corrupt`` for inspection
        or manual repair, and the finalization itself is logged loudly."""
        if not self._journal_enabled:
            self._mutate_snapshot(fn, timeout)
            return
        mutation = _Mutation(
            fn=fn, touched=None if touched is None else list(touched)
        )
        lead = False
        deadline = time.monotonic() + timeout
        # One RETRO span per mutate (trace.record_span — plain counters,
        # the cheapest instrumentation the layer has): a follower's
        # duration IS its group-commit wait; the leader's covers flock +
        # apply + fsync — the "why was this bind slow" attribution the
        # phase histogram aggregates away.
        t_wall, t0 = time.time(), time.perf_counter()
        try:
            with self._commit_cond:
                self._commit_queue.append(mutation)
                while not mutation.done and self._commit_leader:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 and mutation in self._commit_queue:
                        # Still queued (no leader drained it): abandoning is
                        # safe, and honors this CALLER's timeout instead of
                        # silently inheriting the leader's.  Once drained, the
                        # leader owns it and we must see the outcome through.
                        self._commit_queue.remove(mutation)
                        raise FlockTimeout(
                            "timeout waiting for checkpoint group commit "
                            f"after {timeout}s"
                        )
                    self._commit_cond.wait(min(1.0, max(0.05, remaining)))
                if not mutation.done:
                    self._commit_leader = True
                    lead = True
            if lead:
                try:
                    self._lead_commit(timeout)
                finally:
                    with self._commit_cond:
                        self._commit_leader = False
                        self._commit_cond.notify_all()
        finally:
            trace.record_span(
                "checkpoint.commit", t_wall, time.perf_counter() - t0,
                attrs={"led": lead},
            )
        if mutation.error is not None:
            raise mutation.error

    def _lead_commit(self, timeout: float) -> None:
        """The group-commit leader: one flock, the whole queue, one fsync.
        The queue is drained AFTER the flock lands — mutations enqueued
        while the leader waited ride this batch, which is where batching
        under contention comes from."""
        batch: list[_Mutation] = []
        try:
            with Flock(self._lock_path)(timeout=timeout):  # tpudra-lock: id=flock:cp.lock the leader takes the store's one commit lock
                with self._commit_cond:
                    batch = list(self._commit_queue)
                    self._commit_queue.clear()
                self._commit_batch_locked(batch)
        except BaseException as e:  # noqa: BLE001 — flock timeout / IO error
            # A batch-wide fault (the flock timed out, the checkpoint is
            # unreadable): every entry of this batch — including any still
            # queued — gets the error; callers retry exactly as the
            # un-batched path made them.
            with self._commit_cond:
                batch.extend(self._commit_queue)
                self._commit_queue.clear()
                for m in batch:
                    if not m.done:
                        m.error = m.error or e
                        m.done = True
                self._commit_cond.notify_all()
            return
        with self._commit_cond:
            for m in batch:
                m.done = True
            self._commit_cond.notify_all()

    def _preserve_corrupt(self) -> None:
        """Keep the corrupt original at ``<path>.corrupt`` before a commit
        finalizes a degraded (fallback) read."""
        corrupt_path = self._path + ".corrupt"
        try:
            with open(self._path, "rb") as src:
                storage.write_file(corrupt_path, src.read(), site="corrupt-preserve")
        except OSError:
            logger.exception(
                "cannot preserve corrupt checkpoint at %s", corrupt_path
            )
        logger.error(
            "finalizing degraded checkpoint: rewriting all versions "
            "from the fallback payload; original preserved at %s",
            corrupt_path,
        )

    def _load_locked(self) -> tuple[Checkpoint, bool]:
        """Disk-true state for a commit (caller holds the cp.lock flock).

        Steady state is O(delta): when the snapshot stat is unchanged and
        the journal only grew (the invariant: every truncation is paired
        with a snapshot replace, so same-snapshot ⇒ append-only journal),
        only the bytes past the leader's last-known offset are read and
        replayed — copy-on-write, so previously handed-out read views stay
        immutable.  Anything else (fresh manager, sibling compaction,
        degraded snapshot) is a full reload."""
        snap_key = self._stat_key()
        jkey = self._journal.stat_key()
        jrn_ino = jkey[2] if jkey is not None else None
        jrn_size = jkey[1] if jkey is not None else 0
        if (
            self._applied_state is not None
            and snap_key == self._applied_snap_key
            and jrn_ino == self._applied_jrn_ino
            and jrn_size >= self._applied_jrn_offset
        ):
            if jrn_size == self._applied_jrn_offset:
                _READS_CACHE.inc()
                return self._applied_state, False
            # A sibling process appended: replay just its records.
            data = self._journal.read_bytes(self._applied_jrn_offset)
            records, good, torn = journal_mod.decode_records(data)
            if not torn and good == len(data):
                work = Checkpoint(
                    prepared_claims=dict(self._applied_state.prepared_claims)
                )
                for record in records:
                    self._apply_record_cow(work, record)
                self._applied_state = work
                self._applied_jrn_offset += good
                self._journal_records += len(records)
                _READS_CACHE.inc()
                return work, False
            # A torn frame inside the incremental window is NOT repaired
            # from here: the stat-pair match is not collision-proof across
            # processes (the same caveat _mutate_snapshot documents for
            # its cache bypass), and on a collision these bytes could be a
            # sibling's REWRITTEN journal read at a stale offset —
            # truncating would destroy its fsynced records.  Discard the
            # incremental base and let the whole-file reload below decide;
            # only a from-byte-zero parse may repair.
            logger.warning(
                "incremental journal window did not decode cleanly at "
                "offset %d; falling back to a full reload",
                self._applied_jrn_offset,
            )
            self._applied_state = None
        t0 = time.monotonic()
        jdata = self._journal.read_bytes()
        cp, degraded, versions = self._read_disk()
        # A base written by a different driver generation (v1-only file
        # from a pre-V2 driver, or v2-only from some future one) must not
        # linger under an ever-growing journal: the first commit over it
        # forces a full snapshot, restoring the dual-version envelope —
        # the migrate-on-first-write property the journal would otherwise
        # defer to an arbitrary later compaction.
        self._snapshot_needs_migration = bool(versions) and versions != {
            "v1", "v2",
        }
        records, good, torn = journal_mod.decode_records(jdata)
        if torn:
            logger.error(
                "checkpoint journal has a torn/corrupt tail: replaying %d "
                "record(s) and truncating to %d of %d bytes",
                len(records), good, len(jdata),
            )
            metrics.CHECKPOINT_JOURNAL_TRUNCATIONS_TOTAL.inc()
            self._journal.truncate_locked(good)
        for record in records:
            self._apply_record(cp, record)
        _READS_DISK.inc()
        metrics.observe_phase(
            metrics.PHASE_CHECKPOINT_READ, time.monotonic() - t0
        )
        if degraded:
            # Don't adopt a degraded view as the incremental base: if this
            # commit dies before finalizing, the next one must re-read and
            # re-detect (the corruption signal stays loud).
            self._applied_state = None
            return cp, True
        jkey = self._journal.stat_key()
        self._applied_state = cp
        self._applied_snap_key = self._stat_key()
        self._applied_jrn_ino = jkey[2] if jkey is not None else None
        self._applied_jrn_offset = good
        self._journal_records = len(records)
        return cp, False

    def _apply_record_cow(self, work: Checkpoint, record: dict) -> None:
        """Apply a sibling's record to ``work`` without mutating claim
        objects shared with handed-out read views: the in-place ``status``
        op copies its target first (upsert/drop already bind fresh
        objects into ``work``'s private dict)."""
        if record.get("op") == "status":
            uid = record.get("uid", "")
            claim = work.prepared_claims.get(uid)
            if claim is not None:
                work.prepared_claims[uid] = copy.deepcopy(claim)
        self._apply_record(work, record)

    def _commit_batch_locked(self, batch: list[_Mutation]) -> None:
        """Apply every queued mutation against the cached state, persist
        the result — delta records + ONE fsync, or a full snapshot when the
        batch contains a legacy/finalizing entry — and prime the caches.
        Runs under the cp.lock flock and NO in-process lock."""
        t0 = time.monotonic()
        state, degraded = self._load_locked()
        if degraded:
            self._preserve_corrupt()
        # Copy-on-write working state: a fresh top-level dict per commit,
        # fresh objects only for the claims this batch touches — handed-out
        # read views keep the previous generation's graph, untouched.
        work = Checkpoint(prepared_claims=dict(state.prepared_claims))
        records: list[dict] = []
        # not journal_enabled: a commit racing close() — the shutdown
        # compaction already ran or is imminent, so appending would write
        # records a downgraded driver never sees; snapshot instead.
        force_snapshot = (
            degraded
            or self._snapshot_needs_migration
            or not self._journal_enabled
        )
        for m in batch:
            try:
                if m.touched is None:
                    # Legacy contract: fn may mutate anything or return a
                    # replacement.  Isolate on a scratch copy so a fn that
                    # mutates and THEN raises cannot poison the batch.
                    scratch = copy.deepcopy(work)
                    out = m.fn(scratch)
                    # isinstance, not is-not-None: incidental returns (a
                    # lambda ending in dict.pop) must not become the state.
                    work = out if isinstance(out, Checkpoint) else scratch
                    force_snapshot = True
                else:
                    records.extend(self._apply_delta(work, m))
            except BaseException as e:  # noqa: BLE001 — per-entry barrier
                m.error = e
        if force_snapshot:
            self.write(work)
            self._journal_records = 0
            self._snapshot_needs_migration = False
        elif records:
            payloads = [journal_mod.encode_record(r) for r in records]
            tf_wall, tf0 = time.time(), time.perf_counter()
            try:
                n, dir_synced = self._journal.append_locked(payloads)
            except OSError as e:
                # Fail-stop: the append poisoned and rolled back the fd
                # (journal.Journal.append_locked); everything derived past
                # the last known-durable byte is untrusted, so the leader's
                # incremental base and the read cache are dropped — the
                # next commit (or try_recover) re-reads from disk.  The
                # whole batch fails un-acknowledged: _lead_commit's
                # batch-wide barrier hands every caller this error.
                self._applied_state = None
                with self._cache_lock:
                    self._cache = None
                if storage.is_storage_error(e):
                    self._note_storage_failure("journal append", e)
                raise
            self._mark_storage_ok()
            # After the fsync, before any crashpoint: the records ARE
            # durable intent now, even if the process dies next line.
            walwitness.note_journal(r.get("uid", "") for r in records)
            trace.record_span(
                "checkpoint.fsync", tf_wall, time.perf_counter() - tf0,
                attrs={"kind": "journal", "records": len(records)},
            )
            _FSYNC_JOURNAL.inc()
            if dir_synced:
                _FSYNC_DIR.inc()
            _BYTES_JOURNAL.inc(n)
            metrics.CHECKPOINT_JOURNAL_RECORDS_TOTAL.inc(len(records))
            self._journal_records += len(records)
            _crashpoint("post-journal-append")
            jkey = self._journal.stat_key()
            if self._journal_records >= self._journal_max_records:
                self._compact_locked(work, "records")
            elif jkey is not None and jkey[1] >= self._journal_max_bytes:
                self._compact_locked(work, "size")
            else:
                key = (self._stat_key(), jkey)
                with self._cache_lock:
                    self._cache = (key, work)
        else:
            # Nothing durable changed (idempotent hits, all-error batch):
            # zero disk writes, but the assembled state is still the
            # freshest view — prime the caches.
            key = (self._stat_key(), self._journal.stat_key())
            with self._cache_lock:
                self._cache = (key, work)
        metrics.CHECKPOINT_GROUP_COMMIT_BATCH_SIZE.observe(len(batch))
        metrics.observe_phase(
            metrics.PHASE_CHECKPOINT_WRITE, time.monotonic() - t0
        )
        jkey = self._journal.stat_key()
        self._applied_state = work
        self._applied_snap_key = self._stat_key()
        self._applied_jrn_ino = jkey[2] if jkey is not None else None
        self._applied_jrn_offset = jkey[1] if jkey is not None else 0

    def _apply_delta(self, work: Checkpoint, m: _Mutation) -> list[dict]:
        """Run one touched-contract mutator against ``work`` and derive its
        delta records.  The touched claims are copied in first (CoW), the
        pre-images kept for the diff and for rollback if ``fn`` raises."""
        pre: dict[str, Optional[PreparedClaim]] = {}
        for uid in dict.fromkeys(m.touched or ()):
            cur = work.prepared_claims.get(uid)
            pre[uid] = cur
            if cur is not None:
                work.prepared_claims[uid] = copy.deepcopy(cur)
        keys_before = set(work.prepared_claims)
        # Untouched-claim integrity guard, armed under the test suite and
        # the crash harnesses only (an O(state) deepcopy per commit): an
        # in-place write to a claim OUTSIDE the touched set would poison
        # the cache generation shared with read_view() AND emit no record
        # (silently lost on restart) — the key-set drift check below
        # cannot see it, so CI enforces the contract where production
        # relies on it.
        guarded = (
            "PYTEST_CURRENT_TEST" in os.environ
            or os.environ.get("TPUDRA_TEST_HOOKS") == "1"
        )
        untouched_copy: dict[str, PreparedClaim] = {}
        if guarded:
            untouched_copy = {
                uid: copy.deepcopy(claim)
                for uid, claim in work.prepared_claims.items()
                if uid not in pre
            }
        try:
            out = m.fn(work)
            # Incidental return values (a lambda ending in dict.pop/update)
            # are fine; only an actual replacement-Checkpoint return — the
            # legacy contract delta mode cannot honor — is refused.
            if isinstance(out, Checkpoint) and out is not work:
                raise CheckpointError(
                    "a delta mutate (touched=[...]) must mutate in place, "
                    "not return a replacement checkpoint"
                )
            drifted = (set(work.prepared_claims) ^ keys_before) - set(pre)
            if drifted:
                raise CheckpointError(
                    "delta mutate added/removed claims outside its touched "
                    f"set: {sorted(drifted)} — widen `touched` or use "
                    "touched=None"
                )
            if guarded:
                dirty = [
                    uid
                    for uid, snapshot in untouched_copy.items()
                    if work.prepared_claims.get(uid) != snapshot
                ]
                if dirty:
                    raise CheckpointError(
                        "delta mutate modified claims outside its touched "
                        f"set in place: {sorted(dirty)} — the change would "
                        "poison the shared cache generation and never be "
                        "persisted; widen `touched` or use touched=None"
                    )
        except BaseException:
            # This entry contributes nothing: its touched claims roll back
            # so the rest of the batch commits from a clean state.
            for uid, old in pre.items():
                if old is None:
                    work.prepared_claims.pop(uid, None)
                else:
                    work.prepared_claims[uid] = old
            raise
        records: list[dict] = []
        for uid, old in pre.items():
            new = work.prepared_claims.get(uid)
            if new is None:
                if old is not None:
                    records.append({"op": "drop", "uid": uid})
                continue
            if old == new:
                continue
            if (
                old is not None
                and old.status != new.status
                and old.groups == new.groups
                and old.namespace == new.namespace
                and old.name == new.name
            ):
                records.append(
                    {"op": "status", "uid": uid, "status": new.status}
                )
                continue
            records.append(
                {"op": "upsert", "uid": uid, "claim": serde.encode(new)}
            )
        return records

    def _compact_locked(self, state: Checkpoint, reason: str) -> None:
        """Fold the journal into a fresh dual-version snapshot (write()
        replaces the snapshot, then truncates the journal).  After this,
        a downgraded driver reading only checkpoint.json is current."""
        logger.info(
            "compacting checkpoint journal (%s): %d record(s) fold into "
            "the snapshot", reason, self._journal_records,
        )
        self.write(state)
        self._journal_records = 0
        metrics.CHECKPOINT_COMPACTIONS_TOTAL.labels(reason).inc()

    def close(self) -> None:
        """Clean-shutdown compaction: fold any journal remainder into the
        dual-version snapshot.  This is the DOWNGRADE GATE — an older
        driver never reads checkpoint.wal, so its view is current only
        after this compact (docs/bind-path.md "Checkpoint storage").
        Best-effort: a failure leaves the journal in place for the next
        journal-aware start to replay.

        Straggler-safe: any in-flight group commit is waited out first,
        then journaling is switched off so a mutate that races shutdown
        (the GC thread mid-cycle) takes the full-snapshot path — its
        state lands in checkpoint.json, never in a WAL record written
        AFTER the gate compaction (which a downgraded driver would lose).
        The append fd is closed under the flock, so it can never be
        closed out from under a committing leader."""
        if not self._journal_enabled:
            self._journal.close()
            return
        with self._commit_cond:
            deadline = time.monotonic() + 10.0
            while self._commit_leader and time.monotonic() < deadline:
                self._commit_cond.wait(1.0)
            # From here every mutate — including a group commit already
            # queued — persists via a full snapshot (_commit_batch_locked
            # treats disabled journaling as force_snapshot).
            self._journal_enabled = False
        try:
            # The fd closes only while the flock is held: every journal
            # write happens under cp.lock, so under it no leader — not
            # even one that outlived the drain deadline — can be mid-
            # append on the fd we close.
            with Flock(self._lock_path)(timeout=5.0):  # tpudra-lock: id=flock:cp.lock same store lock; excludes a mid-append leader
                jkey = self._journal.stat_key()
                if jkey is not None and jkey[1] > 0:
                    state, degraded = self._load_locked()
                    if degraded:
                        self._preserve_corrupt()
                    self._compact_locked(state, "shutdown")
                self._journal.close()
        except Exception:  # noqa: BLE001 — shutdown must not wedge on IO
            # The flock never landed (a sibling or an overrunning leader
            # holds it): the fd stays OPEN — closing it without the flock
            # could land mid-append.  One fd leaks in an exiting process;
            # the journal stays for the next journal-aware start to replay.
            logger.exception(
                "clean-shutdown checkpoint compaction failed; journal left "
                "in place for the next start to replay"
            )

    def abandon(self) -> None:
        """Drop this manager WITHOUT the clean-shutdown compaction: the
        journal stays on disk exactly as the last group commit left it —
        the on-disk state a SIGKILL would leave.  The chaos harness's
        ``Driver.crash_stop`` uses this to model a plugin crash in-process
        (a fresh manager over the same dir then takes the REAL recovery
        path: snapshot + journal replay with torn-tail truncation).  Only
        the append fd is released, under the flock so it can never close
        out from under a committing leader; if the flock cannot be taken
        the fd is deliberately leaked in the abandoned instance — the same
        tradeoff close() documents."""
        with self._commit_cond:
            self._journal_enabled = False  # no further appends from here
        try:
            with Flock(self._lock_path)(timeout=5.0):  # tpudra-lock: id=flock:cp.lock same store lock; close must not race an append
                self._journal.close()
        except Exception:  # noqa: BLE001 — abandoning must not wedge
            logger.warning(
                "abandon: could not take the checkpoint flock; leaking the "
                "journal fd in the abandoned instance"
            )

    def _mutate_snapshot(
        self, fn: Callable[[Checkpoint], Optional[Checkpoint]], timeout: float
    ) -> None:
        """The pre-journal RMW (``--no-journal``): flock-guarded read,
        mutate, full dual-version write — every mutate pays O(state) and
        its own fsyncs, the A/B baseline arm and the mixed-version escape
        hatch.  (A journal left behind by an earlier journaling run is
        still replayed by the read and folded into the write's snapshot.)"""
        # Fresh Flock per mutate: one shared instance cannot be acquired
        # twice, but in-process callers DO overlap (the GC thread mutates
        # while RPC threads mutate) — each needs its own fd so the kernel
        # serializes them instead of a RuntimeError failing the batch.
        with Flock(self._lock_path)(timeout=timeout):  # tpudra-lock: id=flock:cp.lock fresh fd, same per-store lock file
            # Bypass the read cache inside the RMW: the stat triple is not
            # collision-proof across processes (inode recycling + coarse
            # mtime), and a false cache hit here would write a stale
            # checkpoint back — the one path where the cache could corrupt
            # durable state.  Plain reads keep the cache; the RMW pays one
            # disk read for bulletproof freshness.
            cp, degraded = self._read_flagged(bypass_cache=True)
            out = fn(cp)
            # Only an actual Checkpoint return replaces the state: the
            # delta contract blesses incidental returns (a lambda ending
            # in dict.pop), and this arm must not diverge by writing a
            # popped claim out as the whole checkpoint.
            cp = out if isinstance(out, Checkpoint) else cp
            if degraded:
                self._preserve_corrupt()
            self.write(cp)

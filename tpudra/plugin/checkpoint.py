"""Versioned, checksummed node-local checkpoint.

The analog of gpu-kubelet-plugin/{checkpoint,checkpointv}.go: a JSON file that
is the node-local source of truth for idempotent prepare, partition teardown,
channel-conflict detection, and stale-claim GC.  Both V1 and V2 payloads are
written on every mutation, each with its own checksum, so that *both* driver
upgrade and downgrade find a checkpoint they can read (reference
checkpoint.go:10-47, checkpointv.go:24-82).

- V1 (legacy shape): claim UID → prepared device list only.
- V2: adds per-claim prepare status (PrepareStarted/PrepareCompleted) and the
  claim's namespace/name (needed by the stale-claim GC to validate claims
  against the API server by name+UID, reference cleanup.go:150).

Reads prefer V2 and fall back to V1 — including when V2 is present but fails
its checksum (loudly: an error log plus the
``tpudra_checkpoint_version_fallbacks_total`` counter), which is the whole
point of the dual write: a torn/corrupt newer payload degrades to the older
one instead of wedging every prepare on the node.  Only when *no* version
passes its checksum does the read raise.  Unknown fields are tolerated
(non-strict) so checkpoints written by newer drivers parse (reference
api.go:54-58).

Reads are served from an in-memory cache validated by stat (mtime_ns, size,
inode): the bind path re-reads the checkpoint several times per claim under
an uncontended lock, and each disk read costs open + JSON decode + CRC.
Another process's write (the file is flock-coordinated and replaced
atomically) changes the stat triple and invalidates the cache.
"""

from __future__ import annotations

import copy
import json
import logging
import os
import time
import zlib
from dataclasses import dataclass, field
from typing import Callable, Optional

from tpudra import lockwitness, metrics
from tpudra.api import serde
from tpudra.flock import Flock

logger = logging.getLogger(__name__)

# Labelled counter children resolved once (labels() is registry-locked and
# the bind path reads the checkpoint several times per claim).
_READS_CACHE = metrics.CHECKPOINT_READS_TOTAL.labels("cache")
_READS_DISK = metrics.CHECKPOINT_READS_TOTAL.labels("disk")

PREPARE_STARTED = "PrepareStarted"
PREPARE_COMPLETED = "PrepareCompleted"

CHECKPOINT_FILE = "checkpoint.json"
CHECKPOINT_LOCK = "cp.lock"


class CheckpointError(Exception):
    pass


class ChecksumMismatch(CheckpointError):
    pass


@dataclass
class PreparedDevice:
    """One granted device as recorded in the checkpoint (prepared.go:31)."""

    canonical_name: str = field(default="", metadata={"json": "canonicalName"})
    type: str = field(default="", metadata={"json": "type"})  # chip|partition|vfio|channel|daemon
    pool_name: str = field(default="", metadata={"json": "poolName"})
    request_names: list[str] = field(default_factory=list, metadata={"json": "requestNames"})
    cdi_device_ids: list[str] = field(default_factory=list, metadata={"json": "cdiDeviceIDs"})
    # Hardware identity needed for unprepare/rollback: chip uuid, live
    # partition uuid + spec, vfio PCI address, channel id.
    attributes: dict[str, str] = field(default_factory=dict, metadata={"json": "attributes"})


@dataclass
class PreparedDeviceGroup:
    """Devices sharing one resolved config (prepared.go:44), plus the config
    state needed to undo it (MPS daemon id, timeslice reset, CDI ids)."""

    devices: list[PreparedDevice] = field(default_factory=list, metadata={"json": "devices"})
    config_state: dict[str, str] = field(default_factory=dict, metadata={"json": "configState"})


@dataclass
class PreparedClaim:
    uid: str = field(default="", metadata={"json": "uid"})
    namespace: str = field(default="", metadata={"json": "namespace"})
    name: str = field(default="", metadata={"json": "name"})
    status: str = field(default=PREPARE_STARTED, metadata={"json": "status"})
    groups: list[PreparedDeviceGroup] = field(default_factory=list, metadata={"json": "groups"})

    def all_devices(self) -> list[PreparedDevice]:
        return [d for g in self.groups for d in g.devices]


@dataclass
class Checkpoint:
    prepared_claims: dict[str, PreparedClaim] = field(
        default_factory=dict, metadata={"json": "preparedClaims"}
    )


def _checksum(data: str) -> int:
    return zlib.crc32(data.encode())


def _encode_v2(cp: Checkpoint) -> str:
    return json.dumps(serde.encode(cp), sort_keys=True)


def _decode_v2(data: str) -> Checkpoint:
    return serde.decode(Checkpoint, json.loads(data), strict=False)


def _encode_v1(cp: Checkpoint) -> str:
    """Legacy shape: uid → flat device list, extended for fallback fidelity.

    The flat ``devices`` list is what legacy readers expect; alongside it
    ride ``namespace``/``name`` (without which the stale-claim GC can never
    reclaim a fallen-back claim) and per-group ``groups`` with their
    ``configState`` (without which a started claim's ``plannedPartitions``
    is lost — the retry's rollback becomes a silent no-op and crashed-
    prepare partitions leak — and a multi-group claim's teardown state,
    timeslice/mp UUIDs, is truncated to one group).  Legacy readers decode
    non-strict and ignore the extras."""
    claims = {}
    for uid, claim in cp.prepared_claims.items():
        entry: dict = {"devices": [serde.encode(d) for d in claim.all_devices()]}
        entry["status"] = claim.status
        if claim.namespace:
            entry["namespace"] = claim.namespace
        if claim.name:
            entry["name"] = claim.name
        if any(g.config_state for g in claim.groups) or len(claim.groups) > 1:
            entry["groups"] = [
                {
                    "devices": [serde.encode(d) for d in g.devices],
                    "configState": g.config_state,
                }
                for g in claim.groups
            ]
        claims[uid] = entry
    return json.dumps({"preparedClaims": claims}, sort_keys=True)


def _decode_v1(data: str) -> Checkpoint:
    raw = json.loads(data)
    cp = Checkpoint()
    for uid, entry in raw.get("preparedClaims", {}).items():
        if "groups" in entry:
            # This driver's fallback payload: faithful group structure.
            groups = [
                PreparedDeviceGroup(
                    devices=[
                        serde.decode(PreparedDevice, d, strict=False)
                        for d in g.get("devices", [])
                    ],
                    config_state=dict(g.get("configState", {})),
                )
                for g in entry["groups"]
            ]
        else:
            groups = [
                PreparedDeviceGroup(
                    devices=[
                        serde.decode(PreparedDevice, d, strict=False)
                        for d in entry.get("devices", [])
                    ]
                )
            ]
        devices = [d for g in groups for d in g.devices]
        # V1 written by THIS driver carries an explicit status (the claim-
        # level field covers started claims with empty device lists — the
        # cdplugin's shape — which no device-derived heuristic can).  V1
        # written by an OLD driver has none: every claim in it was fully
        # prepared — except that 'planned'-type devices only ever belong to
        # a PrepareStarted claim, which must take the retry/rollback path,
        # never be served as a completed cached grant (its devices have no
        # CDI ids and no spec file).
        status = entry.get("status") or (
            PREPARE_STARTED
            if any(d.type == "planned" for d in devices)
            else PREPARE_COMPLETED
        )
        cp.prepared_claims[uid] = PreparedClaim(
            uid=uid,
            namespace=entry.get("namespace", ""),
            name=entry.get("name", ""),
            status=status,
            groups=groups,
        )
    return cp


class CheckpointManager:
    """Atomic read/write of the dual-version checkpoint file, with a
    flock-guarded read-mutate-write helper (reference device_state.go:555-582)
    and a stat-validated in-memory read cache."""

    def __init__(self, plugin_dir: str):
        self._path = os.path.join(plugin_dir, CHECKPOINT_FILE)
        self._lock_path = os.path.join(plugin_dir, CHECKPOINT_LOCK)
        os.makedirs(plugin_dir, exist_ok=True)
        # (stat key, decoded checkpoint). Callers may freely mutate what
        # read() returns, so the cache holds its own copy.
        self._cache: Optional[tuple[tuple[int, int, int], Checkpoint]] = None
        self._cache_lock = lockwitness.make_lock("checkpoint.cache_lock")

    @property
    def path(self) -> str:
        return self._path

    def _stat_key(self) -> Optional[tuple[int, int, int]]:
        try:
            st = os.stat(self._path)
        except FileNotFoundError:
            return None
        # The inode guards against the mtime granularity of coarse
        # filesystems: every write lands via os.replace, so a new file
        # always means a new inode.
        return (st.st_mtime_ns, st.st_size, st.st_ino)

    def read(self) -> Checkpoint:
        return self._read_flagged()[0]

    def _read_flagged(self, bypass_cache: bool = False) -> tuple[Checkpoint, bool]:
        """(checkpoint, degraded) — the newest readable version; fresh
        checkpoint if absent.  degraded means a corrupt newer version was
        skipped and an older payload served.

        Served from the in-memory cache when the file's stat triple is
        unchanged since the last read/write (unless ``bypass_cache`` —
        the flock-guarded RMW needs disk-true freshness).  The stat is
        taken BEFORE the disk read: if another process replaces the file
        in between, the cache holds newer content under an older key and
        the next read simply misses — never the reverse (stale content
        under a new key).
        """
        key = self._stat_key()
        if key is None:
            return Checkpoint(), False
        if not bypass_cache:
            with self._cache_lock:
                cached = self._cache
            if cached is not None and cached[0] == key:
                _READS_CACHE.inc()
                # Deepcopy outside the mutex: the cached object is never
                # mutated in place (writers replace the tuple wholesale),
                # so concurrent readers need not serialize on an O(size)
                # copy.  The copy itself scales with prepared-claim count —
                # still cheaper than the open+JSON+CRC+decode it replaces,
                # but a read-only snapshot accessor would beat both if a
                # scan-heavy caller ever shows up hot.
                return copy.deepcopy(cached[1]), False
        t0 = time.monotonic()
        cp, degraded = self._read_disk()
        _READS_DISK.inc()
        metrics.observe_phase(
            metrics.PHASE_CHECKPOINT_READ, time.monotonic() - t0
        )
        if not degraded:
            # A version-fallback read is deliberately NOT cached: caching it
            # would make the fallback loud exactly once and then silent —
            # every read of a corrupt file must re-log and re-count while
            # the node runs on the degraded payload.
            with self._cache_lock:
                self._cache = (key, copy.deepcopy(cp))
        return cp, degraded

    def _read_disk(self) -> tuple[Checkpoint, bool]:
        """Decode the newest version that passes its checksum.  Returns
        (checkpoint, degraded) — degraded means a newer corrupt version was
        skipped and an older payload served."""
        try:
            with open(self._path) as f:
                envelope = json.load(f)
        except FileNotFoundError:
            return Checkpoint(), False
        except ValueError as e:
            raise CheckpointError(f"corrupt checkpoint envelope: {e}") from e
        corrupt: list[str] = []
        for version, decode in (("v2", _decode_v2), ("v1", _decode_v1)):
            entry = envelope.get(version)
            if not entry:
                continue
            data, checksum = entry.get("data", ""), entry.get("checksum")
            if _checksum(data) != checksum:
                corrupt.append(version)
                logger.error(
                    "checkpoint %s checksum mismatch (got %s, want %s): "
                    "trying an older version",
                    version, checksum, _checksum(data),
                )
                continue
            cp = decode(data)
            if corrupt:
                # Loud fallback: the older payload may lack newer-version
                # state (V1 has no PrepareStarted claims), so an ACTUAL
                # successful fallback must be visible in logs and metrics —
                # counted only here, not when every version is corrupt and
                # the read raises below.
                logger.error(
                    "checkpoint fell back to %s (corrupt: %s)",
                    version, ", ".join(corrupt),
                )
                metrics.CHECKPOINT_FALLBACKS_TOTAL.inc()
            return cp, bool(corrupt)
        if corrupt:
            raise ChecksumMismatch(
                "checkpoint has no version with a valid checksum "
                f"(corrupt: {', '.join(corrupt)})"
            )
        raise CheckpointError("checkpoint has no readable version")

    def write(self, cp: Checkpoint) -> None:
        """Durably replace the checkpoint and prime the read cache.

        Cache contract: the cache holds ``cp`` by REFERENCE (a deepcopy per
        write was measurable on the bind path) — after write() the caller
        must not mutate ``cp``.  mutate() guarantees this (its return value
        is unused by design); read() hands out copies, never the cached
        object."""
        t0 = time.monotonic()
        v1, v2 = _encode_v1(cp), _encode_v2(cp)
        envelope = {
            "v1": {"data": v1, "checksum": _checksum(v1)},
            "v2": {"data": v2, "checksum": _checksum(v2)},
        }
        tmp = self._path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(envelope, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._path)
        # The stat is taken after the replace, so the key matches exactly
        # what a subsequent read would see for this content.
        key = self._stat_key()
        with self._cache_lock:
            self._cache = (key, cp) if key is not None else None
        metrics.observe_phase(
            metrics.PHASE_CHECKPOINT_WRITE, time.monotonic() - t0
        )

    def mutate(
        self, fn: Callable[[Checkpoint], Optional[Checkpoint]], timeout: float = 10.0
    ) -> None:
        """flock-guarded read-mutate-write: fn may mutate in place (return
        None) or return a replacement.  Returns nothing: the final object is
        cached by reference (write()'s contract), so handing it out would
        invite cache-poisoning mutations — re-``read()`` for a copy.

        A mutate over a degraded read FINALIZES the fallback — the write
        re-encodes both versions with valid checksums from the fallback
        payload, after which the corruption signal stops firing and the
        newer-version-only state is gone.  So before overwriting, the
        corrupt original is preserved at ``<path>.corrupt`` for inspection
        or manual repair, and the finalization itself is logged loudly."""
        # Fresh Flock per mutate: one shared instance cannot be acquired
        # twice, but in-process callers DO overlap (the GC thread mutates
        # while RPC threads mutate) — each needs its own fd so the kernel
        # serializes them instead of a RuntimeError failing the batch.
        with Flock(self._lock_path)(timeout=timeout):  # tpudra-lock: id=flock:cp.lock
            # Bypass the read cache inside the RMW: the stat triple is not
            # collision-proof across processes (inode recycling + coarse
            # mtime), and a false cache hit here would write a stale
            # checkpoint back — the one path where the cache could corrupt
            # durable state.  Plain reads keep the cache; the RMW pays one
            # disk read for bulletproof freshness.
            cp, degraded = self._read_flagged(bypass_cache=True)
            out = fn(cp)
            cp = out if out is not None else cp
            if degraded:
                corrupt_path = self._path + ".corrupt"
                try:
                    with open(self._path, "rb") as src, open(
                        corrupt_path, "wb"
                    ) as dst:
                        dst.write(src.read())
                except OSError:
                    logger.exception(
                        "cannot preserve corrupt checkpoint at %s", corrupt_path
                    )
                logger.error(
                    "finalizing degraded checkpoint: rewriting all versions "
                    "from the fallback payload; original preserved at %s",
                    corrupt_path,
                )
            self.write(cp)

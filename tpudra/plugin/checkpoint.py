"""Versioned, checksummed node-local checkpoint.

The analog of gpu-kubelet-plugin/{checkpoint,checkpointv}.go: a JSON file that
is the node-local source of truth for idempotent prepare, partition teardown,
channel-conflict detection, and stale-claim GC.  Both V1 and V2 payloads are
written on every mutation, each with its own checksum, so that *both* driver
upgrade and downgrade find a checkpoint they can read (reference
checkpoint.go:10-47, checkpointv.go:24-82).

- V1 (legacy shape): claim UID → prepared device list only.
- V2: adds per-claim prepare status (PrepareStarted/PrepareCompleted) and the
  claim's namespace/name (needed by the stale-claim GC to validate claims
  against the API server by name+UID, reference cleanup.go:150).

Reads prefer V2 and fall back to V1; unknown fields are tolerated (non-strict)
so checkpoints written by newer drivers parse (reference api.go:54-58).
"""

from __future__ import annotations

import json
import os
import zlib
from dataclasses import dataclass, field
from typing import Callable, Optional

from tpudra.api import serde
from tpudra.flock import Flock

PREPARE_STARTED = "PrepareStarted"
PREPARE_COMPLETED = "PrepareCompleted"

CHECKPOINT_FILE = "checkpoint.json"
CHECKPOINT_LOCK = "cp.lock"


class CheckpointError(Exception):
    pass


class ChecksumMismatch(CheckpointError):
    pass


@dataclass
class PreparedDevice:
    """One granted device as recorded in the checkpoint (prepared.go:31)."""

    canonical_name: str = field(default="", metadata={"json": "canonicalName"})
    type: str = field(default="", metadata={"json": "type"})  # chip|partition|vfio|channel|daemon
    pool_name: str = field(default="", metadata={"json": "poolName"})
    request_names: list[str] = field(default_factory=list, metadata={"json": "requestNames"})
    cdi_device_ids: list[str] = field(default_factory=list, metadata={"json": "cdiDeviceIDs"})
    # Hardware identity needed for unprepare/rollback: chip uuid, live
    # partition uuid + spec, vfio PCI address, channel id.
    attributes: dict[str, str] = field(default_factory=dict, metadata={"json": "attributes"})


@dataclass
class PreparedDeviceGroup:
    """Devices sharing one resolved config (prepared.go:44), plus the config
    state needed to undo it (MPS daemon id, timeslice reset, CDI ids)."""

    devices: list[PreparedDevice] = field(default_factory=list, metadata={"json": "devices"})
    config_state: dict[str, str] = field(default_factory=dict, metadata={"json": "configState"})


@dataclass
class PreparedClaim:
    uid: str = field(default="", metadata={"json": "uid"})
    namespace: str = field(default="", metadata={"json": "namespace"})
    name: str = field(default="", metadata={"json": "name"})
    status: str = field(default=PREPARE_STARTED, metadata={"json": "status"})
    groups: list[PreparedDeviceGroup] = field(default_factory=list, metadata={"json": "groups"})

    def all_devices(self) -> list[PreparedDevice]:
        return [d for g in self.groups for d in g.devices]


@dataclass
class Checkpoint:
    prepared_claims: dict[str, PreparedClaim] = field(
        default_factory=dict, metadata={"json": "preparedClaims"}
    )


def _checksum(data: str) -> int:
    return zlib.crc32(data.encode())


def _encode_v2(cp: Checkpoint) -> str:
    return json.dumps(serde.encode(cp), sort_keys=True)


def _decode_v2(data: str) -> Checkpoint:
    return serde.decode(Checkpoint, json.loads(data), strict=False)


def _encode_v1(cp: Checkpoint) -> str:
    """Legacy shape: uid → flat device list (no status, no claim identity)."""
    out = {
        "preparedClaims": {
            uid: {"devices": [serde.encode(d) for d in claim.all_devices()]}
            for uid, claim in cp.prepared_claims.items()
        }
    }
    return json.dumps(out, sort_keys=True)


def _decode_v1(data: str) -> Checkpoint:
    raw = json.loads(data)
    cp = Checkpoint()
    for uid, entry in raw.get("preparedClaims", {}).items():
        devices = [
            serde.decode(PreparedDevice, d, strict=False) for d in entry.get("devices", [])
        ]
        # V1 had no explicit status: a claim present in a V1 checkpoint was
        # fully prepared (started-but-unfinished claims were not persisted).
        cp.prepared_claims[uid] = PreparedClaim(
            uid=uid,
            status=PREPARE_COMPLETED,
            groups=[PreparedDeviceGroup(devices=devices)],
        )
    return cp


class CheckpointManager:
    """Atomic read/write of the dual-version checkpoint file, with a
    flock-guarded read-mutate-write helper (reference device_state.go:555-582)."""

    def __init__(self, plugin_dir: str):
        self._path = os.path.join(plugin_dir, CHECKPOINT_FILE)
        self._lock = Flock(os.path.join(plugin_dir, CHECKPOINT_LOCK))
        os.makedirs(plugin_dir, exist_ok=True)

    @property
    def path(self) -> str:
        return self._path

    def read(self) -> Checkpoint:
        """Read the newest readable version; fresh checkpoint if absent."""
        try:
            with open(self._path) as f:
                envelope = json.load(f)
        except FileNotFoundError:
            return Checkpoint()
        except ValueError as e:
            raise CheckpointError(f"corrupt checkpoint envelope: {e}") from e
        for version, decode in (("v2", _decode_v2), ("v1", _decode_v1)):
            entry = envelope.get(version)
            if not entry:
                continue
            data, checksum = entry.get("data", ""), entry.get("checksum")
            if _checksum(data) != checksum:
                raise ChecksumMismatch(
                    f"checkpoint {version} checksum mismatch "
                    f"(got {checksum}, want {_checksum(data)})"
                )
            return decode(data)
        raise CheckpointError("checkpoint has no readable version")

    def write(self, cp: Checkpoint) -> None:
        v1, v2 = _encode_v1(cp), _encode_v2(cp)
        envelope = {
            "v1": {"data": v1, "checksum": _checksum(v1)},
            "v2": {"data": v2, "checksum": _checksum(v2)},
        }
        tmp = self._path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(envelope, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._path)

    def mutate(
        self, fn: Callable[[Checkpoint], Optional[Checkpoint]], timeout: float = 10.0
    ) -> Checkpoint:
        """flock-guarded read-mutate-write: fn may mutate in place (return
        None) or return a replacement."""
        with self._lock(timeout=timeout):
            cp = self.read()
            out = fn(cp)
            cp = out if out is not None else cp
            self.write(cp)
            return cp

"""The checkpoint-backed Prepare/Unprepare engine.

The analog of gpu-kubelet-plugin/device_state.go — the latency-critical core
of the driver (SURVEY.md §3.2, the ResourceClaim-bind-p50 path):

- idempotent Prepare: a completed claim returns its cached grant; a claim
  found in PrepareStarted is rolled back (orphan partition teardown) before a
  fresh attempt (device_state.go:180-242)
- overlap validation: a device already granted to another claim is refused
  (device_state.go:1118)
- opaque-config resolution with claim-over-class-over-default precedence
  (device_state.go:1019-1072)
- sharing config application (time-slicing / multi-process daemon), dynamic
  partition creation, VFIO rebind (device_state.go:910-1010)
- per-claim transient CDI spec writing
- crash consistency: PrepareStarted is persisted *with the planned dynamic
  partitions* before any hardware mutation, so rollback after a crash needs
  only the checkpoint (device_state.go:231-242, 337)

The engine is *batched and phased* (docs/bind-path.md): a kubelet batch of N
claims costs two checkpoint read-modify-write cycles, not 2N.

- ``begin_prepare``: ONE checkpoint RMW records PrepareStarted for every
  claim in the batch (idempotency check, partial-retry rollback, and overlap
  validation happen inside the same critical section).
- ``run_prepare_effects``: per-claim side effects — config resolution,
  partition creation, sharing daemons, the CDI spec write — run *outside*
  any lock; the durable PrepareStarted record is what reserves the silicon
  (overlap validation in other processes sees it) and what makes a crash
  here convergent.
- ``finish_prepare``: ONE checkpoint RMW flips every successful claim to
  PrepareCompleted.

``prepare``/``unprepare`` remain as batch-of-one wrappers; the Driver holds
the node lock around the begin/finish phases and fans effects across a
bounded pool (driver.py).
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Optional

from tpudra import TPU_DRIVER_NAME, featuregates, metrics, trace, walwitness
from tpudra.api import (
    ComputeDomainChannelConfig,
    ComputeDomainDaemonConfig,
    DecodeError,
    TpuConfig,
    TpuPartitionConfig,
    VfioDeviceConfig,
    decode_config,
)
from tpudra.devicelib import DeviceLib, DeviceLibError, PartitionSpec
from tpudra.plugin import allocatable as alloc
from tpudra.plugin.allocatable import AllocatableDevice
from tpudra.plugin.cdi import (
    CDIHandler,
    ContainerEdits,
    DeviceEditsCache,
    chip_edits,
)
from tpudra.plugin.checkpoint import (
    PREPARE_COMPLETED,
    PREPARE_STARTED,
    Checkpoint,
    CheckpointManager,
    PreparedClaim,
    PreparedDevice,
    PreparedDeviceGroup,
    _crashpoint,  # re-export: the crash sweeps and cdplugin import it here
)
from tpudra.plugin import partitions as partrec
from tpudra.plugin.sharing import MultiProcessManager, TimeSlicingManager
from tpudra.plugin.vfio import VfioManager

logger = logging.getLogger(__name__)

# Labelled counter children resolved once (METRICS-HYGIENE: .labels() is
# registry-locked and partition create sits on the bind hot path).
_PART_CREATED = metrics.PARTITION_LIFECYCLE_TOTAL.labels("create")
_PART_DESTROYED = metrics.PARTITION_LIFECYCLE_TOTAL.labels("destroy")
_PART_SWEPT = metrics.PARTITION_LIFECYCLE_TOTAL.labels("sweep-destroy")
_PART_RECORD_DROPPED = metrics.PARTITION_LIFECYCLE_TOTAL.labels("record-drop")


class PermanentError(Exception):
    """Non-retryable failure: kubelet retries won't fix bad user input
    (reference compute-domain plugin's permanentError type)."""


class PrepareError(Exception):
    """Retryable failure."""


@dataclass
class PreparedDeviceResult:
    """One device entry of a NodePrepareResources response."""

    request_names: list[str]
    pool_name: str
    device_name: str
    cdi_device_ids: list[str]


@dataclass
class _ConfigGroup:
    config: object
    results: list[dict] = field(default_factory=list)


@dataclass
class PrepareItem:
    """One claim's state as it moves through the phased prepare."""

    claim: dict
    uid: str = ""
    namespace: str = ""
    name: str = ""
    results: list = field(default_factory=list)
    planned: list = field(default_factory=list)
    #: Idempotent hit: the claim was already PrepareCompleted.
    cached: Optional[list[PreparedDeviceResult]] = None
    error: Optional[Exception] = None
    #: Set by run_prepare_effects on success; finish_prepare persists it.
    plain_groups: Optional[list[PreparedDeviceGroup]] = None
    #: A fresh PrepareStarted record was written for this claim.
    started: bool = False
    #: Retry of a partial prepare: (old record, owned-partition set) whose
    #: orphan teardown runs at the START of the effects phase — hardware
    #: rollback must not run inside the locked RMW.
    rollback: Optional[tuple] = None

    def device_names(self) -> list[str]:
        return [r.get("device", "") for r in self.results]

    def partition_record_uids(self) -> list[str]:
        """Checkpoint keys of this claim's per-partition records (one per
        planned dynamic partition, docs/partitioning.md)."""
        return [
            partrec.record_uid(alloc.partition_name(s)) for s in self.planned
        ]

    def device_results(self) -> list[PreparedDeviceResult]:
        """The grant to return to kubelet: idempotent-cached or fresh."""
        if self.cached is not None:
            return self.cached
        return _results_from_groups(self.plain_groups or [])


@dataclass
class PrepareBatch:
    items: list[PrepareItem] = field(default_factory=list)

    def pending(self) -> list[PrepareItem]:
        """Items that still need side effects run."""
        return [
            it for it in self.items if it.error is None and it.cached is None
        ]


@dataclass
class UnprepareItem:
    uid: str
    #: Checkpoint record at begin time; None = nothing to tear down.
    record: Optional[PreparedClaim] = None
    #: Partition UUIDs owned by OTHER completed claims at begin time
    #: (rollback of a partial claim must not destroy these).
    owned_partitions: set = field(default_factory=set)
    #: Checkpoint keys of the claim's per-partition records: flipped to
    #: Destroying at begin, dropped with the claim record at finish.
    partition_uids: list = field(default_factory=list)
    error: Optional[Exception] = None
    #: Side effects finished; finish_unprepare drops the record.
    done: bool = False

    def device_names(self) -> list[str]:
        if self.record is None:
            return []
        return [d.canonical_name for d in self.record.all_devices()]


@dataclass
class UnprepareBatch:
    items: list[UnprepareItem] = field(default_factory=list)

    def pending(self) -> list[UnprepareItem]:
        return [it for it in self.items if it.error is None and not it.done]


class DeviceState:
    def __init__(
        self,
        devicelib: DeviceLib,
        cdi: CDIHandler,
        checkpoints: CheckpointManager,
        node_name: str,
        ts_manager: Optional[TimeSlicingManager] = None,
        mp_manager: Optional[MultiProcessManager] = None,
        vfio_manager: Optional[VfioManager] = None,
    ):
        self._lib = devicelib
        self._cdi = cdi
        self._cp = checkpoints
        self._node_name = node_name
        self._ts = ts_manager or TimeSlicingManager(devicelib)
        self._mp = mp_manager
        self._vfio = vfio_manager
        self._dynamic = featuregates.enabled(featuregates.DYNAMIC_PARTITIONING)
        self._passthrough = featuregates.enabled(featuregates.PASSTHROUGH_SUPPORT)
        # Capability gating (the MIG-capability analog, nvlib.go:269-301):
        # dynamic partitions are only advertised when the backend attests
        # it can actually mutate them.  Real silicon attests False today —
        # no public TPU runtime API exposes sub-chip partition mutation —
        # so a hardware node advertises chips but not dynamic partitions;
        # the SimulatedPartitions gate overrides for test/dev rigs (the
        # partitions are then file-backed simulation the hardware never
        # enforces; the native backend additionally needs
        # TPUINFO_SIMULATE_PARTITIONS=1 so its registry exists).
        partitions_supported = devicelib.partitions_supported()
        if self._dynamic and not partitions_supported:
            if featuregates.enabled(featuregates.SIMULATED_PARTITIONS):
                # The override must never advertise devices the backend
                # cannot even simulate (native without
                # TPUINFO_SIMULATE_PARTITIONS has no registry: every
                # prepare would fail and pods would wedge on phantom
                # devices) — prove the mutation path with a real
                # create/delete roundtrip before advertising.
                self._probe_simulated_partitions(devicelib)
                logger.warning(
                    "backend attests partitions_supported=false; the "
                    "SimulatedPartitions gate forces advertisement of "
                    "file-backed simulated partitions (probe roundtrip ok)"
                )
            else:
                logger.warning(
                    "DynamicPartitioning requested but the backend attests "
                    "partitions_supported=false (no TPU runtime API for "
                    "sub-chip partition mutation): advertising chips only"
                )
                self._dynamic = False

        chips = devicelib.enumerate_chips()
        self._chips_by_index = {c.index: c for c in chips}
        self._chips_by_uuid = {c.uuid: c for c in chips}
        static_parts = [] if self._dynamic else devicelib.list_partitions()
        dynamic_placements = None
        if self._dynamic:
            dynamic_placements = {
                c.index: devicelib.possible_placements(c) for c in chips
            }
        self.allocatable = alloc.build_allocatable(
            chips,
            static_parts,
            dynamic_placements,
            partitions_supported=partitions_supported,
            multiprocess_mode=devicelib.multiprocess_mode(),
            with_vfio=self._passthrough,
        )
        # Per-device edits cache with startup warmup (reference
        # cdi.go:65,151).  Builders are currently trivial — see the
        # DeviceEditsCache docstring for why the cache exists anyway.
        self._edits_cache = DeviceEditsCache()
        self._edits_cache.warmup(
            {
                name: (lambda d=dev: self._build_device_edits(d))
                for name, dev in self.allocatable.items()
                if dev.type != alloc.TYPE_VFIO  # vfio edits depend on bind state
            }
        )

    def _build_device_edits(self, adev) -> ContainerEdits:
        return ContainerEdits(
            device_nodes=[self._cdi.host_path(p) for p in adev.chip.dev_paths()]
        )

    # ------------------------------------------------------------------ API

    def prepare(self, claim: dict) -> list[PreparedDeviceResult]:
        """Batch-of-one wrapper over the phased engine (tests, simple
        callers).  Raises on failure exactly as the pre-batch engine did."""
        t0 = time.monotonic()
        batch = self.begin_prepare([claim])
        (item,) = batch.items
        if item.error is not None:
            raise item.error
        if item.cached is not None:
            return item.cached
        self.run_prepare_effects(item)
        self.finish_prepare(batch)
        if item.error is not None:
            raise item.error
        logger.info(
            "prepared claim %s/%s:%s t_prep=%.4fs",
            item.namespace, item.name, item.uid, time.monotonic() - t0,
        )
        return _results_from_groups(item.plain_groups)

    def unprepare(self, claim_uid: str) -> None:
        t0 = time.monotonic()
        batch = self.begin_unprepare([claim_uid])
        (item,) = batch.items
        if item.error is None:
            self.run_unprepare_effects(item)
        self.finish_unprepare(batch)
        if item.error is not None:
            raise item.error
        logger.info("unprepared claim %s t_unprep=%.4fs", claim_uid, time.monotonic() - t0)

    # ------------------------------------------------- phased batch engine

    def begin_prepare(self, claims: list[dict]) -> PrepareBatch:
        """Phase 1 of a batched prepare: ONE checkpoint RMW that, for every
        claim in the batch, resolves idempotent hits, rolls back partial
        retries, validates silicon overlap (against durable claims AND the
        earlier claims of this batch), and records PrepareStarted with the
        planned dynamic partitions.

        Per-claim failures land in ``item.error`` — one bad claim never
        poisons the batch.  The caller serializes this phase under the
        node-global lock (driver.py)."""
        batch = PrepareBatch()
        # Captured on the CALLING thread: the mutator closure below runs on
        # whichever thread leads the group commit, whose context is not
        # this bind's (tpudra/trace.py lineage rules).
        batch_traceparent = trace.current_traceparent() or None
        seen: dict[str, PrepareItem] = {}
        for claim in claims:
            item = PrepareItem(claim=claim)
            try:
                item.uid, item.namespace, item.name = _claim_identity(claim)
            except PermanentError as e:
                item.error = e
                batch.items.append(item)
                continue
            if item.uid in seen:
                # Kubelet never sends a uid twice; if a caller does, the
                # first occurrence wins and duplicates alias its outcome
                # (out dicts are keyed by uid anyway).
                continue
            seen[item.uid] = item
            batch.items.append(item)
            try:
                item.results = _allocation_results(claim)
                if not item.results:
                    raise PermanentError(
                        f"claim {item.namespace}/{item.name}:{item.uid} has "
                        f"no allocation for {TPU_DRIVER_NAME}"
                    )
                item.planned = self._planned_partition_specs(item.results)
            except Exception as e:  # noqa: BLE001 — per-claim barrier: one
                item.error = e      # malformed claim must not fail the batch

        def start_all(cp: Checkpoint) -> None:
            for item in batch.items:
                if item.error is not None:
                    continue
                try:
                    self._start_one(cp, item, batch_traceparent)
                except Exception as e:  # noqa: BLE001 — per-claim barrier
                    item.error = e

        # Delta contract: start_all reads every claim (overlap validation)
        # but writes only the batch's uids plus their per-partition record
        # keys — the commit appends O(batch + planned partitions) journal
        # records (~70 B each), not an O(state) snapshot.
        touched = [it.uid for it in batch.items if it.uid]
        for item in batch.items:
            touched.extend(item.partition_record_uids())
        self._cp.mutate(start_all, touched=touched)
        if any(it.started for it in batch.items):
            _crashpoint("post-prepare-started")
        for item in batch.items:
            if item.cached is not None:
                logger.info(
                    "claim %s already prepared (idempotent return)", item.uid
                )
        return batch

    def _start_one(
        self, cp: Checkpoint, item: PrepareItem,
        traceparent: Optional[str] = None,
    ) -> None:
        existing = cp.prepared_claims.get(item.uid)
        if existing is not None and existing.status == PREPARE_COMPLETED:
            item.cached = _results_from_claim(existing)
            return
        if existing is not None and existing.status == PREPARE_STARTED:
            # Retry of a partial prepare: its orphans must be torn down
            # before re-preparing (device_state.go:223-228) — but the
            # teardown is O(seconds) hardware work, so it runs at the start
            # of this item's effects phase, NOT here inside the locked RMW.
            # Safe to defer: the new PrepareStarted record (same claim, same
            # planned specs) keeps covering the orphans, so a crash before
            # the deferred rollback converges exactly like a crash before
            # an inline one.
            item.rollback = (
                existing, _owned_partition_uuids(cp, existing.uid)
            )
        self._validate_no_overlap(cp, item.uid, item.results)
        # PrepareStarted first, per-partition records second: both land in
        # the SAME atomic commit (durable intent before any hardware
        # mutation, recovery sweep owning anything that dies between this
        # record and the Live flip), but the claim-family write must come
        # before the partition-family writes so the mutator touches stripe
        # families in canonical order (STRIPE-ORDER) — the striped
        # checkpoint locks families in that order.  An idempotent retry
        # re-upserts identical records — zero delta bytes.
        cp.prepared_claims[item.uid] = PreparedClaim(
            uid=item.uid,
            namespace=item.namespace,
            name=item.name,
            status=PREPARE_STARTED,
            traceparent=traceparent,
            groups=[
                PreparedDeviceGroup(
                    # Requested device names are recorded at Started so
                    # concurrent prepares see this claim's footprint.
                    devices=[
                        PreparedDevice(canonical_name=r["device"], type="planned")
                        for r in item.results
                    ],
                    config_state={
                        "plannedPartitions": _encode_specs(item.planned)
                    },
                )
            ],
        )
        # Journal one per-partition record per planned dynamic partition
        # (phase=Creating), same commit as the PrepareStarted write above.
        for spec in item.planned:
            pname = alloc.partition_name(spec)
            cp.prepared_claims[partrec.record_uid(pname)] = partrec.make_record(
                pname, partrec.PHASE_CREATING, item.uid, spec
            )
        item.started = True

    def run_prepare_effects(self, item: PrepareItem) -> None:
        """Phase 2: one claim's side effects — config resolution, hardware
        mutation, sharing, the CDI spec write.  Runs OUTSIDE every lock: the
        durable PrepareStarted record already reserves the silicon, and a
        crash anywhere in here converges from the checkpoint alone.  Raises
        on failure (after best-effort undo); the claim stays PrepareStarted
        so the retry's rollback covers anything the undo missed."""
        if item.rollback is not None:
            # Deferred partial-retry rollback (see _start_one): runs before
            # this claim's own effects — serially within the same item, and
            # the orphans share this claim's footprint so the effect-group
            # net keeps other items off this silicon.  The span resumes the
            # INTERRUPTED bind's trace (the traceparent its record
            # journaled), so the crashed prepare and its cleanup read as
            # one causal chain in trace_report.
            old_record, owned = item.rollback
            with trace.start_span(
                "bind.retry-rollback",
                parent=old_record.traceparent or None,
                attrs={"claim": item.uid},
            ):
                self._rollback_partial(old_record, owned)
        if item.planned:
            # The new crash window this subsystem introduces: the Creating
            # records are durable (begin's commit), NO hardware has been
            # mutated — a SIGKILL here must leak nothing (the recovery
            # sweep drops the stale records; the claim stays retryable).
            _crashpoint("mid-partition-create")
        undos: list = []
        t0 = time.monotonic()
        try:
            with trace.start_span(
                "bind.config-apply", attrs={"claim": item.uid}
            ):
                groups = self._prepare_devices(
                    item.uid, item.results, _opaque_configs(item.claim), undos
                )
        except Exception:
            for undo in reversed(undos):
                try:
                    undo()
                except Exception:  # noqa: BLE001
                    logger.exception("prepare-failure cleanup step failed")
            raise
        metrics.observe_phase(
            metrics.PHASE_CONFIG_APPLY, time.monotonic() - t0
        )
        _crashpoint("post-mutate")
        with trace.start_span("bind.cdi-write", attrs={"claim": item.uid}):
            self._write_cdi_spec(item.uid, groups)
        _crashpoint("post-cdi")
        item.plain_groups = [g for g, _ in groups]

    def finish_prepare(self, batch: PrepareBatch) -> None:
        """Phase 3: ONE checkpoint RMW flips every claim whose effects
        succeeded to PrepareCompleted.  Failed claims stay PrepareStarted
        for the retry's rollback."""
        done = [it for it in batch.items if it.plain_groups is not None]
        if not done:
            return

        def _live_partitions(item: PrepareItem) -> list[tuple[str, str]]:
            """(canonical name, live uuid) of the claim's fresh dynamic
            partitions, straight from the effects phase's grant."""
            return [
                (d.canonical_name, d.attributes.get("partitionUUID", ""))
                for g in item.plain_groups
                for d in g.devices
                if d.type == alloc.TYPE_PARTITION_DYNAMIC
            ]

        def complete_all(cp: Checkpoint) -> None:
            for item in done:
                prev = cp.prepared_claims.get(item.uid)
                cp.prepared_claims[item.uid] = PreparedClaim(
                    uid=item.uid,
                    namespace=item.namespace,
                    name=item.name,
                    status=PREPARE_COMPLETED,
                    # The ORIGINAL bind's trace rides the record across the
                    # started→completed flip (and any crash in between).
                    traceparent=prev.traceparent if prev is not None else None,
                    groups=item.plain_groups,
                )
                # The same commit flips each partition record Creating →
                # Live with the hardware uuid: claim completion and
                # partition-record truth can never diverge across a crash.
                for pname, uuid in _live_partitions(item):
                    spec = alloc.parse_partition_name(pname)
                    if spec is None:
                        continue
                    cp.prepared_claims[partrec.record_uid(pname)] = (
                        partrec.make_record(
                            pname, partrec.PHASE_LIVE, item.uid, spec,
                            partition_uuid=uuid,
                        )
                    )

        touched = [it.uid for it in done]
        for item in done:
            touched.extend(
                partrec.record_uid(pname) for pname, _ in _live_partitions(item)
            )
        self._cp.mutate(complete_all, touched=touched)
        _crashpoint("post-completed")

    def begin_unprepare(self, claim_uids: list[str]) -> UnprepareBatch:
        """Phase 1 of a batched unprepare: ONE checkpoint read snapshots
        each claim's record and the partition-ownership set rollback needs.
        The claim record stays in place (still reserving its silicon)
        until finish_unprepare; claims holding dynamic partitions
        additionally journal destroy INTENT — their per-partition records
        flip to Destroying in one commit — so a crash between here and the
        hardware delete leaves orphans the recovery sweep destroys
        (``mid-partition-destroy``)."""
        batch = UnprepareBatch()
        cp = self._cp.read()
        seen: set[str] = set()
        for uid in claim_uids:
            if uid in seen:
                continue
            seen.add(uid)
            item = UnprepareItem(uid=uid)
            batch.items.append(item)
            if not uid:
                item.error = PermanentError("claim reference has no uid")
                continue
            item.record = cp.prepared_claims.get(uid)
            if item.record is not None and item.record.status == PREPARE_STARTED:
                item.owned_partitions = _owned_partition_uuids(cp, uid)
            if item.record is not None:
                item.partition_uids = _claim_partition_record_uids(item.record)
        flip = [u for it in batch.items for u in it.partition_uids]
        if flip:

            def mark_destroying(cpw: Checkpoint) -> None:
                for rec_uid in flip:
                    claim = cpw.prepared_claims.get(rec_uid)
                    if claim is None:
                        continue
                    rec = partrec.parse_record(rec_uid, claim)
                    if rec is None or rec.spec is None:
                        continue
                    cpw.prepared_claims[rec_uid] = partrec.make_record(
                        rec.name, partrec.PHASE_DESTROYING, rec.claim_uid,
                        rec.spec, partition_uuid=rec.partition_uuid,
                    )

            self._cp.mutate(mark_destroying, touched=flip)
            _crashpoint("mid-partition-destroy")
        return batch

    def run_unprepare_effects(self, item: UnprepareItem) -> None:
        """Phase 2: teardown side effects for one claim, outside every lock.
        All teardown steps are idempotent (partition delete tolerates
        already-gone, timeslice reset is absolute, daemon stop is a delete),
        so a crash between effects and finish_unprepare re-runs cleanly."""
        if item.record is None:
            self._cdi.delete_claim_spec_file(item.uid)
            item.done = True
            return
        if item.record.status == PREPARE_STARTED:
            self._rollback_partial(item.record, item.owned_partitions)
        else:
            self._unprepare_devices(item.record)
        self._cdi.delete_claim_spec_file(item.uid)
        item.done = True

    def finish_unprepare(self, batch: UnprepareBatch) -> None:
        """Phase 3: ONE checkpoint RMW drops every record whose teardown
        completed — the claim record AND its per-partition records in one
        commit.  No-op (zero disk writes) when nothing was recorded."""
        drop = [
            u
            for it in batch.items
            if it.done and it.record is not None
            for u in (it.uid, *it.partition_uids)
        ]
        if not drop:
            return

        def drop_all(cp: Checkpoint) -> None:
            for uid in drop:
                cp.prepared_claims.pop(uid, None)

        self._cp.mutate(drop_all, touched=drop)

    def effect_groups(self, keyed: list) -> list[list]:
        """Partition batch items into groups whose device footprints overlap
        (same silicon under any alias); the driver runs groups concurrently
        and members sequentially.  ``keyed`` is [(item, device_names)].

        Overlap validation already guarantees the started claims of one
        batch are disjoint, so groups are normally singletons — the grouping
        is the safety net for unvalidated shapes (duplicate names, unknown
        devices) where serial order is the conservative answer."""
        n = len(keyed)
        parent = list(range(n))

        def find(i: int) -> int:
            while parent[i] != i:
                parent[i] = parent[parent[i]]
                i = parent[i]
            return i

        prints = [
            [(name, self._footprint(name)) for name in names]
            for _, names in keyed
        ]
        for i in range(n):
            for j in range(i + 1, n):
                if find(i) == find(j):
                    continue
                if _names_clash(prints[i], prints[j]):
                    parent[find(j)] = find(i)
        groups: dict[int, list] = {}
        for i, (item, _) in enumerate(keyed):
            groups.setdefault(find(i), []).append(item)
        return list(groups.values())

    def prepared_claim_uids(self) -> dict[str, tuple[str, str, str]]:
        """uid → (namespace, name, status) for the stale-claim GC (read-
        only scan: the copy-free ``read_view``).  Partition records are
        NOT claims — they have no apiserver object to validate, so the GC
        must never see them (the recovery sweep owns their lifecycle)."""
        cp = self._cp.read_view()
        return {
            uid: (c.namespace, c.name, c.status)
            for uid, c in cp.prepared_claims.items()
            if not partrec.is_partition_record(uid)
        }

    def bound_sibling_devices(self) -> set[str]:
        """Device names sharing silicon with a prepared passthrough grant —
        withheld from publication so the scheduler cannot double-book the
        chip under its other alias (reference allocatable.go:238,
        device_state.go:252-262,409-421).

        A prepared vfio alias withholds the chip device and its partitions;
        a prepared chip/partition withholds the chip's vfio alias.
        """
        if not self._passthrough:
            return set()
        # Read-only scan on the publish path: the copy-free read_view —
        # this runs on every slice rebuild and scales with resident claims.
        cp = self._cp.read_view()
        withheld: set[str] = set()
        for claim in cp.prepared_claims.values():
            for dev in claim.all_devices():
                adev = self.allocatable.get(dev.canonical_name)
                if adev is None:
                    continue
                if adev.type == alloc.TYPE_VFIO:
                    idx = adev.chip.index
                    withheld.add(alloc.chip_name(idx))
                    withheld.update(
                        n
                        for n, d in self.allocatable.items()
                        if d.is_partition and d.chip.index == idx
                    )
                else:
                    withheld.add(alloc.vfio_name(adev.chip.index))
        return withheld

    @staticmethod
    # tpudra-wal: nonrecoverable the probe partition is deliberately journal-less: it carries a reserved probe spec no claim can own, and a crash mid-probe converges via _reap_probe_leftover at the next init
    def _probe_simulated_partitions(devicelib: DeviceLib) -> None:
        """Create-and-delete one real partition to prove the backend can
        simulate before SimulatedPartitions advertises any (init-time
        only).  Raises with the remedy when it cannot."""
        # walwitness.exempt() is the runtime twin of the nonrecoverable
        # annotation above: the static walk skips this subtree, so the
        # witness must not report the probe's create/destroy either.
        with walwitness.exempt():
            chips = devicelib.enumerate_chips()
            for chip in chips:
                placements = devicelib.possible_placements(chip)
                if not placements:
                    continue
                p = placements[0]
                spec = PartitionSpec(
                    parent_index=chip.index,
                    profile=p.profile.name,
                    core_start=p.core_start,
                    hbm_start=p.hbm_start,
                )
                remedy = (
                    "SimulatedPartitions is enabled but the backend cannot "
                    "simulate partition mutation ({}); on the native "
                    "backend set TPUINFO_SIMULATE_PARTITIONS=1 so the "
                    "file-backed registry exists"
                )
                try:
                    live = devicelib.create_partition(spec)
                except DeviceLibError as e:
                    # A probe partition leaked by a crashed earlier init can
                    # make this create fail; reap any live partition matching
                    # the probe spec and retry once before misdiagnosing the
                    # backend as unable to simulate (ADVICE r4).
                    if not DeviceState._reap_probe_leftover(devicelib, spec):
                        raise DeviceLibError(remedy.format(e)) from e
                    try:
                        live = devicelib.create_partition(spec)
                    except DeviceLibError as e2:
                        raise DeviceLibError(remedy.format(e2)) from e2
                try:
                    devicelib.delete_partition(live.uuid)
                except DeviceLibError as e:
                    # Best-effort: the probe partition is not in any
                    # checkpoint, so startup reconciliation
                    # (destroy_unknown_partitions) reaps it — failing init
                    # here would wedge the plugin over an already-recoverable
                    # leak.
                    logger.warning(
                        "probe partition %s could not be deleted (%s); "
                        "startup reconciliation will destroy it",
                        live.uuid, e,
                    )
                return
            raise DeviceLibError(
                "SimulatedPartitions is enabled but no chip offers a "
                "partition placement (generation not partitionable?)"
            )

    @staticmethod
    # tpudra-wal: nonrecoverable reaps only the journal-less probe's exact spec; deleting it converges init, and a crash mid-reap just retries next init
    def _reap_probe_leftover(devicelib: DeviceLib, spec: PartitionSpec) -> bool:
        """Delete any live partition with exactly the probe's spec — only a
        leaked probe from a crashed init can match it, since an occupied
        placement would not have been offered by possible_placements."""
        reaped = False
        try:
            with walwitness.exempt():
                for live in devicelib.list_partitions():
                    if live.spec == spec:
                        logger.warning(
                            "reaping leftover probe partition %s (%s)",
                            live.uuid, live.spec,
                        )
                        devicelib.delete_partition(live.uuid)
                        reaped = True
        except DeviceLibError as e:
            logger.warning("could not reap leftover probe partition: %s", e)
        return reaped

    # tpudra-wal: recovers=partition the startup sweep converges every partition record (Creating/Destroying orphans, Live strays) against live hardware, so its own destroys act FROM checkpoint truth rather than needing fresh intent
    def destroy_unknown_partitions(self) -> int:
        """The partition RECOVERY SWEEP (docs/partitioning.md): converge
        live hardware and per-partition checkpoint records to each other —
        both directions — from checkpoint truth alone.

        Hardware side (DestroyUnknownMIGDevices, device_state.go:337):
        every live partition must be explained by a completed claim's
        grant or a Live-phase record; others are destroyed — including
        partitions whose record journaled destroy intent (``Destroying``,
        the ``mid-partition-destroy`` crash window) or create intent the
        claim never completed (``Creating``, ``mid-partition-create``).

        Record side: Creating/Destroying records are dropped after their
        hardware is confirmed gone, and a Live record whose partition or
        claim vanished is reconciled — so the soak's partition-leak
        invariant (record ⟷ live partition, quiet windows) restarts true
        after every crash.  Hardware mutation runs BEFORE the record
        commit (the phased discipline: a crash in between re-runs
        idempotently).  Returns the number of partitions destroyed."""
        if not self._dynamic:
            return 0
        cp = self._cp.read_view()
        records = partrec.records_in(cp)
        # uuid → owning completed claim.  The sweep must never destroy
        # another claim's granted silicon, but a record's journaled
        # destroy intent DOES override its own claim's grant — unprepare
        # was requested, the grant is already dead to kubelet.
        owned: dict[str, str] = {}
        for uid, claim in cp.prepared_claims.items():
            if partrec.is_partition_record(uid):
                continue
            if claim.status != PREPARE_COMPLETED:
                continue
            for dev in claim.all_devices():
                uuid = dev.attributes.get("partitionUUID")
                if uuid:
                    owned[uuid] = uid
        live = {p.uuid: p for p in self._lib.list_partitions()}
        live_by_spec = {p.spec: p for p in live.values()}
        destroyed = 0
        drop: list[str] = []

        def _destroy(uuid: str, why: str) -> bool:
            nonlocal destroyed
            logger.warning(
                "destroying unknown partition %s (%s)", uuid, why
            )
            try:
                # Runtime twin of the recovers=partition annotation: the
                # sweep destroys FROM checkpoint truth, so even a
                # record-less stray (a leaked probe) carries the
                # checkpoint's authority for the witness.
                with walwitness.recovery_scope("partition"):
                    self._lib.delete_partition(uuid)
            except DeviceLibError as e:
                logger.warning("sweep could not destroy %s: %s", uuid, e)
                return False
            _PART_SWEPT.inc()
            live.pop(uuid, None)
            destroyed += 1
            return True

        for rec_uid, rec in sorted(records.items()):
            claim = cp.prepared_claims.get(rec.claim_uid)
            if rec.phase == partrec.PHASE_DESTROYING:
                # Journaled destroy intent: finish what the crash cut off.
                # The record's OWN claim's grant does not protect the
                # partition (unprepare was already requested); any other
                # claim's does.
                target = live.get(rec.partition_uuid)
                if target is None and rec.spec is not None:
                    target = live_by_spec.get(rec.spec)
                # A FAILED destroy keeps the record: the journaled intent
                # is the retry plan (the next sweep, or the unprepare
                # retry's own idempotent delete) — dropping it would leave
                # the partition with no checkpoint tracker.
                if (
                    target is None
                    or owned.get(target.uuid, rec.claim_uid) != rec.claim_uid
                    or _destroy(target.uuid, f"record {rec_uid} phase=Destroying")
                ):
                    drop.append(rec_uid)
            elif rec.phase == partrec.PHASE_CREATING:
                # Create intent the claim never completed: any matching
                # hardware is an orphan; the claim (if still present)
                # stays PrepareStarted and the retry re-journals.
                target = live_by_spec.get(rec.spec) if rec.spec else None
                if (
                    target is None
                    or target.uuid in owned
                    or _destroy(target.uuid, f"record {rec_uid} phase=Creating")
                ):
                    drop.append(rec_uid)
            elif rec.phase == partrec.PHASE_LIVE:
                if rec.partition_uuid not in live:
                    # Hardware vanished out-of-band: the record lies.
                    drop.append(rec_uid)
                elif claim is None:
                    # Owning claim gone (forced drop, corrupt fallback):
                    # the partition is unexplained silicon.  The record
                    # only drops once the hardware is actually gone.
                    if _destroy(
                        rec.partition_uuid, f"record {rec_uid} claim gone"
                    ):
                        drop.append(rec_uid)
        known = set(owned) | {
            rec.partition_uuid
            for rec_uid, rec in records.items()
            if rec.phase == partrec.PHASE_LIVE and rec_uid not in drop
        }
        for uuid in list(live):
            if uuid not in known:
                _destroy(uuid, str(live[uuid].spec))
        if drop:
            def drop_records(cpw: Checkpoint) -> None:
                for rec_uid in drop:
                    cpw.prepared_claims.pop(rec_uid, None)

            self._cp.mutate(drop_records, touched=drop)
            _PART_RECORD_DROPPED.inc(len(drop))
        return destroyed

    # ------------------------------------------------------- prepare internals

    def _device_for_result(self, result: dict) -> AllocatableDevice:
        name = result.get("device", "")
        dev = self.allocatable.get(name)
        if dev is None:
            raise PermanentError(f"allocated device {name!r} is not allocatable on this node")
        return dev

    def _planned_partition_specs(self, results: list[dict]) -> list[PartitionSpec]:
        out = []
        for r in results:
            dev = self.allocatable.get(r.get("device", ""))
            if dev is not None and dev.type == alloc.TYPE_PARTITION_DYNAMIC:
                out.append(dev.partition_spec)
        return out

    def _footprint(
        self, name: str
    ) -> Optional[tuple[int, tuple[int, int], tuple[int, int]]]:
        """Silicon footprint of a canonical device name: (chip index,
        core range, hbm range).  A full chip, its vfio alias, and its
        partitions all map onto the same chip's ranges, so overlap detection
        catches grants of the same silicon under different names."""
        spec = alloc.parse_partition_name(name)
        if spec is not None:
            cores, hbm = alloc._profile_counts(spec.profile)
            return (
                spec.parent_index,
                (spec.core_start, spec.core_start + cores),
                (spec.hbm_start, spec.hbm_start + hbm),
            )
        dev = self.allocatable.get(name)
        if dev is not None:
            from tpudra.devicelib import HBM_SLICES_PER_CHIP

            return (
                dev.chip.index,
                (0, dev.chip.tensorcores),
                (0, HBM_SLICES_PER_CHIP),
            )
        return None

    def _validate_no_overlap(self, cp: Checkpoint, uid: str, results: list[dict]) -> None:
        """Refuse devices whose silicon overlaps another claim's grant —
        including in-flight PrepareStarted claims (device_state.go:1118)."""
        wanted = {r["device"]: self._footprint(r["device"]) for r in results}
        for other_uid, other in cp.prepared_claims.items():
            if other_uid == uid or partrec.is_partition_record(other_uid):
                continue  # partition records carry no devices (own sweep)
            for dev in other.all_devices():
                theirs = self._footprint(dev.canonical_name)
                if theirs is None:
                    continue
                for name, ours in wanted.items():
                    if ours is None or ours[0] != theirs[0]:
                        continue
                    cores_clash = ours[1][0] < theirs[1][1] and theirs[1][0] < ours[1][1]
                    hbm_clash = ours[2][0] < theirs[2][1] and theirs[2][0] < ours[2][1]
                    if cores_clash or hbm_clash:
                        # Retryable, not permanent: with the narrowed node
                        # lock the other claim may be mid-teardown (its
                        # record stays durable until finish_unprepare), and
                        # kubelet's retry lands after the silicon frees up.
                        # A genuine double-allocation keeps erroring loudly
                        # on every retry (the kubelet DRA API retries all
                        # prepare errors anyway).
                        raise PrepareError(
                            f"device {name} overlaps {dev.canonical_name}, already "
                            f"prepared for claim {other.namespace}/{other.name}:{other_uid}"
                        )

    def _resolve_configs(
        self, results: list[dict], opaque: list[tuple[list[str], object]]
    ) -> list[_ConfigGroup]:
        """Assign each result its winning config: claim configs override class
        configs override per-type defaults (device_state.go:1019-1072)."""
        groups: list[_ConfigGroup] = []

        def group_for(config_obj) -> _ConfigGroup:
            for g in groups:
                if g.config is config_obj:
                    return g
            g = _ConfigGroup(config=config_obj)
            groups.append(g)
            return g

        defaults: dict[str, object] = {}

        for r in results:
            dev = self._device_for_result(r)
            winner = None
            for requests, config in opaque:
                if not requests or r.get("request") in requests:
                    winner = config
            if winner is None:
                key = dev.type
                if key not in defaults:
                    defaults[key] = self._default_config_for(dev)
                winner = defaults[key]
            group_for(winner).results.append(r)
        return groups

    def _default_config_for(self, dev: AllocatableDevice):
        if dev.type == alloc.TYPE_CHIP:
            cfg = TpuConfig.default()
        elif dev.is_partition:
            cfg = TpuPartitionConfig.default()
        else:
            cfg = VfioDeviceConfig.default()
        cfg.normalize()
        cfg.validate()
        return cfg

    def _prepare_devices(
        self,
        uid: str,
        results: list[dict],
        opaque: list[tuple[list[str], object]],
        undos: list,
    ) -> list[tuple[PreparedDeviceGroup, ContainerEdits]]:
        groups_out: list[tuple[PreparedDeviceGroup, ContainerEdits]] = []
        for group in self._resolve_configs(results, opaque):
            groups_out.append(self._apply_config(uid, group.config, group.results, undos))
        return groups_out

    def _apply_config(
        self, uid: str, config, results: list[dict], undos: list
    ) -> tuple[PreparedDeviceGroup, ContainerEdits]:
        devices = [self._device_for_result(r) for r in results]
        types = {d.type for d in devices}
        config_state: dict[str, str] = {}
        group_edits = ContainerEdits()

        partition_sharing = False
        if isinstance(config, TpuConfig):
            if types - {alloc.TYPE_CHIP}:
                raise PermanentError(
                    f"TpuConfig applied to non-chip devices: {sorted(types)}"
                )
            config_state, group_edits = self._apply_sharing(uid, config, devices, undos)
        elif isinstance(config, TpuPartitionConfig):
            if not types <= {alloc.TYPE_PARTITION_STATIC, alloc.TYPE_PARTITION_DYNAMIC}:
                raise PermanentError(
                    f"TpuPartitionConfig applied to non-partition devices: {sorted(types)}"
                )
            # Multi-process sharing OF partitions (the MPS-on-MIG analog)
            # is applied AFTER the device loop below: the broker brokers
            # live partition uuids, which exist only once the hardware
            # mutation has run.
            partition_sharing = (
                config.sharing is not None and config.sharing.is_multi_process
            )
        elif isinstance(config, VfioDeviceConfig):
            if types != {alloc.TYPE_VFIO}:
                raise PermanentError(
                    f"VfioDeviceConfig applied to non-vfio devices: {sorted(types)}"
                )
            if self._vfio is None:
                raise PermanentError("passthrough support is not enabled")
        elif isinstance(config, (ComputeDomainChannelConfig, ComputeDomainDaemonConfig)):
            raise PermanentError(
                f"{type(config).__name__} belongs to the compute-domain driver"
            )
        else:
            raise PermanentError(f"unsupported config type {type(config).__name__}")

        prepared: list[PreparedDevice] = []
        for r, dev in zip(results, devices):
            attributes: dict[str, str] = {"uuid": dev.chip.uuid}
            # The hot op: dynamic partition creation (createMigDevice analog,
            # device_state.go:763, O(seconds) on real silicon).
            if dev.type == alloc.TYPE_PARTITION_DYNAMIC:
                t0 = time.monotonic()
                try:
                    live = self._lib.create_partition(dev.partition_spec)
                except DeviceLibError as e:
                    raise PrepareError(f"creating partition for {dev.name}: {e}") from e
                undos.append(lambda u=live.uuid: self._lib.delete_partition(u))
                _PART_CREATED.inc()
                attributes["partitionUUID"] = live.uuid
                logger.info(
                    "t_prep_create_partition=%.4fs device=%s", time.monotonic() - t0, dev.name
                )
            elif dev.type == alloc.TYPE_PARTITION_STATIC:
                attributes["partitionUUID"] = dev.live_partition.uuid
            elif dev.type == alloc.TYPE_VFIO:
                group = self._vfio.configure(dev.chip)
                attributes["iommuGroup"] = group
            prepared.append(
                PreparedDevice(
                    canonical_name=dev.name,
                    type=dev.type,
                    pool_name=alloc.pool_name(self._node_name),
                    request_names=[r["request"]] if r.get("request") else [],
                    cdi_device_ids=[self._cdi.qualified_device_id(uid, dev.name)],
                    attributes=attributes,
                )
            )
        if partition_sharing:
            config_state, group_edits = self._apply_partition_sharing(
                uid, config, devices, prepared, undos
            )
        return PreparedDeviceGroup(devices=prepared, config_state=config_state), group_edits

    def _apply_sharing(
        self, uid: str, config: TpuConfig, devices: list[AllocatableDevice], undos: list
    ) -> tuple[dict[str, str], ContainerEdits]:
        """applySharingConfig analog (device_state.go:926)."""
        if config.sharing is None:
            return {}, ContainerEdits()
        uuids = [d.chip.uuid for d in devices]
        if config.sharing.is_time_slicing:
            if not featuregates.enabled(featuregates.TIME_SLICING_SETTINGS):
                raise PermanentError("TimeSlicing sharing requires the TimeSlicingSettings gate")
            interval = self._ts.set_timeslice(uuids, config.sharing.get_time_slicing_config())
            undos.append(lambda: self._ts.reset(uuids))
            return (
                {"timeslice": interval, "timesliceUUIDs": ",".join(uuids)},
                ContainerEdits(env=[f"TPU_TIMESLICE_HINT={interval}"]),
            )
        if config.sharing.is_multi_process:
            if not featuregates.enabled(featuregates.MULTI_PROCESS_SHARING):
                raise PermanentError(
                    "MultiProcess sharing requires the MultiProcessSharing gate"
                )
            if self._mp is None:
                raise PermanentError("multi-process manager is not configured")
            mp_config = config.sharing.get_multi_process_config()
            daemon = self._mp.new_daemon(uid, uuids, mp_config)
            daemon.start()
            undos.append(daemon.stop)
            daemon.assert_ready()
            return (
                {"mpDaemon": uid, "mpUUIDs": ",".join(uuids)},
                daemon.get_cdi_edits(),
            )
        return {}, ContainerEdits()

    def _apply_partition_sharing(
        self,
        uid: str,
        config: TpuPartitionConfig,
        devices: list[AllocatableDevice],
        prepared: list,
        undos: list,
    ) -> tuple[dict[str, str], ContainerEdits]:
        """Multi-process sharing of FRACTIONAL chips: one per-claim
        control daemon brokers the claim's live partition uuids, each
        pinned to an HBM budget derived from its profile's HBM fraction
        (only explicit PER-DEVICE limits override — the claim-level
        ``defaultPinnedHbmLimit`` is a whole-chip knob and must not blow
        a half-chip partition's budget past its profile) and a TensorCore
        percentage defaulting to the smallest partition's fraction of its
        chip.  Runs after partition creation — the broker needs the live
        uuids."""
        from tpudra.api.quantity import format_mebibytes
        from tpudra.api.sharing import MultiProcessConfig
        from tpudra.devicelib import HBM_SLICES_PER_CHIP

        if not featuregates.enabled(featuregates.MULTI_PROCESS_SHARING):
            raise PermanentError(
                "MultiProcess sharing requires the MultiProcessSharing gate"
            )
        if self._mp is None:
            raise PermanentError("multi-process manager is not configured")
        mp_config = config.sharing.get_multi_process_config() or MultiProcessConfig()
        part_uuids: list[str] = []
        derived: dict[str, str] = {}
        min_fraction = 100
        for dev, pdev in zip(devices, prepared):
            uuid = pdev.attributes.get("partitionUUID", "")
            if not uuid:
                raise PrepareError(
                    f"partition {dev.name} has no live uuid for sharing"
                )
            part_uuids.append(uuid)
            spec = dev.partition_spec
            cores, hbm_slices = alloc._profile_counts(spec.profile)
            budget = dev.chip.hbm_bytes * hbm_slices // HBM_SLICES_PER_CHIP
            text, ok = format_mebibytes(budget)
            if ok:
                derived[uuid] = text
            if dev.chip.tensorcores:
                min_fraction = min(
                    min_fraction, round(100 * cores / dev.chip.tensorcores)
                )
        limits = dict(derived)
        per_device = MultiProcessConfig(
            default_per_device_pinned_hbm_limit=(
                mp_config.default_per_device_pinned_hbm_limit
            )
        )
        limits.update(per_device.normalized_limits(part_uuids))
        daemon = self._mp.new_daemon(
            uid, part_uuids, mp_config,
            limits=limits, tensorcore_pct=min_fraction, exclusive=False,
        )
        daemon.start()
        undos.append(daemon.stop)
        daemon.assert_ready()
        return (
            {
                "mpDaemon": uid,
                "mpUUIDs": ",".join(part_uuids),
                "mpPartition": "1",
            },
            daemon.get_cdi_edits(),
        )

    def _write_cdi_spec(
        self, uid: str, groups: list[tuple[PreparedDeviceGroup, ContainerEdits]]
    ) -> list[str]:
        """Per-device entries carry only device nodes; all env is claim-wide.

        A container consuming a multi-device claim receives every device's
        CDI entry, and the runtime merges env lists — per-device
        TPU_VISIBLE_DEVICES values would clobber each other, leaving libtpu
        with one visible chip.  So the env union (visible devices, coords,
        partitions) lives in the claim-wide containerEdits."""
        device_edits: dict[str, ContainerEdits] = {}
        common = ContainerEdits()
        tpu_chips: dict[int, object] = {}
        partition_descs: list[str] = []
        for group, group_common in groups:
            common = common.merge(group_common)
            for dev in group.devices:
                adev = self.allocatable[dev.canonical_name]
                if dev.type == alloc.TYPE_VFIO:
                    edits = self._vfio.get_cdi_edits(
                        adev.chip, dev.attributes.get("iommuGroup", "")
                    )
                else:
                    tpu_chips[adev.chip.index] = adev.chip
                    edits = self._edits_cache.get(
                        dev.canonical_name,
                        lambda a=adev: self._build_device_edits(a),
                    )
                    if adev.is_partition:
                        spec = adev.partition_spec
                        partition_descs.append(
                            f"{dev.canonical_name}={spec.profile}@{spec.core_start},{spec.hbm_start}"
                        )
                device_edits[dev.canonical_name] = edits
        if tpu_chips:
            chips = [tpu_chips[i] for i in sorted(tpu_chips)]
            env_edits = chip_edits(chips)
            env_edits.device_nodes = []  # nodes already on per-device entries
            if partition_descs:
                env_edits.env.append("TPUDRA_PARTITIONS=" + ";".join(partition_descs))
            common = common.merge(env_edits)
        return self._cdi.create_claim_spec_file(uid, device_edits, common)

    # ------------------------------------------------------ unprepare internals

    def _unprepare_devices(self, claim: PreparedClaim) -> None:
        """Teardown for a completed claim (device_state.go:794-849)."""
        for group in claim.groups:
            state = group.config_state
            if "timeslice" in state:
                uuids = [u for u in state.get("timesliceUUIDs", "").split(",") if u]
                self._ts.reset(uuids)
            if "mpDaemon" in state and self._mp is not None:
                uuids = [u for u in state.get("mpUUIDs", "").split(",") if u]
                # Partition-mode daemons never pinned chips exclusive
                # (sibling partitions may belong to other claims).
                self._mp.daemon_for(
                    claim.uid, uuids, exclusive="mpPartition" not in state
                ).stop()
            for dev in group.devices:
                if dev.type == alloc.TYPE_PARTITION_DYNAMIC:
                    uuid = dev.attributes.get("partitionUUID")
                    if uuid:
                        try:
                            self._lib.delete_partition(uuid)
                            _PART_DESTROYED.inc()
                        except DeviceLibError:
                            logger.warning("partition %s already gone", uuid)
                elif dev.type == alloc.TYPE_VFIO and self._vfio is not None:
                    chip_uuid = dev.attributes.get("uuid", "")
                    chip = self._chips_by_uuid.get(chip_uuid)
                    if chip is not None:
                        self._vfio.unconfigure(chip)

    def _rollback_partial(self, claim: PreparedClaim, owned: set[str]) -> None:
        """Tear down partitions a crashed/failed prepare may have created.

        The planned specs were checkpointed before hardware mutation; any live
        partition matching a planned spec that is *not* owned by a completed
        claim is an orphan (unpreparePartiallyPrepairedClaim,
        device_state.go:482 + guard on completed-claim usage).  ``owned`` is
        the completed-claim partition-UUID set snapshotted from the same
        checkpoint view that produced ``claim``."""
        planned = _decode_specs(
            claim.groups[0].config_state.get("plannedPartitions", "") if claim.groups else ""
        )
        if not planned:
            return
        planned_set = set(planned)
        for live in self._lib.list_partitions():
            if live.spec in planned_set and live.uuid not in owned:
                logger.info("rollback: destroying orphan partition %s", live.uuid)
                try:
                    self._lib.delete_partition(live.uuid)
                    _PART_DESTROYED.inc()
                except DeviceLibError:
                    pass


# ---------------------------------------------------------------------------
# Claim-object helpers
# ---------------------------------------------------------------------------


def _claim_identity(claim: dict) -> tuple[str, str, str]:
    meta = claim.get("metadata", {})
    uid = meta.get("uid", "")
    if not uid:
        raise PermanentError("claim has no uid")
    return uid, meta.get("namespace", ""), meta.get("name", "")


def _allocation_results(claim: dict) -> list[dict]:
    results = (
        claim.get("status", {})
        .get("allocation", {})
        .get("devices", {})
        .get("results", [])
    )
    return [r for r in results if r.get("driver") == TPU_DRIVER_NAME]


def _opaque_configs(claim: dict) -> list[tuple[list[str], object]]:
    """Decode this driver's opaque configs from the allocation, class-sourced
    first so claim-sourced entries win (GetOpaqueDeviceConfigs,
    device_state.go:1019)."""
    entries = (
        claim.get("status", {})
        .get("allocation", {})
        .get("devices", {})
        .get("config", [])
    )
    ordered = [e for e in entries if e.get("source") == "FromClass"] + [
        e for e in entries if e.get("source") != "FromClass"
    ]
    out: list[tuple[list[str], object]] = []
    for entry in ordered:
        opaque = entry.get("opaque")
        if not opaque or opaque.get("driver") != TPU_DRIVER_NAME:
            continue
        try:
            config = decode_config(opaque.get("parameters", {}), strict=True)
            config.normalize()
            config.validate()
        except (DecodeError, ValueError) as e:
            raise PermanentError(f"invalid opaque config: {e}") from e
        out.append((entry.get("requests", []), config))
    return out


def _results_from_groups(groups: list[PreparedDeviceGroup]) -> list[PreparedDeviceResult]:
    return [
        PreparedDeviceResult(
            request_names=d.request_names,
            pool_name=d.pool_name,
            device_name=d.canonical_name,
            cdi_device_ids=d.cdi_device_ids,
        )
        for g in groups
        for d in g.devices
    ]


def _results_from_claim(claim: PreparedClaim) -> list[PreparedDeviceResult]:
    return _results_from_groups(claim.groups)


def _claim_partition_record_uids(record: PreparedClaim) -> list[str]:
    """Checkpoint keys of a claim's per-partition records, from its
    granted dynamic-partition devices (completed claims) and its planned
    specs (started claims — the retry/rollback shapes)."""
    names = {
        d.canonical_name
        for d in record.all_devices()
        if d.type == alloc.TYPE_PARTITION_DYNAMIC
    }
    for group in record.groups:
        planned = group.config_state.get("plannedPartitions", "")
        if planned:
            try:
                for spec in _decode_specs(planned):
                    names.add(alloc.partition_name(spec))
            except ValueError:
                pass  # garbled planned set: the sweep converges by spec
    return sorted(partrec.record_uid(n) for n in names)


def _owned_partition_uuids(cp: Checkpoint, exclude_uid: str) -> set[str]:
    """Partition UUIDs owned by completed claims other than ``exclude_uid``
    — the set a partial-claim rollback must never destroy."""
    owned: set[str] = set()
    for other in cp.prepared_claims.values():
        if other.uid == exclude_uid or other.status != PREPARE_COMPLETED:
            continue
        for dev in other.all_devices():
            uuid = dev.attributes.get("partitionUUID")
            if uuid:
                owned.add(uuid)
    return owned


def _names_clash(a: list, b: list) -> bool:
    """True when any device of one (name, footprint) list shares silicon —
    or a literal name — with any device of the other."""
    for name_a, fp_a in a:
        for name_b, fp_b in b:
            if name_a and name_a == name_b:
                return True
            if fp_a is None or fp_b is None or fp_a[0] != fp_b[0]:
                continue
            cores = fp_a[1][0] < fp_b[1][1] and fp_b[1][0] < fp_a[1][1]
            hbm = fp_a[2][0] < fp_b[2][1] and fp_b[2][0] < fp_a[2][1]
            if cores or hbm:
                return True
    return False


def _encode_specs(specs: list[PartitionSpec]) -> str:
    return "|".join(
        f"{s.parent_index}:{s.profile}:{s.core_start}:{s.hbm_start}" for s in specs
    )


def _decode_specs(text: str) -> list[PartitionSpec]:
    out = []
    for part in text.split("|"):
        if not part:
            continue
        idx, profile, cs, hs = part.split(":")
        out.append(PartitionSpec(int(idx), profile, int(cs), int(hs)))
    return out

"""Kubelet-facing plugin sockets: registration + DRA service.

The reference delegates this to k8s.io/dynamic-resource-allocation's
``kubeletplugin.Start`` helper (gpu-kubelet-plugin/driver.go:123-132), which
serves two gRPC unix sockets: a *registration* socket kubelet discovers under
``plugins_registry/`` and the *DRA service* socket it then calls
NodePrepareResources/NodeUnprepareResources on.

The TPU build keeps the same two-socket contract but frames messages as
newline-delimited JSON over SOCK_STREAM — a dependency-free wire format the
in-repo fake kubelet (tests) speaks natively.  Every request is one line
``{"id": n, "method": "...", "params": {...}}`` answered by one line
``{"id": n, "result": {...}}`` or ``{"id": n, "error": "..."}``.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import socketserver
import threading
from typing import Callable, Optional

logger = logging.getLogger(__name__)

DRA_PLUGIN_TYPE = "DRAPlugin"
SUPPORTED_VERSIONS = ["v1", "v1beta1"]


class RPCError(Exception):
    pass


# ---------------------------------------------------------------------------
# Server
# ---------------------------------------------------------------------------


class _Handler(socketserver.StreamRequestHandler):
    def handle(self) -> None:
        for raw in self.rfile:
            raw = raw.strip()
            if not raw:
                continue
            try:
                req = json.loads(raw)
            except json.JSONDecodeError:
                self._reply({"id": None, "error": "malformed request"})
                continue
            method = req.get("method", "")
            fn = self.server.methods.get(method)  # type: ignore[attr-defined]
            if fn is None:
                self._reply({"id": req.get("id"), "error": f"unknown method {method!r}"})
                continue
            try:
                result = fn(req.get("params") or {})
                self._reply({"id": req.get("id"), "result": result})
            except Exception as e:  # noqa: BLE001 — fault barrier per request
                logger.exception("RPC %s failed", method)
                self._reply({"id": req.get("id"), "error": str(e)})

    def _reply(self, obj: dict) -> None:
        self.wfile.write((json.dumps(obj) + "\n").encode())
        self.wfile.flush()


class UnixRPCServer(socketserver.ThreadingUnixStreamServer):
    """Threaded unix-socket JSON-RPC server with a method table."""

    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, path: str, methods: dict[str, Callable[[dict], dict]]):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        if os.path.exists(path):
            os.unlink(path)
        self.methods = methods
        self.path = path
        super().__init__(path, _Handler)
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.serve_forever, daemon=True, name=f"rpc:{os.path.basename(self.path)}"
        )
        self._thread.start()

    def stop(self) -> None:
        if self._thread is not None:
            # shutdown() blocks on serve_forever's ack — calling it on a
            # server that was never started would wait forever.
            self.shutdown()
        self.server_close()
        if os.path.exists(self.path):
            os.unlink(self.path)


class UnixRPCClient:
    """One persistent connection; thread-safe request/response pairing by id."""

    def __init__(self, path: str, timeout: float = 30.0):
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.settimeout(timeout)
        self._sock.connect(path)
        self._file = self._sock.makefile("rwb")
        self._lock = threading.Lock()
        self._next_id = 0

    def call(self, method: str, params: Optional[dict] = None) -> dict:
        with self._lock:
            self._next_id += 1
            req_id = self._next_id
            req = {"id": req_id, "method": method, "params": params or {}}
            try:
                self._file.write((json.dumps(req) + "\n").encode())
                self._file.flush()
                while True:
                    raw = self._file.readline()
                    if not raw:
                        raise RPCError(f"connection closed during {method}")
                    resp = json.loads(raw)
                    if resp.get("id") == req_id:
                        break
                    # A stale response from a timed-out earlier call; skip it.
                    logger.warning("discarding stale RPC response id=%s", resp.get("id"))
            except (OSError, TimeoutError):
                # The stream is desynchronized (a late response would pair
                # with the wrong call) — poison the connection.
                self.close()
                raise
        if resp.get("error"):
            raise RPCError(resp["error"])
        return resp.get("result") or {}

    def close(self) -> None:
        try:
            self._file.close()
        finally:
            self._sock.close()


# ---------------------------------------------------------------------------
# The two plugin sockets
# ---------------------------------------------------------------------------


class PluginSockets:
    """Registration + DRA-service sockets for one driver.

    ``prepare``/``unprepare`` are callables provided by the Driver; both
    receive/return plain dicts shaped like the DRA v1 messages:

      NodePrepareResources:   {"claims": [<ResourceClaim>...]}
        → {"claims": {uid: {"devices": [...]} | {"error": str}}}
      NodeUnprepareResources: {"claims": [{"uid": ..., "namespace": ..., "name": ...}]}
        → {"claims": {uid: {} | {"error": str}}}
    """

    def __init__(
        self,
        driver_name: str,
        plugin_dir: str,
        registry_dir: str,
        prepare: Callable[[list[dict]], dict],
        unprepare: Callable[[list[dict]], dict],
    ):
        self.driver_name = driver_name
        self.dra_socket_path = os.path.join(plugin_dir, "dra.sock")
        self.registration_socket_path = os.path.join(
            registry_dir, f"{driver_name}-reg.sock"
        )
        self._registered = threading.Event()

        self._dra = UnixRPCServer(
            self.dra_socket_path,
            {
                "NodePrepareResources": lambda p: prepare(p.get("claims", [])),
                "NodeUnprepareResources": lambda p: unprepare(p.get("claims", [])),
            },
        )
        self._reg = UnixRPCServer(
            self.registration_socket_path,
            {
                "GetInfo": self._get_info,
                "NotifyRegistrationStatus": self._notify,
            },
        )

    def _get_info(self, _params: dict) -> dict:
        return {
            "type": DRA_PLUGIN_TYPE,
            "name": self.driver_name,
            "endpoint": self.dra_socket_path,
            "supportedVersions": SUPPORTED_VERSIONS,
        }

    def _notify(self, params: dict) -> dict:
        if params.get("pluginRegistered"):
            logger.info("kubelet acknowledged registration of %s", self.driver_name)
            self._registered.set()
        else:
            logger.error(
                "kubelet rejected registration of %s: %s",
                self.driver_name,
                params.get("error", ""),
            )
        return {}

    @property
    def registered(self) -> bool:
        return self._registered.is_set()

    def start(self) -> None:
        self._dra.start()
        self._reg.start()

    def stop(self) -> None:
        self._dra.stop()
        self._reg.stop()

"""TPU kubelet plugin binary (the cmd/gpu-kubelet-plugin analog)."""

from __future__ import annotations

import argparse
import logging

from tpudra.flags import (
    add_common_flags,
    env_default,
    install_stop_handlers,
    make_device_lib,
    make_kube_client_from_args,
    setup_common,
)

logger = logging.getLogger(__name__)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("tpu-kubelet-plugin")
    add_common_flags(p)
    p.add_argument("--node-name", default=env_default("NODE_NAME"), required=not env_default("NODE_NAME"))
    p.add_argument(
        "--plugin-dir",
        default=env_default("PLUGIN_DIR", "/var/lib/kubelet/plugins/tpu.google.com"),
    )
    p.add_argument(
        "--registry-dir",
        default=env_default("REGISTRY_DIR", "/var/lib/kubelet/plugins_registry"),
    )
    p.add_argument("--cdi-root", default=env_default("CDI_ROOT", "/var/run/cdi"))
    p.add_argument("--driver-root", default=env_default("DRIVER_ROOT", "/"))
    p.add_argument(
        "--sysfs-root", default=env_default("SYSFS_ROOT", "/sys"),
        help="sysfs mount the vfio rebind path manipulates; a containerized "
        "driver mounts the host's at a prefix [SYSFS_ROOT]",
    )
    p.add_argument(
        "--dev-root", default=env_default("DEV_ROOT", "/dev"),
        help="device-node root for vfio group nodes [DEV_ROOT]",
    )
    p.add_argument(
        "--device-backend", default=env_default("DEVICE_BACKEND", "native"),
        choices=["mock", "native"],
    )
    p.add_argument("--tpuinfo-config", default=env_default("TPUINFO_CONFIG"))
    p.add_argument(
        "--healthcheck-port", type=int,
        default=int(env_default("HEALTHCHECK_PORT", "-1")),
        help="liveness HTTP port; < 0 disables [HEALTHCHECK_PORT]",
    )
    p.add_argument("--k8s-minor", type=int, default=int(env_default("K8S_MINOR", "35")))
    p.add_argument(
        "--mp-daemon-image",
        default=env_default("MP_DAEMON_IMAGE", "tpudra:latest"),
        help="image for per-claim multi-process control daemons; the binary "
        "ships in the driver image [MP_DAEMON_IMAGE]",
    )
    p.add_argument(
        "--no-claim-cache",
        action="store_true",
        default=env_default("NO_CLAIM_CACHE", "").lower() == "true",
        help="resolve every kubelet claim reference with a direct apiserver "
        "GET instead of the watch-backed informer cache (escape hatch; the "
        "cache is the default bind path) [NO_CLAIM_CACHE]",
    )
    p.add_argument(
        "--claim-informer-resync-s",
        type=float,
        default=float(env_default("CLAIM_INFORMER_RESYNC_S", "0")),
        help="claim-informer resync period: re-dispatch MODIFIED for cached "
        "objects to handlers (client-go semantics; it replays the cache, "
        "it does not refresh it — resolver safety rests on the UID guard "
        "and needs no resync, hence default off); <= 0 disables "
        "[CLAIM_INFORMER_RESYNC_S]",
    )
    p.add_argument(
        "--no-journal",
        action="store_true",
        default=env_default("NO_JOURNAL", "").lower() == "true",
        help="disable the append-only checkpoint journal: every mutation "
        "rewrites the full dual-version snapshot (the pre-journal "
        "behavior; bench A/B arm and the escape hatch for mixed-version "
        "node windows — old drivers never read checkpoint.wal, so a "
        "downgrade otherwise requires the clean-shutdown compaction) "
        "[NO_JOURNAL]",
    )
    p.add_argument(
        "--publish-debounce-ms",
        type=int,
        default=int(env_default("PUBLISH_DEBOUNCE_MS", "50")),
        help="coalescing window of the async ResourceSlice publisher: "
        "health/withheld events within one window cost one rebuild+write "
        "[PUBLISH_DEBOUNCE_MS]",
    )
    p.add_argument(
        "--publish-reassert-s",
        type=float,
        default=float(env_default("PUBLISH_REASSERT_S", "300")),
        help="re-assert published ResourceSlices older than this through "
        "the no-op content-hash gate, healing slices lost out-of-band; "
        "<= 0 disables [PUBLISH_REASSERT_S]",
    )
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    setup_common(args)

    from tpudra.plugin.driver import Driver, DriverConfig
    from tpudra.plugin.health import Healthcheck
    from tpudra.plugin.sharing import MultiProcessManager
    from tpudra.plugin.vfio import VfioManager

    kube = make_kube_client_from_args(args)
    lib = make_device_lib(args.device_backend, args.tpuinfo_config)
    driver = Driver(
        DriverConfig(
            node_name=args.node_name,
            plugin_dir=args.plugin_dir,
            registry_dir=args.registry_dir,
            cdi_root=args.cdi_root,
            driver_root=args.driver_root,
            k8s_minor=args.k8s_minor,
            device_backend=args.device_backend,
            claim_cache=not args.no_claim_cache,
            journal=not args.no_journal,
            claim_informer_resync_s=args.claim_informer_resync_s,
            publish_debounce_s=max(0.0, args.publish_debounce_ms / 1000.0),
            publish_reassert_s=args.publish_reassert_s,
        ),
        kube,
        lib,
        mp_manager=MultiProcessManager(
            kube, lib, args.node_name, image=args.mp_daemon_image
        ),
        vfio_manager=VfioManager(
            sysfs_root=args.sysfs_root, dev_root=args.dev_root
        ),
    )
    # Handlers go in before driver.start() publishes sockets/slices: anything
    # observing the published state may signal immediately (kubelet drain,
    # the system test), and the default disposition would kill us with no
    # teardown (reference orders this the same way, driver.go:170-200).
    stop = install_stop_handlers()
    hc = None
    try:
        driver.start()
        if args.healthcheck_port >= 0:
            hc = Healthcheck(driver.sockets, port=args.healthcheck_port)
            hc.start()
        logger.info("tpu-kubelet-plugin up on node %s", args.node_name)
        stop.wait()
    finally:
        if hc is not None:
            hc.stop()
        driver.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Chip-sharing strategy managers.

The analog of gpu-kubelet-plugin/sharing.go:

- TimeSlicingManager: TPUs have no `nvidia-smi compute-policy` knob; the
  interval is applied as a scheduling hint through the device library (carried
  to the runtime via env) and recorded for reset on unprepare
  (reference sharing.go:107-121 sets DEFAULT compute mode + timeslice).

- MultiProcessManager: the MPS analog.  Several processes share one chip,
  each pinned to an HBM budget and a TensorCore percentage.  Like the
  reference (sharing.go:123-445), a per-claim *control daemon* Deployment is
  stamped onto this node; it owns the chip in exclusive mode and brokers
  client processes through a pipe directory that is CDI-mounted into workload
  containers together with TPUDRA_MP_* env.
"""

from __future__ import annotations

import contextlib
import logging
import os
import signal
import subprocess
import sys
import time
from typing import Optional

import yaml

from tpudra import walwitness
from tpudra.api.sharing import DEFAULT_TIME_SLICE, MultiProcessConfig, TimeSlicingConfig
from tpudra.devicelib import DeviceLib
from tpudra.kube import gvr
from tpudra.kube.client import KubeAPI
from tpudra.plugin.cdi import ContainerEdits

logger = logging.getLogger(__name__)

MP_DAEMON_NAME_PREFIX = "tpu-mp-control-daemon-"
from tpudra.paths import template_path

DEFAULT_TEMPLATE_PATH = template_path("multi-process-daemon.tmpl.yaml")

DAEMON_PID_FILE = "daemon.pid"


class SharingError(Exception):
    pass


class LocalDaemonRunner:
    """Runs the real ``tpu-mp-control-daemon`` as a host subprocess.

    The production shape is the stamped Deployment (the pod runs the same
    console script; its readinessProbe is ``tpu-mp-control-daemon
    status``).  Harnesses with no kubelet — the e2e suite, the chaos
    soak, bats — hand the manager this runner so the broker contract
    (``limits.json`` + ``control.sock`` ATTACH/DETACH) is exercised by a
    REAL process, exactly like the multihost harness runs real rank
    processes.  A pid file in the pipe dir makes stop convergent across
    plugin restarts: a crashed plugin's orphan daemon is killed by pid at
    the next ``cleanup_stale`` even though the process handle died with
    the plugin."""

    def __init__(self):
        self._procs: dict[str, subprocess.Popen] = {}

    @staticmethod
    def _pid_path(pipe_dir: str) -> str:
        return os.path.join(pipe_dir, DAEMON_PID_FILE)

    def start(self, claim_uid: str, pipe_dir: str, env: dict[str, str]) -> int:
        os.makedirs(pipe_dir, exist_ok=True)
        full_env = dict(os.environ)
        full_env.update(env)
        # The child must import tpudra regardless of the caller's cwd
        # (harnesses launch from scratch dirs): pin the package root.
        import tpudra

        repo_root = os.path.dirname(
            os.path.dirname(os.path.abspath(tpudra.__file__))
        )
        full_env["PYTHONPATH"] = (
            repo_root + os.pathsep + full_env.get("PYTHONPATH", "")
        ).rstrip(os.pathsep)
        log_path = os.path.join(pipe_dir, "daemon.log")
        with open(log_path, "ab") as log:
            proc = subprocess.Popen(
                [sys.executable, "-m", "tpudra.mpdaemon", "run"],
                env=full_env,
                stdout=log,
                stderr=subprocess.STDOUT,
            )
        self._procs[claim_uid] = proc
        with open(self._pid_path(pipe_dir), "w") as f:
            f.write(str(proc.pid))
        logger.info(
            "mp control daemon for claim %s spawned (pid %d, pipe %s)",
            claim_uid, proc.pid, pipe_dir,
        )
        return proc.pid

    def pid(self, claim_uid: str, pipe_dir: str) -> Optional[int]:
        """The daemon's pid, or None when it is not running.  A pid read
        from the FILE (a prior plugin incarnation's daemon) is only
        trusted when the live process is identifiably OUR daemon — pids
        recycle, and signaling a recycled pid would kill an innocent
        process."""
        proc = self._procs.get(claim_uid)
        if proc is not None and proc.poll() is None:
            return proc.pid
        try:
            with open(self._pid_path(pipe_dir)) as f:
                pid = int(f.read().strip())
        except (OSError, ValueError):
            return None
        return pid if _pid_is_mpdaemon(pid) else None

    def stop(self, claim_uid: str, pipe_dir: str, timeout: float = 5.0) -> None:
        """Terminate the daemon (tracked handle, or the pid file when the
        handle died with a previous plugin incarnation).  Idempotent.
        The pid-file path only ever signals a process ``pid()`` verified
        as our daemon, and re-verifies before the SIGKILL escalation —
        pid recycling must never cost an unrelated process its life."""
        proc = self._procs.pop(claim_uid, None)
        if proc is not None:
            # poll() first: a child that already exited was (or will be)
            # reaped, and its pid may belong to someone else by now — the
            # same recycling hazard the pid-file path verifies against.
            if proc.poll() is None:
                with contextlib.suppress(OSError):
                    os.kill(proc.pid, signal.SIGTERM)
                try:
                    proc.wait(timeout)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()
        else:
            pid = self.pid(claim_uid, pipe_dir)
            if pid is not None:
                with contextlib.suppress(OSError):
                    os.kill(pid, signal.SIGTERM)
                deadline = time.monotonic() + timeout
                while time.monotonic() < deadline and _pid_is_mpdaemon(pid):
                    time.sleep(0.05)
                if _pid_is_mpdaemon(pid):
                    with contextlib.suppress(OSError):
                        os.kill(pid, signal.SIGKILL)
        with contextlib.suppress(OSError):
            os.unlink(self._pid_path(pipe_dir))


def _pid_is_mpdaemon(pid: int) -> bool:
    """True when ``pid`` is a live process identifiable as the mp control
    daemon (``/proc/<pid>/cmdline`` names tpudra.mpdaemon or the console
    script).  Unreadable cmdline (no /proc, a zombie child — which only
    a tracked handle can reap anyway) counts as NOT ours: the failure
    mode of a false negative is a leaked daemon the next cleanup pass
    retries; a false positive is a SIGKILL to an innocent process."""
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            cmdline = f.read().replace(b"\x00", b" ")
    except OSError:
        return False
    return b"mpdaemon" in cmdline or b"tpu-mp-control-daemon" in cmdline


class TimeSlicingManager:
    """Applies/resets cooperative time-slice intervals on full chips."""

    def __init__(self, devicelib: DeviceLib):
        self._lib = devicelib

    def set_timeslice(self, chip_uuids: list[str], config: Optional[TimeSlicingConfig]) -> str:
        interval = DEFAULT_TIME_SLICE
        if config is not None and config.interval is not None:
            interval = config.interval
        self._lib.set_timeslice(chip_uuids, interval)
        return interval

    def reset(self, chip_uuids: list[str]) -> None:
        self._lib.set_timeslice(chip_uuids, DEFAULT_TIME_SLICE)


class MultiProcessControlDaemon:
    """One per-claim control daemon (reference MpsControlDaemon, sharing.go:72)."""

    def __init__(
        self,
        manager: "MultiProcessManager",
        claim_uid: str,
        chip_uuids: list[str],
        config: MultiProcessConfig,
        limits: Optional[dict[str, str]] = None,
        tensorcore_pct: Optional[int] = None,
        exclusive: bool = True,
    ):
        self._m = manager
        self.claim_uid = claim_uid
        #: The UUIDs the broker brokers: whole-chip UUIDs for a chip claim,
        #: LIVE PARTITION UUIDs for a fractional (partition) claim.
        self.chip_uuids = chip_uuids
        self.config = config
        #: Pre-normalized pinned-HBM budgets (uuid → "NM"): the partition
        #: path derives each partition's budget from its profile's HBM
        #: fraction and overlays any explicit per-device limits; None
        #: falls back to the config's own normalization (chip mode).
        self._limits = limits
        self._pct = tensorcore_pct
        #: Chip mode pins the silicon exclusive (the MPS-owns-the-GPU
        #: analog); partition mode must NOT — sibling partitions of the
        #: same chip may belong to other claims' brokers.
        self.exclusive = exclusive
        self.name = MP_DAEMON_NAME_PREFIX + claim_uid

    @property
    def pipe_dir(self) -> str:
        return os.path.join(self._m.pipe_root, self.claim_uid)

    @property
    def shm_dir(self) -> str:
        return os.path.join(self._m.pipe_root, self.claim_uid, "shm")

    def resolved_limits(self) -> dict[str, str]:
        if self._limits is not None:
            return dict(self._limits)
        return self.config.normalized_limits(self.chip_uuids)

    def resolved_pct(self) -> int:
        if self.config.default_active_tensorcore_percentage is not None:
            return self.config.default_active_tensorcore_percentage
        return self._pct if self._pct is not None else 100

    def daemon_env(self, limits: dict[str, str]) -> dict[str, str]:
        """The broker's own env — one rendering shared by the Deployment
        template and the local runner, so the two execution shapes cannot
        drift (tpudra/mpdaemon.py consumes exactly these)."""
        return {
            "TPUDRA_MP_PIPE_DIRECTORY": self.pipe_dir,
            "TPUDRA_MP_CHIP_UUIDS": ",".join(self.chip_uuids),
            "TPUDRA_MP_ACTIVE_TENSORCORE_PERCENTAGE": str(self.resolved_pct()),
            "TPUDRA_MP_PINNED_HBM_LIMITS": ";".join(
                f"{k}={v}" for k, v in sorted(limits.items())
            ),
            "TPUDRA_MP_PLATFORM_MODE": self._m.devicelib.multiprocess_mode(),
        }

    def start(self) -> None:
        """Pin chips exclusive (chip mode), stamp the daemon Deployment
        onto this node (reference sharing.go:186-291), and — when the
        manager carries a local runner — actually spawn the broker
        process the Deployment describes."""
        if self.exclusive:
            self._m.devicelib.set_exclusive(self.chip_uuids, True)
        os.makedirs(self.shm_dir, exist_ok=True)
        limits = self.resolved_limits()
        deployment = self._m.render_template(
            name=self.name,
            claim_uid=self.claim_uid,
            chip_uuids=self.chip_uuids,
            tensorcore_pct=self.resolved_pct(),
            hbm_limits=limits,
            pipe_dir=self.pipe_dir,
            platform_mode=self._m.devicelib.multiprocess_mode(),
        )
        self._m.stamp_deployment(deployment)
        if self._m.runner is not None:
            self._m.runner.start(
                self.claim_uid, self.pipe_dir, self.daemon_env(limits)
            )

    def probe_ready(self) -> bool:
        """One READY probe of the broker's control socket — the same
        contract the pod's readinessProbe runs (``tpu-mp-control-daemon
        status``), asked directly over the hostPath pipe dir."""
        from tpudra import mpdaemon

        try:
            return mpdaemon.query(self.pipe_dir, "STATUS").startswith("READY")
        except OSError:
            return False

    def assert_ready(self, timeout: float = 30.0, poll: float = 1.0) -> None:
        """Block until the broker is READY (reference AssertReady,
        sharing.go:293-349).  With a local runner the truth is the
        control socket itself; without one (production: the daemon runs
        inside the stamped pod) the Deployment's readyReplicas — fed by
        the pod's ``status``-subcommand readinessProbe — is the kubelet's
        word for the same probe.  Check-first, then a gentle poll: this
        runs inside NodePrepareResources, and tens of concurrent prepares
        hammering the apiserver at high frequency would be self-inflicted
        load.  Not-ready raises SharingError, which the bind path maps to
        a RETRYABLE prepare error (permanent=false): kubelet retries
        while the daemon comes up."""
        deadline = time.monotonic() + timeout
        while True:
            if self._m.runner is not None:
                if self.probe_ready():
                    return
            else:
                dep = self._m.get_deployment(self.name)
                if dep and dep.get("status", {}).get("readyReplicas", 0) >= 1:
                    return
            if time.monotonic() >= deadline:
                raise SharingError(
                    f"multi-process control daemon {self.name} not ready after {timeout}s"
                )
            time.sleep(min(poll, max(0.0, deadline - time.monotonic())))

    def get_cdi_edits(self) -> ContainerEdits:
        """Edits injected into every consumer of the claim
        (reference GetCDIContainerEdits, sharing.go:350-370)."""
        return ContainerEdits(
            env=[
                f"TPUDRA_MP_PIPE_DIRECTORY=/var/run/tpudra/mp/{self.claim_uid}",
                "TPUDRA_MP_ACTIVE_TENSORCORE_PERCENTAGE="
                f"{self.resolved_pct()}",
            ],
            mounts=[
                (self.pipe_dir, f"/var/run/tpudra/mp/{self.claim_uid}"),
                (self.shm_dir, "/dev/shm/tpudra-mp"),
            ],
        )

    def stop(self) -> None:
        self._m.delete_deployment(self.name)
        if self._m.runner is not None:
            self._m.runner.stop(self.claim_uid, self.pipe_dir)
        if self.exclusive:
            self._m.devicelib.set_exclusive(self.chip_uuids, False)


class MultiProcessManager:
    def __init__(
        self,
        kube: KubeAPI,
        devicelib: DeviceLib,
        node_name: str,
        namespace: str = "tpudra-system",
        pipe_root: str = "/var/run/tpudra/mp",
        template_path: str = DEFAULT_TEMPLATE_PATH,
        # The control daemon ships IN the driver image (console script
        # tpu-mp-control-daemon); the chart passes the deployed driver
        # image via --mp-daemon-image / MP_DAEMON_IMAGE.
        image: str = "tpudra:latest",
        # Optional execution seam: a LocalDaemonRunner actually spawns
        # the broker process the Deployment describes (harnesses without
        # a kubelet); None leaves execution to the stamped pod.
        runner: Optional[LocalDaemonRunner] = None,
    ):
        self.kube = kube
        self.devicelib = devicelib
        self.node_name = node_name
        self.namespace = namespace
        self.pipe_root = pipe_root
        self.template_path = template_path
        self.image = image
        self.runner = runner

    def new_daemon(
        self,
        claim_uid: str,
        chip_uuids: list[str],
        config: MultiProcessConfig,
        limits: Optional[dict[str, str]] = None,
        tensorcore_pct: Optional[int] = None,
        exclusive: bool = True,
    ) -> MultiProcessControlDaemon:
        walwitness.note_effect("daemon:start")
        return MultiProcessControlDaemon(
            self, claim_uid, chip_uuids, config,
            limits=limits, tensorcore_pct=tensorcore_pct, exclusive=exclusive,
        )

    def daemon_for(
        self, claim_uid: str, chip_uuids: list[str], exclusive: bool = True
    ) -> MultiProcessControlDaemon:
        """Reconstruct a handle for stop() from checkpoint state."""
        return MultiProcessControlDaemon(
            self, claim_uid, chip_uuids, MultiProcessConfig(),
            exclusive=exclusive,
        )

    # The apiserver verbs live on the MANAGER, not the daemon handle: the
    # daemon reaches the cluster only through these, which keeps the
    # static lock model exact — ``self.kube`` is a one-hop annotated
    # attribute the analyzer resolves to KubeAPI verbs, so the
    # effects-phase edge flock:claim-uid → accounting.counts_lock (the
    # edge the partition_fault soak witnessed) derives statically.

    def stamp_deployment(self, deployment: dict) -> None:
        from tpudra.kube.errors import AlreadyExists

        try:
            self.kube.create(gvr.DEPLOYMENTS, deployment, self.namespace)
        except AlreadyExists:
            pass  # retry of a crashed prepare: the stamp already landed

    def get_deployment(self, name: str) -> Optional[dict]:
        try:
            return self.kube.get(gvr.DEPLOYMENTS, name, self.namespace)
        except Exception:  # noqa: BLE001 — not-ready poll tolerates blips
            return None

    def delete_deployment(self, name: str) -> None:
        from tpudra.kube.errors import NotFound

        try:
            self.kube.delete(gvr.DEPLOYMENTS, name, self.namespace)
        except NotFound:
            pass

    def cleanup_stale(self, valid_claim_uids: set[str]) -> int:
        """Startup GC: delete control-daemon Deployments on this node whose
        claim is no longer checkpointed (crash between daemon.start() and
        checkpoint completion leaks one), and release their chips from
        exclusive mode."""
        from tpudra.kube.errors import NotFound

        listing = self.kube.list(
            gvr.DEPLOYMENTS,
            namespace=self.namespace,
            label_selector=(
                "app.kubernetes.io/name=tpu-mp-control-daemon,"
                f"tpu.google.com/node={self.node_name}"
            ),
        )
        removed = 0
        reaped_uids: set[str] = set()
        for dep in listing.get("items", []):
            uid = dep["metadata"].get("labels", {}).get("tpu.google.com/claim-uid", "")
            if uid in valid_claim_uids:
                continue
            reaped_uids.add(uid)
            chip_uuids = []
            for c in dep.get("spec", {}).get("template", {}).get("spec", {}).get(
                "containers", []
            ):
                for env in c.get("env", []):
                    if env.get("name") == "TPUDRA_MP_CHIP_UUIDS" and env.get("value"):
                        chip_uuids = env["value"].split(",")
            logger.info("removing stale mp control daemon %s", dep["metadata"]["name"])
            try:
                self.kube.delete(gvr.DEPLOYMENTS, dep["metadata"]["name"], self.namespace)
            except NotFound:
                pass
            if chip_uuids:
                try:
                    self.devicelib.set_exclusive(chip_uuids, False)
                except Exception:  # noqa: BLE001 — chips may be gone
                    logger.warning("could not release chips %s", chip_uuids)
            removed += 1
        # Local-runner convergence: a daemon PROCESS leaked by a crashed
        # plugin (its handle died with the plugin; only the pid file
        # remains) is killed by pid for every pipe dir whose claim is no
        # longer checkpointed — the "no live daemon without a checkpoint
        # record" half of the partition-leak story.
        if self.runner is not None:
            try:
                pipe_entries = os.listdir(self.pipe_root)
            except FileNotFoundError:
                pipe_entries = []
            for uid in pipe_entries:
                pipe_dir = os.path.join(self.pipe_root, uid)
                if uid in valid_claim_uids or not os.path.isdir(pipe_dir):
                    continue
                if self.runner.pid(uid, pipe_dir) is None:
                    continue  # dead already (pid() verifies liveness+identity)
                logger.info("stopping stale local mp daemon for claim %s", uid)
                self.runner.stop(uid, pipe_dir)
                # One stale claim = one removal, even when both its
                # Deployment and its local process were reaped this pass.
                if uid not in reaped_uids:
                    removed += 1
        return removed

    def render_template(
        self,
        name: str,
        claim_uid: str,
        chip_uuids: list[str],
        tensorcore_pct: int,
        hbm_limits: dict[str, str],
        pipe_dir: str,
        platform_mode: str = "unknown",
    ) -> dict:
        """Render templates/multi-process-daemon.tmpl.yaml
        (reference templates/mps-control-daemon.tmpl.yaml)."""
        with open(self.template_path) as f:
            text = f.read()
        rendered = text.format(
            name=name,
            namespace=self.namespace,
            node_name=self.node_name,
            claim_uid=claim_uid,
            image=self.image,
            chip_uuids=",".join(chip_uuids),
            tensorcore_pct=tensorcore_pct,
            hbm_limits=";".join(f"{k}={v}" for k, v in sorted(hbm_limits.items())),
            pipe_dir=pipe_dir,
            platform_mode=platform_mode,
        )
        return yaml.safe_load(rendered)

"""Chip-sharing strategy managers.

The analog of gpu-kubelet-plugin/sharing.go:

- TimeSlicingManager: TPUs have no `nvidia-smi compute-policy` knob; the
  interval is applied as a scheduling hint through the device library (carried
  to the runtime via env) and recorded for reset on unprepare
  (reference sharing.go:107-121 sets DEFAULT compute mode + timeslice).

- MultiProcessManager: the MPS analog.  Several processes share one chip,
  each pinned to an HBM budget and a TensorCore percentage.  Like the
  reference (sharing.go:123-445), a per-claim *control daemon* Deployment is
  stamped onto this node; it owns the chip in exclusive mode and brokers
  client processes through a pipe directory that is CDI-mounted into workload
  containers together with TPUDRA_MP_* env.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Optional

import yaml

from tpudra.api.sharing import DEFAULT_TIME_SLICE, MultiProcessConfig, TimeSlicingConfig
from tpudra.devicelib import DeviceLib
from tpudra.kube import gvr
from tpudra.kube.client import KubeAPI
from tpudra.plugin.cdi import ContainerEdits

logger = logging.getLogger(__name__)

MP_DAEMON_NAME_PREFIX = "tpu-mp-control-daemon-"
from tpudra.paths import template_path

DEFAULT_TEMPLATE_PATH = template_path("multi-process-daemon.tmpl.yaml")


class SharingError(Exception):
    pass


class TimeSlicingManager:
    """Applies/resets cooperative time-slice intervals on full chips."""

    def __init__(self, devicelib: DeviceLib):
        self._lib = devicelib

    def set_timeslice(self, chip_uuids: list[str], config: Optional[TimeSlicingConfig]) -> str:
        interval = DEFAULT_TIME_SLICE
        if config is not None and config.interval is not None:
            interval = config.interval
        self._lib.set_timeslice(chip_uuids, interval)
        return interval

    def reset(self, chip_uuids: list[str]) -> None:
        self._lib.set_timeslice(chip_uuids, DEFAULT_TIME_SLICE)


class MultiProcessControlDaemon:
    """One per-claim control daemon (reference MpsControlDaemon, sharing.go:72)."""

    def __init__(
        self,
        manager: "MultiProcessManager",
        claim_uid: str,
        chip_uuids: list[str],
        config: MultiProcessConfig,
    ):
        self._m = manager
        self.claim_uid = claim_uid
        self.chip_uuids = chip_uuids
        self.config = config
        self.name = MP_DAEMON_NAME_PREFIX + claim_uid

    @property
    def pipe_dir(self) -> str:
        return os.path.join(self._m.pipe_root, self.claim_uid)

    @property
    def shm_dir(self) -> str:
        return os.path.join(self._m.pipe_root, self.claim_uid, "shm")

    def start(self) -> None:
        """Pin chips exclusive and stamp the daemon Deployment onto this node
        (reference sharing.go:186-291)."""
        self._m.devicelib.set_exclusive(self.chip_uuids, True)
        os.makedirs(self.shm_dir, exist_ok=True)
        limits = self.config.normalized_limits(self.chip_uuids)
        deployment = self._m.render_template(
            name=self.name,
            claim_uid=self.claim_uid,
            chip_uuids=self.chip_uuids,
            tensorcore_pct=self.config.default_active_tensorcore_percentage or 100,
            hbm_limits=limits,
            pipe_dir=self.pipe_dir,
            platform_mode=self._m.devicelib.multiprocess_mode(),
        )
        try:
            self._m.kube.create(gvr.DEPLOYMENTS, deployment, self._m.namespace)
        except Exception as e:  # AlreadyExists on retry is fine
            from tpudra.kube.errors import AlreadyExists

            if not isinstance(e, AlreadyExists):
                raise

    def assert_ready(self, timeout: float = 30.0, poll: float = 1.0) -> None:
        """Block until the daemon Deployment reports a ready replica
        (reference AssertReady, sharing.go:293-349).  Check-first, then a
        gentle poll — this runs inside NodePrepareResources, and tens of
        concurrent prepares hammering the apiserver at high frequency would
        be self-inflicted load."""
        deadline = time.monotonic() + timeout
        while True:
            try:
                dep = self._m.kube.get(gvr.DEPLOYMENTS, self.name, self._m.namespace)
            except Exception:
                dep = None
            if dep and dep.get("status", {}).get("readyReplicas", 0) >= 1:
                return
            if time.monotonic() >= deadline:
                raise SharingError(
                    f"multi-process control daemon {self.name} not ready after {timeout}s"
                )
            time.sleep(min(poll, max(0.0, deadline - time.monotonic())))

    def get_cdi_edits(self) -> ContainerEdits:
        """Edits injected into every consumer of the claim
        (reference GetCDIContainerEdits, sharing.go:350-370)."""
        return ContainerEdits(
            env=[
                f"TPUDRA_MP_PIPE_DIRECTORY=/var/run/tpudra/mp/{self.claim_uid}",
                "TPUDRA_MP_ACTIVE_TENSORCORE_PERCENTAGE="
                f"{self.config.default_active_tensorcore_percentage or 100}",
            ],
            mounts=[
                (self.pipe_dir, f"/var/run/tpudra/mp/{self.claim_uid}"),
                (self.shm_dir, "/dev/shm/tpudra-mp"),
            ],
        )

    def stop(self) -> None:
        from tpudra.kube.errors import NotFound

        try:
            self._m.kube.delete(gvr.DEPLOYMENTS, self.name, self._m.namespace)
        except NotFound:
            pass
        self._m.devicelib.set_exclusive(self.chip_uuids, False)


class MultiProcessManager:
    def __init__(
        self,
        kube: KubeAPI,
        devicelib: DeviceLib,
        node_name: str,
        namespace: str = "tpudra-system",
        pipe_root: str = "/var/run/tpudra/mp",
        template_path: str = DEFAULT_TEMPLATE_PATH,
        # The control daemon ships IN the driver image (console script
        # tpu-mp-control-daemon); the chart passes the deployed driver
        # image via --mp-daemon-image / MP_DAEMON_IMAGE.
        image: str = "tpudra:latest",
    ):
        self.kube = kube
        self.devicelib = devicelib
        self.node_name = node_name
        self.namespace = namespace
        self.pipe_root = pipe_root
        self.template_path = template_path
        self.image = image

    def new_daemon(
        self, claim_uid: str, chip_uuids: list[str], config: MultiProcessConfig
    ) -> MultiProcessControlDaemon:
        return MultiProcessControlDaemon(self, claim_uid, chip_uuids, config)

    def daemon_for(self, claim_uid: str, chip_uuids: list[str]) -> MultiProcessControlDaemon:
        """Reconstruct a handle for stop() from checkpoint state."""
        return MultiProcessControlDaemon(self, claim_uid, chip_uuids, MultiProcessConfig())

    def cleanup_stale(self, valid_claim_uids: set[str]) -> int:
        """Startup GC: delete control-daemon Deployments on this node whose
        claim is no longer checkpointed (crash between daemon.start() and
        checkpoint completion leaks one), and release their chips from
        exclusive mode."""
        from tpudra.kube.errors import NotFound

        listing = self.kube.list(
            gvr.DEPLOYMENTS,
            namespace=self.namespace,
            label_selector=(
                "app.kubernetes.io/name=tpu-mp-control-daemon,"
                f"tpu.google.com/node={self.node_name}"
            ),
        )
        removed = 0
        for dep in listing.get("items", []):
            uid = dep["metadata"].get("labels", {}).get("tpu.google.com/claim-uid", "")
            if uid in valid_claim_uids:
                continue
            chip_uuids = []
            for c in dep.get("spec", {}).get("template", {}).get("spec", {}).get(
                "containers", []
            ):
                for env in c.get("env", []):
                    if env.get("name") == "TPUDRA_MP_CHIP_UUIDS" and env.get("value"):
                        chip_uuids = env["value"].split(",")
            logger.info("removing stale mp control daemon %s", dep["metadata"]["name"])
            try:
                self.kube.delete(gvr.DEPLOYMENTS, dep["metadata"]["name"], self.namespace)
            except NotFound:
                pass
            if chip_uuids:
                try:
                    self.devicelib.set_exclusive(chip_uuids, False)
                except Exception:  # noqa: BLE001 — chips may be gone
                    logger.warning("could not release chips %s", chip_uuids)
            removed += 1
        return removed

    def render_template(
        self,
        name: str,
        claim_uid: str,
        chip_uuids: list[str],
        tensorcore_pct: int,
        hbm_limits: dict[str, str],
        pipe_dir: str,
        platform_mode: str = "unknown",
    ) -> dict:
        """Render templates/multi-process-daemon.tmpl.yaml
        (reference templates/mps-control-daemon.tmpl.yaml)."""
        with open(self.template_path) as f:
            text = f.read()
        rendered = text.format(
            name=name,
            namespace=self.namespace,
            node_name=self.node_name,
            claim_uid=claim_uid,
            image=self.image,
            chip_uuids=",".join(chip_uuids),
            tensorcore_pct=tensorcore_pct,
            hbm_limits=";".join(f"{k}={v}" for k, v in sorted(hbm_limits.items())),
            pipe_dir=pipe_dir,
            platform_mode=platform_mode,
        )
        return yaml.safe_load(rendered)

"""Watch-backed ResourceClaim resolution: the apiserver off the bind path.

PR 1 made the node-local half of the bind path fast; the remote half still
paid one synchronous apiserver GET per claim in every NodePrepareResources
(`grpcserver.kube_claim_resolver`).  At production scale that is O(churn ×
nodes) apiserver load sitting in front of every bind — the reference driver
avoids it with client-go shared informers feeding its draclient lookups
(vendored kubeletplugin/draplugin.go), and this module is that analog:

- **Cache hit**: once the claim informer has synced AND its watch is
  live, a cached object whose uid matches the reference kubelet sent is
  returned without touching the apiserver.  The UID guard is what makes
  the cache safe: kubelet names the exact object generation it wants
  (namespace/name/uid), allocations only change through delete-and-
  recreate (uid change) or an explicit deallocate→reallocate (a status
  rewrite the watch delivers, and which evicts the claim from the
  driver's filtered cache in between) — so with a live watch, a
  uid-matching cached copy carrying an allocation matches a live GET for
  every field the bind path reads, up to delivery lag of milliseconds.
  While the watch is broken (``Informer.watch_healthy`` False), lag can
  grow to the relist backoff, so resolution falls back to GETs.
- **Read-through fallback**: pre-sync (an empty cache looks like "nothing
  exists"), a cache miss, a cached object whose uid does NOT match (the
  watch may lag a delete-and-recreate — the live object must get the final
  word before a UID-mismatch error), or a cached copy with no allocation
  yet (the status watch event may lag the scheduler) all fall back to a
  direct GET, exactly what the resolver did before the cache existed.
- **Singleflight**: N resolver-pool threads missing on the same claim
  collapse into ONE in-flight GET; the rest wait for the leader's result.

Every resolution outcome lands in ``tpudra_claim_resolutions_total`` and
collapses in ``tpudra_claim_singleflight_collapsed_total`` — the
steady-state criterion is ~all-cache with fallback GETs < 5% of
resolutions (docs/bind-path.md).
"""

from __future__ import annotations

import copy
import logging
import threading
from typing import Callable

from tpudra import lockwitness, metrics
from tpudra.kube import gvr
from tpudra.kube.client import KubeAPI
from tpudra.kube.informer import Informer

logger = logging.getLogger(__name__)


class _Call:
    __slots__ = ("done", "result", "error", "waiters")

    def __init__(self):
        self.done = threading.Event()
        self.result = None
        self.error: BaseException | None = None
        self.waiters = 0


class Singleflight:
    """Deduplicate concurrent identical calls: the first caller for a key
    (the leader) runs ``fn``; every caller that arrives while that call is
    in flight waits for the leader's result instead of issuing its own.
    Callers arriving after the leader finished start a fresh call — this
    collapses concurrency, it is not a cache."""

    def __init__(self):
        self._lock = lockwitness.make_lock("singleflight.lock")
        self._calls: dict[tuple, _Call] = {}

    def do(self, key: tuple, fn: Callable[[], dict]) -> tuple[dict, bool]:
        """Run ``fn`` (or wait on whoever already is); returns
        ``(result, leader)``.  Followers get a deep copy so no two callers
        share one mutable claim dict; the leader's exception is re-raised
        in every waiter."""
        with self._lock:
            call = self._calls.get(key)
            leader = call is None
            if leader:
                call = _Call()
                self._calls[key] = call
            else:
                call.waiters += 1
        if not leader:
            call.done.wait()
            if call.error is not None:
                raise call.error
            return copy.deepcopy(call.result), False
        try:
            call.result = fn()
        except BaseException as e:
            call.error = e
            raise
        finally:
            with self._lock:
                self._calls.pop(key, None)
            call.done.set()
        return call.result, True

    def waiting(self, key: tuple) -> int:
        """How many followers are parked on ``key`` right now (tests)."""
        with self._lock:
            call = self._calls.get(key)
            return call.waiters if call is not None else 0


class CachedClaimResolver:
    """A ``ClaimResolver`` (grpcserver contract: ``(namespace, name, uid)
    -> full ResourceClaim dict, or raise``) served from an informer cache
    with read-through GET fallback and singleflight deduplication."""

    def __init__(self, kube: KubeAPI, informer: Informer):
        self._kube = kube
        self._informer = informer
        self._singleflight = Singleflight()

    def __call__(self, namespace: str, name: str, uid: str) -> dict:
        source = self._cache_lookup(namespace, name, uid)
        if isinstance(source, dict):
            metrics.count_resolution(metrics.RESOLVE_CACHE)
            return source
        metrics.count_resolution(source)
        claim, leader = self._singleflight.do(
            (namespace, name, uid),
            lambda: self._kube.get(gvr.RESOURCE_CLAIMS, name, namespace),
        )
        if not leader:
            metrics.CLAIM_SINGLEFLIGHT_COLLAPSED.inc()
        have_uid = claim.get("metadata", {}).get("uid", "")
        if uid and have_uid != uid:
            raise ValueError(
                f"UID mismatch: live claim has {have_uid!r}, want {uid!r}"
            )
        return claim

    def _cache_lookup(self, namespace: str, name: str, uid: str):
        """The cached claim (a private copy) on a safe hit, else the
        fallback reason for the resolutions counter."""
        if not self._informer.has_synced:
            return metrics.RESOLVE_GET_PRESYNC
        if not self._informer.watch_healthy:
            # A broken watch widens cache lag from delivery latency
            # (milliseconds) to the relist backoff (up to ~30 s) — wide
            # enough for a deallocate→reallocate of the SAME uid to hide
            # in.  Treat it like pre-sync until the relist lands.
            return metrics.RESOLVE_GET_WATCH_DOWN
        cached = self._informer.get(name, namespace)
        if cached is None:
            return metrics.RESOLVE_GET_MISS
        have_uid = cached.get("metadata", {}).get("uid", "")
        if uid and have_uid != uid:
            # Deleted-and-recreated claim the watch hasn't caught up with:
            # only the LIVE object may ground a UID-mismatch error.
            return metrics.RESOLVE_GET_STALE_UID
        if not cached.get("status", {}).get("allocation"):
            # Kubelet only prepares allocated claims; a cached copy without
            # an allocation is behind the scheduler's status write.
            return metrics.RESOLVE_GET_UNALLOCATED
        # Deep copy: the store object is shared with every other reader and
        # the prepare path must never see a claim mutated under it.
        return copy.deepcopy(cached)

"""The real kubelet plugin wire protocol: gRPC over two unix sockets.

This is the faithful analog of the reference's use of the
k8s.io/dynamic-resource-allocation ``kubeletplugin.Start`` helper
(gpu-kubelet-plugin/driver.go:123-132, vendored
kubeletplugin/draplugin.go:560-680):

- a *registration* socket under kubelet's ``plugins_registry/`` serving
  ``pluginregistration.Registration`` — kubelet's pluginwatcher dials every
  socket that appears there, calls GetInfo, and acks with
  NotifyRegistrationStatus;
- a *DRA service* socket (``dra.sock`` in the per-driver plugin data dir)
  serving ``k8s.io.kubelet.pkg.apis.dra.v1.DRAPlugin`` and the identical
  ``...dra.v1beta1.DRAPlugin`` (kubelet ≤1.33), exactly as the reference
  registers both versions (draplugin.go:652-657).

Kubelet sends only Claim *references* (namespace/uid/name); the driver
resolves them against the API server for the allocation result — the same
division of labor as the reference helper's draclient lookup.  Message
classes come from protoc-generated modules (``protos/generate.sh``); the
service plumbing is hand-written with grpc generic handlers so no grpc_tools
dependency is needed.
"""

from __future__ import annotations

import contextvars
import logging
import os
import threading
from concurrent import futures
from typing import Callable, Optional

import grpc

from tpudra import trace
from tpudra.kube.deadline import api_deadline

from tpudra.drapb import dra_v1_pb2 as drapb
from tpudra.drapb import dra_v1beta1_pb2 as drapb_beta
from tpudra.drapb import pluginregistration_v1_pb2 as regpb

logger = logging.getLogger(__name__)

DRA_PLUGIN_TYPE = "DRAPlugin"
# supported_versions carries DRA gRPC *service names*; kubelet picks the
# newest it speaks (vendored kubeletplugin/draplugin.go:617-621).
DRA_SERVICE_V1 = "v1.DRAPlugin"
DRA_SERVICE_V1BETA1 = "v1beta1.DRAPlugin"
SUPPORTED_SERVICES = [DRA_SERVICE_V1, DRA_SERVICE_V1BETA1]

_V1_SERVICE = "k8s.io.kubelet.pkg.apis.dra.v1.DRAPlugin"
_V1BETA1_SERVICE = "k8s.io.kubelet.pkg.apis.dra.v1beta1.DRAPlugin"
_REG_SERVICE = "pluginregistration.Registration"

# Resolves a Claim reference to the full ResourceClaim object, or raises.
ClaimResolver = Callable[[str, str, str], dict]

#: Apiserver budget for one NodePrepare/NodeUnprepare call: kubelet's DRA
#: client deadline is 30 s (DRAClient mirrors it) — leave headroom so a
#: latency-spiked apiserver verb fails the RPC *inside* the deadline with
#: a retryable per-claim error instead of wedging a gRPC worker past it
#: (kube/deadline.py; the chaos soak's apiserver_latency fault pins this).
DEFAULT_RPC_API_BUDGET_S = 25.0


def kube_claim_resolver(kube) -> ClaimResolver:
    """The direct-GET resolver: fetch the ResourceClaim and enforce the
    stale-UID guard.  Kubelet only sends (namespace, uid, name) on the
    wire; the allocation result lives in the API object — the same
    division of labor as the reference helper's draclient lookup.  A UID
    mismatch means the claim was deleted and re-created; preparing against
    the old allocation would grant the wrong devices.

    This is the uncached fallback arm (``DriverConfig.claim_cache=False``
    and the bench A/B): the production path routes resolution through the
    watch-backed ``claimresolver.CachedClaimResolver``, which applies the
    same UID guard against its cache and only GETs on miss/pre-sync —
    with singleflight so N resolver-pool threads missing on one claim
    issue one GET, not N."""
    from tpudra.kube import gvr  # local import to avoid a cycle at module load

    def resolve(namespace: str, name: str, uid: str) -> dict:
        claim = kube.get(gvr.RESOURCE_CLAIMS, name, namespace)
        have_uid = claim.get("metadata", {}).get("uid", "")
        if uid and have_uid != uid:
            raise ValueError(
                f"UID mismatch: live claim has {have_uid!r}, want {uid!r}"
            )
        return claim

    return resolve


class RPCError(Exception):
    """Client-side failure surfaced from a DRA/registration RPC."""


def _unix_addr(path: str) -> str:
    return "unix:" + os.path.abspath(path)


def _metadata_traceparent(context) -> Optional[str]:
    """The caller's traceparent from gRPC invocation metadata, or None —
    the kubelet boundary half of trace propagation (DRAClient sends it,
    the handlers adopt it as the RPC span's parent)."""
    try:
        metadata = context.invocation_metadata() if context is not None else ()
    except Exception:  # noqa: BLE001 — a sim context without metadata
        return None
    for key, value in metadata or ():
        if key == trace.GRPC_METADATA_KEY:
            return value
    return None


def _serve(path: str, generic_handlers: tuple) -> grpc.Server:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    if os.path.exists(path):
        os.unlink(path)
    server = grpc.server(
        futures.ThreadPoolExecutor(
            max_workers=8, thread_name_prefix=f"grpc:{os.path.basename(path)}"
        )
    )
    server.add_generic_rpc_handlers(generic_handlers)
    server.add_insecure_port(_unix_addr(path))
    server.start()
    return server


def _unary(fn, deserializer, msg_cls):
    return grpc.unary_unary_rpc_method_handler(
        fn,
        request_deserializer=deserializer,
        response_serializer=msg_cls.SerializeToString,
    )


class PluginSockets:
    """Registration + DRA-service gRPC sockets for one driver.

    ``prepare``/``unprepare`` are the Driver's claim fan-in callables and
    keep their dict contract:

      prepare(full_claims)  → {"claims": {uid: {"devices": [...]} | {"error": str}}}
      unprepare(refs)       → {"claims": {uid: {} | {"error": str}}}

    ``resolve_claim(namespace, name, uid)`` turns a kubelet Claim reference
    into the full ResourceClaim dict (normally an API-server GET).
    """

    def __init__(
        self,
        driver_name: str,
        plugin_dir: str,
        registry_dir: str,
        prepare: Callable[[list[dict]], dict],
        unprepare: Callable[[list[dict]], dict],
        resolve_claim: ClaimResolver,
        shed_probe: Optional[Callable[[str], Optional[str]]] = None,
    ):
        self.driver_name = driver_name
        self.dra_socket_path = os.path.join(plugin_dir, "dra.sock")
        self.registration_socket_path = os.path.join(
            registry_dir, f"{driver_name}-reg.sock"
        )
        self._prepare = prepare
        self._unprepare = unprepare
        self._resolve_claim = resolve_claim
        # Degraded-mode probe (docs/bind-path.md "Storage fault
        # contract"): called with the op name BEFORE any claim-reference
        # resolution; a non-None return is the typed retryable error every
        # claim of the batch gets — so a node whose checkpoint storage is
        # down sheds with ZERO apiserver work, even when the resolver's
        # fallback GET would itself be slow (a compounding latency spike).
        self._shed_probe = shed_probe
        self._registered = threading.Event()
        self._dra_server: Optional[grpc.Server] = None
        self._reg_server: Optional[grpc.Server] = None
        # Claim-reference resolution fan-out (threads spawn lazily; only
        # multi-claim batches ever submit to it).
        self._resolver_pool = futures.ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="claim-resolve"
        )
        # Optional third service on the DRA socket: the kubelet-facing
        # v1alpha1.DRAResourceHealth stream.  Mirrors the official helper's
        # implements-it-then-serve-and-advertise semantics
        # (draplugin.go:623-663): set before start() or not at all.
        self.health_broadcaster = None  # Optional[HealthBroadcaster]

    @property
    def resolve_claim(self) -> ClaimResolver:
        """The claim-reference resolver the DRA service runs on every
        NodePrepareResources.  The cluster harness (sim/cluster.py) calls
        it directly to model kubelet's ref→object step without paying a
        gRPC server per simulated node."""
        return self._resolve_claim

    # ------------------------------------------------------------ DRA bridge

    def _resolve_all(self, refs) -> list[tuple]:
        """Resolve every claim reference, concurrently when the batch has
        more than one (a resolution can be an API-server GET — serial
        lookups would put N round-trips ahead of the bind path; with the
        cached resolver a fan-out of hits costs nothing and concurrent
        misses on one claim collapse to a single GET via singleflight).
        Pool workers run under a COPY of the calling context so the RPC's
        ambient apiserver deadline (kube/deadline.py) travels with each
        fallback GET — contextvars do not cross executor threads on their
        own.  Returns [(ref, claim-or-None, error-or-None)] in request
        order."""
        def one(ref):
            try:
                return ref, self._resolve_claim(ref.namespace, ref.name, ref.uid), None
            except Exception as e:  # noqa: BLE001 — per-claim fault barrier
                return ref, None, e

        refs = list(refs)
        if len(refs) <= 1:
            return [one(ref) for ref in refs]
        ctx = contextvars.copy_context()
        return list(
            self._resolver_pool.map(lambda ref: ctx.copy().run(one, ref), refs)
        )

    def _node_prepare(self, request, context, pb):
        """Resolve claim refs → run the driver's prepare → proto response.

        Every requested claim gets an entry (kubelet re-calls for missing
        ones); a reference that fails to resolve gets a per-claim error, the
        same contract as the reference helper's claim lookup.

        The whole call runs under an ambient apiserver deadline
        (``DEFAULT_RPC_API_BUDGET_S``): any apiserver verb on the path —
        the resolver's fallback GET above all — fails fast with a typed,
        retryable 504 once the budget is gone, so an apiserver latency
        spike cannot wedge this handler past kubelet's own gRPC deadline.
        """
        resp = pb.NodePrepareResourcesResponse()
        with trace.start_span(
            "rpc.NodePrepareResources",
            parent=_metadata_traceparent(context),
            attrs={"claims": len(request.claims)},
        ), api_deadline(DEFAULT_RPC_API_BUDGET_S):
            shed = self._shed_probe("prepare") if self._shed_probe else None
            if shed is not None:
                for ref in request.claims:
                    resp.claims[ref.uid].error = shed
                return resp
            full_claims = []
            # A resolve span only for multi-claim batches: a single
            # cached-hit resolution is cheaper than its span, and its cost
            # is visible anyway as the gap before the plugin.prepare
            # child (the ≤5% overhead budget, bench --trace-ab).
            if len(request.claims) > 1:
                with trace.start_span(
                    "bind.resolve", attrs={"claims": len(request.claims)}
                ):
                    resolved = self._resolve_all(request.claims)
            else:
                resolved = self._resolve_all(request.claims)
            for ref, claim, err in resolved:
                if err is not None:
                    resp.claims[ref.uid].error = (
                        f"resolve claim {ref.namespace}/{ref.name}: {err}"
                    )
                else:
                    full_claims.append(claim)
            if full_claims:
                result = self._prepare(full_claims)
                for uid, entry in result.get("claims", {}).items():
                    if entry.get("error"):
                        resp.claims[uid].error = entry["error"]
                        continue
                    out = resp.claims[uid]
                    for d in entry.get("devices", []):
                        out.devices.add(
                            request_names=d.get("requestNames", []),
                            pool_name=d.get("poolName", ""),
                            device_name=d.get("deviceName", ""),
                            cdi_device_ids=d.get("cdiDeviceIDs", []),
                        )
        return resp

    def _node_unprepare(self, request, context, pb):
        refs = [
            {"uid": c.uid, "namespace": c.namespace, "name": c.name}
            for c in request.claims
        ]
        # Same ambient apiserver budget as prepare (see _node_prepare).
        with trace.start_span(
            "rpc.NodeUnprepareResources",
            parent=_metadata_traceparent(context),
            attrs={"claims": len(refs)},
        ), api_deadline(DEFAULT_RPC_API_BUDGET_S):
            shed = self._shed_probe("unprepare") if self._shed_probe else None
            if shed is not None:
                resp = pb.NodeUnprepareResourcesResponse()
                for ref in refs:
                    resp.claims[ref["uid"]].error = shed
                return resp
            result = self._unprepare(refs)
        resp = pb.NodeUnprepareResourcesResponse()
        for uid, entry in result.get("claims", {}).items():
            resp.claims[uid].error = entry.get("error", "")
        return resp

    def _dra_handlers(self, service_name: str, pb) -> grpc.GenericRpcHandler:
        return grpc.method_handlers_generic_handler(
            service_name,
            {
                "NodePrepareResources": _unary(
                    lambda req, ctx: self._node_prepare(req, ctx, pb),
                    pb.NodePrepareResourcesRequest.FromString,
                    pb.NodePrepareResourcesResponse,
                ),
                "NodeUnprepareResources": _unary(
                    lambda req, ctx: self._node_unprepare(req, ctx, pb),
                    pb.NodeUnprepareResourcesRequest.FromString,
                    pb.NodeUnprepareResourcesResponse,
                ),
            },
        )

    # ---------------------------------------------------------- registration

    def supported_services(self) -> list[str]:
        services = list(SUPPORTED_SERVICES)
        if self.health_broadcaster is not None:
            from tpudra.plugin.healthservice import HEALTH_SERVICE

            services.append(HEALTH_SERVICE)
        return services

    def _get_info(self, request, context):
        return regpb.PluginInfo(
            type=DRA_PLUGIN_TYPE,
            name=self.driver_name,
            endpoint=os.path.abspath(self.dra_socket_path),
            supported_versions=self.supported_services(),
        )

    def _notify(self, request, context):
        if request.plugin_registered:
            logger.info("kubelet acknowledged registration of %s", self.driver_name)
            self._registered.set()
        else:
            logger.error(
                "kubelet rejected registration of %s: %s",
                self.driver_name,
                request.error,
            )
        return regpb.RegistrationStatusResponse()

    @property
    def registered(self) -> bool:
        return self._registered.is_set()

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        # DRA service first so the endpoint is live before kubelet can
        # discover the registration socket (draplugin.go:640 ordering).
        dra_handlers = [
            self._dra_handlers(_V1_SERVICE, drapb),
            self._dra_handlers(_V1BETA1_SERVICE, drapb_beta),
        ]
        if self.health_broadcaster is not None:
            dra_handlers.append(self.health_broadcaster.handler())
        self._dra_server = _serve(self.dra_socket_path, tuple(dra_handlers))
        self._reg_server = _serve(
            self.registration_socket_path,
            (
                grpc.method_handlers_generic_handler(
                    _REG_SERVICE,
                    {
                        "GetInfo": _unary(
                            self._get_info, regpb.InfoRequest.FromString, regpb.PluginInfo
                        ),
                        "NotifyRegistrationStatus": _unary(
                            self._notify,
                            regpb.RegistrationStatus.FromString,
                            regpb.RegistrationStatusResponse,
                        ),
                    },
                ),
            ),
        )

    def stop(self) -> None:
        if self.health_broadcaster is not None:
            # Unblock stream threads waiting on the broadcaster condition so
            # the grace period below doesn't have to kill them.
            self.health_broadcaster.stop()
        for server in (self._reg_server, self._dra_server):
            if server is not None:
                server.stop(grace=1.0).wait()
        # After the grace drain: an in-flight RPC may still be resolving
        # claims, and a shut-down executor would fail it mid-grace.
        self._resolver_pool.shutdown(wait=False)
        for path in (self.registration_socket_path, self.dra_socket_path):
            if os.path.exists(path):
                os.unlink(path)


# ---------------------------------------------------------------------------
# Clients (tests, health self-probe, bench — the "kubelet side")
# ---------------------------------------------------------------------------


class DRAClient:
    """Speaks the DRA gRPC service the way kubelet does: claim references on
    the wire, v1 by default (``service="v1beta1"`` exercises the legacy
    service a ≤1.33 kubelet would pick)."""

    def __init__(self, path: str, timeout: float = 30.0, service: str = "v1"):
        self._pb = {"v1": drapb, "v1beta1": drapb_beta}[service]
        self._prefix = {"v1": _V1_SERVICE, "v1beta1": _V1BETA1_SERVICE}[service]
        self._timeout = timeout
        self._channel = grpc.insecure_channel(_unix_addr(path))

    def _call(self, method: str, request, resp_cls):
        rpc = self._channel.unary_unary(
            f"/{self._prefix}/{method}",
            request_serializer=type(request).SerializeToString,
            response_deserializer=resp_cls.FromString,
        )
        # Trace propagation across the kubelet boundary: the active span
        # (if any) rides gRPC metadata; the server handlers adopt it as
        # the RPC span's parent (tpudra/trace.py).
        traceparent = trace.current_traceparent()
        metadata = (
            ((trace.GRPC_METADATA_KEY, traceparent),) if traceparent else None
        )
        try:
            return rpc(request, timeout=self._timeout, metadata=metadata)
        except grpc.RpcError as e:
            raise RPCError(f"{method}: {e.code().name}: {e.details()}") from e

    @staticmethod
    def _refs(claims: list[dict]) -> list[dict]:
        out = []
        for c in claims:
            meta = c.get("metadata", c)
            out.append(
                {
                    "namespace": meta.get("namespace", ""),
                    "uid": meta.get("uid", ""),
                    "name": meta.get("name", ""),
                }
            )
        return out

    def prepare(self, claims: list[dict]) -> dict:
        """claims may be full ResourceClaim dicts or bare refs; only the
        reference triple goes on the wire."""
        pb = self._pb
        req = pb.NodePrepareResourcesRequest(
            claims=[pb.Claim(**r) for r in self._refs(claims)]
        )
        resp = self._call("NodePrepareResources", req, pb.NodePrepareResourcesResponse)
        out: dict[str, dict] = {}
        for uid, entry in resp.claims.items():
            if entry.error:
                out[uid] = {"error": entry.error}
            else:
                out[uid] = {
                    "devices": [
                        {
                            "requestNames": list(d.request_names),
                            "poolName": d.pool_name,
                            "deviceName": d.device_name,
                            "cdiDeviceIDs": list(d.cdi_device_ids),
                        }
                        for d in entry.devices
                    ]
                }
        return {"claims": out}

    def unprepare(self, claims: list[dict]) -> dict:
        pb = self._pb
        req = pb.NodeUnprepareResourcesRequest(
            claims=[pb.Claim(**r) for r in self._refs(claims)]
        )
        resp = self._call(
            "NodeUnprepareResources", req, pb.NodeUnprepareResourcesResponse
        )
        return {
            "claims": {
                uid: ({"error": e.error} if e.error else {})
                for uid, e in resp.claims.items()
            }
        }

    def close(self) -> None:
        self._channel.close()


class RegistrationClient:
    """The pluginwatcher side of the registration handshake."""

    def __init__(self, path: str, timeout: float = 10.0):
        self._timeout = timeout
        self._channel = grpc.insecure_channel(_unix_addr(path))

    def get_info(self) -> dict:
        rpc = self._channel.unary_unary(
            f"/{_REG_SERVICE}/GetInfo",
            request_serializer=regpb.InfoRequest.SerializeToString,
            response_deserializer=regpb.PluginInfo.FromString,
        )
        try:
            info = rpc(regpb.InfoRequest(), timeout=self._timeout)
        except grpc.RpcError as e:
            raise RPCError(f"GetInfo: {e.code().name}: {e.details()}") from e
        return {
            "type": info.type,
            "name": info.name,
            "endpoint": info.endpoint,
            "supportedVersions": list(info.supported_versions),
        }

    def notify(self, registered: bool, error: str = "") -> None:
        rpc = self._channel.unary_unary(
            f"/{_REG_SERVICE}/NotifyRegistrationStatus",
            request_serializer=regpb.RegistrationStatus.SerializeToString,
            response_deserializer=regpb.RegistrationStatusResponse.FromString,
        )
        try:
            rpc(
                regpb.RegistrationStatus(plugin_registered=registered, error=error),
                timeout=self._timeout,
            )
        except grpc.RpcError as e:
            raise RPCError(f"Notify: {e.code().name}: {e.details()}") from e

    def close(self) -> None:
        self._channel.close()

"""Native device-library backend: ctypes over C++ libtpuinfo.

The analog of the reference's cgo→NVML boundary (nvlib.go:56-71 loading
libnvidia-ml.so.1 by explicit path).  All enumeration, topology and the
partition registry live in native/tpuinfo (built to
native/build/libtpuinfo.so); this binding adapts the C ABI to the DeviceLib
interface so the plugins run identically on mock and native backends.

Health events: the native library exposes hardware interrupts by appending
lines ``<kind> <chip_uuid> [partition_uuid] [detail...]`` to an event file
(on real hosts, a fifo fed by the platform's interrupt handler; in tests, a
plain file) which this backend tails.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Iterator

from tpudra import walwitness
from tpudra.devicelib.base import (
    DeviceLib,
    DeviceLibError,
    HealthEvent,
    LivePartition,
    PartitionSpec,
)
from tpudra.devicelib.topology import (
    GENERATIONS,
    PartitionPlacement,
    SliceTopology,
    TpuChip,
    partition_profiles,
)

# Resolution order: explicit env (the container image sets it), then the
# in-repo build product (dev checkouts), then the system install location
# (the dlopen-by-known-path pattern of reference nvlib.go:69-71).
def _default_lib_path() -> str:
    env = os.environ.get("TPUINFO_LIBRARY_PATH")
    if env:
        return env
    repo_build = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
        "native",
        "build",
        "libtpuinfo.so",
    )
    if os.path.exists(repo_build):
        return repo_build
    return "/usr/local/lib/libtpuinfo.so"


DEFAULT_LIB_PATH = _default_lib_path()


class _Chip(ctypes.Structure):
    _fields_ = [
        ("index", ctypes.c_int),
        ("uuid", ctypes.c_char * 64),
        ("generation", ctypes.c_char * 8),
        ("coords", ctypes.c_int * 3),
        ("pci_address", ctypes.c_char * 24),
        ("clique_id", ctypes.c_char * 96),
        ("hbm_bytes", ctypes.c_longlong),
        ("tensorcores", ctypes.c_int),
    ]


class _Partition(ctypes.Structure):
    _fields_ = [
        ("parent_index", ctypes.c_int),
        ("profile", ctypes.c_char * 16),
        ("core_start", ctypes.c_int),
        ("hbm_start", ctypes.c_int),
        ("uuid", ctypes.c_char * 64),
    ]


class _Topology(ctypes.Structure):
    _fields_ = [
        ("slice_uuid", ctypes.c_char * 64),
        ("mesh", ctypes.c_int * 3),
        ("host_index", ctypes.c_int),
        ("num_hosts", ctypes.c_int),
    ]


def _load(lib_path: str):
    lib = ctypes.CDLL(lib_path)
    lib.tpuinfo_open.argtypes = [ctypes.c_char_p, ctypes.POINTER(ctypes.c_void_p)]
    lib.tpuinfo_open.restype = ctypes.c_int
    lib.tpuinfo_close.argtypes = [ctypes.c_void_p]
    lib.tpuinfo_chip_count.argtypes = [ctypes.c_void_p]
    lib.tpuinfo_chip_count.restype = ctypes.c_int
    lib.tpuinfo_get_chip.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(_Chip)]
    lib.tpuinfo_get_chip.restype = ctypes.c_int
    lib.tpuinfo_get_topology.argtypes = [ctypes.c_void_p, ctypes.POINTER(_Topology)]
    lib.tpuinfo_get_topology.restype = ctypes.c_int
    lib.tpuinfo_create_partition.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p,
        ctypes.c_int, ctypes.c_int, ctypes.POINTER(_Partition),
    ]
    lib.tpuinfo_create_partition.restype = ctypes.c_int
    lib.tpuinfo_delete_partition.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.tpuinfo_delete_partition.restype = ctypes.c_int
    lib.tpuinfo_list_partitions.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(_Partition), ctypes.c_int,
    ]
    lib.tpuinfo_list_partitions.restype = ctypes.c_int
    lib.tpuinfo_last_error.argtypes = [ctypes.c_void_p]
    lib.tpuinfo_last_error.restype = ctypes.c_char_p
    lib.tpuinfo_partitions_supported.argtypes = [ctypes.c_void_p]
    lib.tpuinfo_partitions_supported.restype = ctypes.c_int
    lib.tpuinfo_multiprocess_mode.argtypes = [ctypes.c_void_p]
    lib.tpuinfo_multiprocess_mode.restype = ctypes.c_int
    return lib


class NativeDeviceLib(DeviceLib):
    def __init__(
        self,
        config_path: str = "",
        lib_path: str = DEFAULT_LIB_PATH,
        health_events_path: str = "",
        runtime_probe=None,
    ):
        if not os.path.exists(lib_path):
            raise DeviceLibError(
                f"libtpuinfo not found at {lib_path} (build with `make -C native`)"
            )
        self._lib = _load(lib_path)
        self._handle = ctypes.c_void_p()
        self._mp_mode: str | None = None  # probe-once cache (multiprocess_mode)
        rc = self._lib.tpuinfo_open(
            config_path.encode() or None, ctypes.byref(self._handle)
        )
        if rc != 0:
            err = self._error()
            self._lib.tpuinfo_close(self._handle)
            self._handle = None
            raise DeviceLibError(f"tpuinfo_open: {err}")
        self._health_events_path = health_events_path or os.environ.get(
            "TPUINFO_HEALTH_EVENTS", ""
        )
        # Live-runtime corroboration (runtimeprobe.py): when a probe is
        # provided — or TPUINFO_RUNTIME_PROBE=1 asks for one at open — the
        # runtime's attested chip coordinates replace the spec-table guess
        # and corroborate_runtime() can diff the two views.  Opt-in: the
        # probe subprocess briefly touches the TPU runtime, which a
        # production kubelet plugin must not do unasked.
        self._runtime_probe = runtime_probe
        if self._runtime_probe is None and os.environ.get(
            "TPUINFO_RUNTIME_PROBE"
        ) == "1":
            from tpudra.devicelib.runtimeprobe import probe_runtime

            self._runtime_probe = probe_runtime()
        self._sharing_lock = threading.Lock()
        self._timeslice: dict[str, str] = {}
        self._exclusive: dict[str, bool] = {}

    def _error(self) -> str:
        return (self._lib.tpuinfo_last_error(self._handle) or b"").decode()

    # -- enumeration --------------------------------------------------------

    def enumerate_chips(self) -> list[TpuChip]:
        n = self._lib.tpuinfo_chip_count(self._handle)
        out = []
        for i in range(n):
            c = _Chip()
            if self._lib.tpuinfo_get_chip(self._handle, i, ctypes.byref(c)) != 0:
                raise DeviceLibError(self._error())
            out.append(
                TpuChip(
                    index=c.index,
                    uuid=c.uuid.decode(),
                    generation=c.generation.decode(),
                    coords=tuple(c.coords),
                    pci_address=c.pci_address.decode(),
                    clique_id=c.clique_id.decode(),
                    hbm_bytes=c.hbm_bytes,
                    tensorcores=c.tensorcores,
                )
            )
        if self._runtime_probe is not None:
            from tpudra.devicelib.runtimeprobe import apply_to_chips

            out = apply_to_chips(out, self._runtime_probe)
        return out

    def corroborate_runtime(self) -> dict:
        """Diff this library's enumeration against the live TPU runtime
        (the NVML-is-truth gap of reference nvlib.go closed from the other
        side).  {"available": False} when no runtime is reachable.

        Compares the library's RAW view — the overlay a held probe applies
        in enumerate_chips is suppressed for the diff, otherwise the check
        would compare the probe against itself and a wrong spec-table
        placement could never surface."""
        from tpudra.devicelib.runtimeprobe import corroborate, probe_runtime

        probe = self._runtime_probe or probe_runtime()
        saved, self._runtime_probe = self._runtime_probe, None
        try:
            chips = self.enumerate_chips()
            topo = self.slice_topology()
        finally:
            self._runtime_probe = saved
        return corroborate(chips, topo, probe)

    def slice_topology(self) -> SliceTopology:
        t = _Topology()
        if self._lib.tpuinfo_get_topology(self._handle, ctypes.byref(t)) != 0:
            raise DeviceLibError(self._error())
        slice_uuid = t.slice_uuid.decode()
        chips = self.enumerate_chips()
        partition_id = (
            chips[0].clique_id.split(".", 1)[1] if chips and "." in chips[0].clique_id else "0"
        )
        return SliceTopology(
            slice_uuid=slice_uuid,
            partition_id=partition_id,
            mesh_shape=tuple(t.mesh),
            host_index=t.host_index,
            num_hosts=t.num_hosts,
        )

    # -- partitions ---------------------------------------------------------

    def partitions_supported(self) -> bool:
        """The library's per-handle attestation (tpuinfo.h): config-file
        handles with a state_file say yes (hermetic sim); hardware handles
        say no unless TPUINFO_SIMULATE_PARTITIONS=1 opted into file-backed
        simulation — no public TPU runtime API mutates sub-chip
        partitions."""
        return bool(self._lib.tpuinfo_partitions_supported(self._handle))

    def multiprocess_mode(self) -> str:
        """Fork/double-open probe of the first granted /dev/accelN
        (tpuinfo_multiprocess_mode, tpuinfo.h); "unknown" when there is no
        node to probe (config mode, remote tunnel).  Probed once per
        handle — the first call runs at DeviceState init, before any
        workload holds the chip; re-probing on every MP claim would both
        flap the published value with chip occupancy and briefly hold the
        node O_RDWR on the prepare hot path."""
        if self._mp_mode is None:
            mode = self._lib.tpuinfo_multiprocess_mode(self._handle)
            self._mp_mode = {1: "exclusive", 2: "concurrent"}.get(mode, "unknown")
        return self._mp_mode

    def possible_placements(self, chip: TpuChip) -> list[PartitionPlacement]:
        spec = GENERATIONS[chip.generation]
        out = []
        for profile in partition_profiles(spec):
            out.extend(profile.placements(spec))
        return out

    def create_partition(self, spec: PartitionSpec) -> LivePartition:
        walwitness.note_effect("partition:create")
        p = _Partition()
        rc = self._lib.tpuinfo_create_partition(
            self._handle,
            spec.parent_index,
            spec.profile.encode(),
            spec.core_start,
            spec.hbm_start,
            ctypes.byref(p),
        )
        if rc != 0:
            raise DeviceLibError(f"create_partition: {self._error()}")
        chips = {c.index: c for c in self.enumerate_chips()}
        parent = chips[spec.parent_index]
        return LivePartition(
            spec=spec,
            uuid=p.uuid.decode(),
            parent_uuid=parent.uuid,
            dev_paths=parent.dev_paths(),
        )

    def delete_partition(self, uuid: str) -> None:
        walwitness.note_effect("partition:destroy")
        if self._lib.tpuinfo_delete_partition(self._handle, uuid.encode()) != 0:
            raise DeviceLibError(f"delete_partition: {self._error()}")

    def list_partitions(self) -> list[LivePartition]:
        cap = 256
        while True:
            arr = (_Partition * cap)()
            n = self._lib.tpuinfo_list_partitions(self._handle, arr, cap)
            if n < 0:
                raise DeviceLibError(f"list_partitions: {self._error()}")
            if n <= cap:
                break
            cap = n
        chips = {c.index: c for c in self.enumerate_chips()}
        out = []
        for i in range(n):
            p = arr[i]
            parent = chips[p.parent_index]
            out.append(
                LivePartition(
                    spec=PartitionSpec(
                        parent_index=p.parent_index,
                        profile=p.profile.decode(),
                        core_start=p.core_start,
                        hbm_start=p.hbm_start,
                    ),
                    uuid=p.uuid.decode(),
                    parent_uuid=parent.uuid,
                    dev_paths=parent.dev_paths(),
                )
            )
        return out

    # -- sharing knobs ------------------------------------------------------

    def set_timeslice(self, chip_uuids: list[str], interval: str) -> None:
        walwitness.note_effect("timeslice:set")
        with self._sharing_lock:
            for u in chip_uuids:
                self._timeslice[u] = interval

    def set_exclusive(self, chip_uuids: list[str], exclusive: bool) -> None:
        with self._sharing_lock:
            for u in chip_uuids:
                self._exclusive[u] = exclusive

    # -- health -------------------------------------------------------------

    # Kernel-log patterns → HealthEventKind: on TPU hosts, hardware faults
    # surface as accel-driver lines in the kernel ring buffer — the same
    # channel NVIDIA XIDs use ("NVRM: Xid" in dmesg; the reference reads
    # them via NVML events instead, device_health.go:38).  Matched against
    # the record's message, case-insensitively, FIRST MATCH WINS — keep the
    # specific fabric/thermal/firmware patterns ahead of the broad ECC one,
    # or an "uncorrectable ICI link" fault would classify as HbmEccError and
    # escape DEFAULT_IGNORED (IciLinkDown degrades the fabric but the chip
    # still computes, base.py:63-67).
    KMSG_PATTERNS: list[tuple[str, str]] = [
        (r"ici.*link|link.*down", "IciLinkDown"),
        (r"thermal|overtemp", "ThermalTrip"),
        (r"firmware (fault|crash|error)", "FirmwareFault"),
        (r"lockup|wedged|watchdog timeout", "ChipLockup"),
        (r"uncorrectable|ecc error", "HbmEccError"),
    ]

    @staticmethod
    def _tail_lines(path: str, stop: threading.Event, from_end: bool) -> Iterator[str]:
        """Yield decoded lines appended to *path* until *stop*.

        One loop for all three shapes the health sources take:

        - plain file: byte tail (``from_end=False`` starts at offset 0);
        - fifo: non-blocking open so a missing writer never wedges the
          monitor thread; EOF just means the writer went away — keep
          polling the same fd;
        - /dev/kmsg: record-oriented non-blocking reads (EAGAIN when
          drained); ``from_end=True`` seeks past boot history so stale
          faults from before this process don't poison the allocatable
          set; EPIPE signals a ring-buffer overrun and reading again on
          the SAME fd continues from the oldest surviving record —
          reopening would seek to the end and silently drop buffered
          faults.
        """
        while not stop.is_set():
            try:
                fd = os.open(path, os.O_RDONLY | os.O_NONBLOCK)
            except OSError:
                if stop.wait(1.0):
                    return
                continue
            try:
                if from_end:
                    try:
                        os.lseek(fd, 0, os.SEEK_END)
                    except OSError:
                        pass  # unseekable (fifo): tail from here anyway
                buf = b""
                while not stop.is_set():
                    try:
                        chunk = os.read(fd, 8192)
                    except BlockingIOError:
                        chunk = b""
                    except BrokenPipeError:
                        continue  # kmsg overrun: next read resumes at oldest record
                    except OSError:
                        break  # fd went bad; reopen
                    if not chunk:
                        # EOF on a plain file / writerless fifo: new appends
                        # (or a new writer) show up on the same fd.
                        if stop.wait(0.2):
                            return
                        continue
                    buf += chunk
                    while b"\n" in buf:
                        line, buf = buf.split(b"\n", 1)
                        yield line.decode(errors="replace")
            finally:
                os.close(fd)
            if stop.wait(0.5):
                return

    def health_events(self, stop: threading.Event) -> Iterator[HealthEvent]:
        path = self._health_events_path
        if path:
            # Explicit event file/fifo, one HealthEvent.to_line() per line
            # (the shared wire form — writers and this parser cannot drift).
            for line in self._tail_lines(path, stop, from_end=False):
                event = HealthEvent.from_line(line)
                if event is not None:
                    yield event
            return
        # No explicit source: scan the kernel log for accel driver faults
        # (the real interrupt surface on TPU VM hosts).
        kmsg = os.environ.get("TPUINFO_KMSG_PATH", "/dev/kmsg")
        if not os.path.exists(kmsg):
            stop.wait()
            return
        import re

        patterns = [(re.compile(rx, re.I), kind) for rx, kind in self.KMSG_PATTERNS]
        accel_rx = re.compile(r"accel\s*(?:accel)?(\d+)")
        uuid_by_index = {c.index: c.uuid for c in self.enumerate_chips()}
        for line in self._tail_lines(kmsg, stop, from_end=True):
            # Strip the "prio,seq,ts,flags;" record prefix if present.
            message = line.split(";", 1)[1] if ";" in line else line
            m = accel_rx.search(message)
            if m is None:
                continue
            uuid = uuid_by_index.get(int(m.group(1)))
            if uuid is None:
                continue
            for rx, kind in patterns:
                if rx.search(message):
                    yield HealthEvent(
                        kind=kind,
                        chip_uuid=uuid,
                        partition_uuid=None,
                        detail=message.strip(),
                    )
                    break

    # -- lifecycle ----------------------------------------------------------

    def close(self) -> None:
        if self._handle:
            self._lib.tpuinfo_close(self._handle)
            self._handle = None

from tpudra.devicelib.base import (
    DeviceLib,
    DeviceLibError,
    HealthEvent,
    HealthEventKind,
    LivePartition,
    PartitionSpec,
    make_device_lib,
)
from tpudra.devicelib.topology import (
    GENERATIONS,
    HBM_SLICES_PER_CHIP,
    MockTopologyConfig,
    PartitionProfile,
    SliceTopology,
    TpuChip,
    partition_profiles,
)

__all__ = [
    "DeviceLib",
    "DeviceLibError",
    "HealthEvent",
    "HealthEventKind",
    "LivePartition",
    "PartitionSpec",
    "make_device_lib",
    "GENERATIONS",
    "HBM_SLICES_PER_CHIP",
    "MockTopologyConfig",
    "PartitionProfile",
    "SliceTopology",
    "TpuChip",
    "partition_profiles",
]

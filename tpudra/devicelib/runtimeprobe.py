"""Live TPU-runtime corroboration for the native device library.

The reference's device library IS hardware truth — NVML loaded by path
answers from silicon (nvlib.go:69-71).  Our C++ libtpuinfo answers from
sysfs PCI ids, the Cloud TPU VM metadata env, and a per-generation spec
table — so whenever a real TPU runtime is reachable, we cross-examine the
two: a short-lived subprocess asks the runtime (jax/libtpu) what hardware
it sees, and ``corroborate`` diffs that against what ``NativeDeviceLib``
enumerates.  The probe is a subprocess on purpose: importing jax in the
kubelet-plugin process would acquire the TPU runtime and starve the very
workloads the driver exists to admit; a probe process exits immediately
and releases it.

The probe result can also *upgrade* enumeration: runtime-attested chip
coordinates replace the spec-table guess (``apply_to_chips``), with the
table remaining the fallback when no runtime is present (exactly the
strict/legacy duality of the clique-id path).
"""

from __future__ import annotations

import json
import logging
import os
import subprocess
import sys
from dataclasses import dataclass, field
from typing import Optional

logger = logging.getLogger(__name__)

# jax Device.device_kind → our generation keys (devicelib/topology.py
# GENERATIONS).  Substring match on the lowercased kind.
_KIND_TO_GENERATION = [
    ("v5 lite", "v5e"),
    ("v5litepod", "v5e"),
    ("v5e", "v5e"),
    ("v5p", "v5p"),
    ("v6 lite", "v6e"),
    ("v6e", "v6e"),
    ("trillium", "v6e"),
    ("v4", "v4"),
    ("v3", "v3"),
    ("v2", "v2"),
]

_PROBE_CODE = r"""
import json, sys
import jax

devs = jax.local_devices()
out = {
    "platform": devs[0].platform if devs else "",
    "device_kind": devs[0].device_kind if devs else "",
    "num_devices": len(devs),
    "coords": [list(getattr(d, "coords", ()) or ()) for d in devs],
    "cores_on_chip": sorted({getattr(d, "core_on_chip", 0) for d in devs}),
    "process_index": jax.process_index(),
    "process_count": jax.process_count(),
    "hbm_bytes_limit": (devs[0].memory_stats() or {}).get("bytes_limit", 0)
    if devs
    else 0,
}
print("TPUPROBE " + json.dumps(out))
"""


@dataclass
class RuntimeProbe:
    platform: str = ""
    device_kind: str = ""
    num_devices: int = 0
    coords: list = field(default_factory=list)
    cores_on_chip: list = field(default_factory=list)
    process_index: int = 0
    process_count: int = 1
    hbm_bytes_limit: int = 0

    @property
    def generation(self) -> str:
        kind = self.device_kind.lower()
        for key, gen in _KIND_TO_GENERATION:
            if key in kind:
                return gen
        return ""


def hardware_env(base: Optional[dict] = None) -> dict:
    """A copy of the environment with test-harness CPU pinning removed.

    Under pytest, tests/conftest.py exports JAX_PLATFORMS=cpu and the
    virtual-device XLA flag into os.environ; a child meant to see REAL
    hardware (the runtime probe, bench's claim→jax workload) must not
    inherit them — on a plain TPU VM they would pin the child to CPU and
    the hardware gate would silently skip.  Only the cpu pin is dropped
    (an operator's explicit JAX_PLATFORMS=tpu survives)."""
    env = dict(os.environ if base is None else base)
    if env.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        env.pop("JAX_PLATFORMS")
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" in flags:
        kept = " ".join(
            t for t in flags.split()
            if "xla_force_host_platform_device_count" not in t
        )
        if kept:
            env["XLA_FLAGS"] = kept
        else:
            env.pop("XLA_FLAGS")
    return env


def probe_runtime(timeout: float = 180.0, env: Optional[dict] = None) -> Optional[RuntimeProbe]:
    """Ask the live TPU runtime what it sees; None when there is none.

    Runs in a fresh interpreter with the ambient environment minus any
    test-harness CPU pinning (``hardware_env``) — on Cloud TPU VMs and
    under the remote-execution tunnel the ambient env is what pins jax to
    the TPU.  An explicit ``env`` is used verbatim.  Any failure — no jax,
    no TPU, CPU-only platform — is a clean None, never an exception.
    """
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _PROBE_CODE],
            capture_output=True,
            text=True,
            timeout=timeout,
            env=hardware_env() if env is None else dict(env),
        )
    except (OSError, subprocess.TimeoutExpired) as e:
        logger.debug("runtime probe failed to run: %s", e)
        return None
    for line in reversed(proc.stdout.splitlines()):
        if line.startswith("TPUPROBE "):
            try:
                data = json.loads(line[len("TPUPROBE "):])
            except ValueError:
                return None
            probe = RuntimeProbe(**data)
            if probe.platform != "tpu":
                logger.debug("runtime probe: platform %r, not tpu", probe.platform)
                return None
            return probe
    return None


def apply_to_chips(chips: list, probe: RuntimeProbe) -> list:
    """Overlay runtime-attested coordinates onto enumerated chips.

    The spec table can only guess coords from the accelerator-type mesh
    (tpuinfo.cc generation table); the runtime knows where each chip
    actually sits.  Only applied when the runtime sees the same chip count
    — a probe from inside a partitioned/shared host must not relabel chips
    it cannot see.
    """
    if len(probe.coords) != len(chips):
        return chips
    from dataclasses import replace

    out = []
    for chip, xyz in zip(chips, probe.coords):
        if len(xyz) == 3 and tuple(xyz) != chip.coords:
            chip = replace(chip, coords=tuple(xyz))
        out.append(chip)
    return out


def corroborate(chips: list, topo, probe: Optional[RuntimeProbe]) -> dict:
    """Diff the native library's enumeration against the live runtime.

    Returns a dict suitable for a bench artifact / test assertion:
    per-attribute match booleans plus both sides' raw values, and
    ``consistent`` = everything comparable matched.
    """
    if probe is None:
        return {"available": False, "reason": "no live TPU runtime"}
    lib_gens = sorted({c.generation for c in chips})
    gen_match = lib_gens == [probe.generation] if probe.generation else None
    lib_coords = [list(c.coords) for c in chips]
    probe_coords = [list(c) for c in probe.coords if len(c) == 3]
    # The runtime may legitimately address a SUBSET of the host's chips
    # (TPU_VISIBLE_DEVICES, a partitioned grant, or a remote-execution
    # tunnel exposing one chip of an attested slice).  A subset is
    # corroboration, not contradiction — the library advertising chips the
    # runtime cannot see is exactly the plugin's job; the failure mode to
    # catch is the runtime seeing chips the library does NOT enumerate.
    subset = 0 < probe.num_devices < len(chips) and (
        not probe_coords
        or all(c in lib_coords for c in probe_coords)
    )
    if probe.num_devices <= 0:
        count_match = None  # a probe that saw no devices compares nothing
    else:
        count_match = True if subset else len(chips) == probe.num_devices
    if probe_coords:
        coords_match = (
            all(c in lib_coords for c in probe_coords)
            if subset
            else lib_coords == probe_coords
        )
    else:
        coords_match = None
    hbm_match = None
    if probe.hbm_bytes_limit and chips:
        # The runtime's bytes_limit is usable HBM after runtime reservation;
        # the spec table records physical capacity.  35% covers every
        # published reservation without passing a wrong generation (the
        # next generation differs by >= 2x).
        lib_hbm = chips[0].hbm_bytes
        hbm_match = abs(lib_hbm - probe.hbm_bytes_limit) / lib_hbm <= 0.35
    comparisons = {
        "generation": gen_match,
        "chip_count": count_match,
        "coords": coords_match,
        "hbm": hbm_match,
    }
    checked = sum(1 for v in comparisons.values() if v is not None)
    return {
        "available": True,
        # A probe with nothing comparable (no generation, no coords, no
        # HBM figure) must read as "unverified", not "corroborated":
        # consistent is None when zero comparisons actually ran, and
        # checked_count lets artifact readers see how much evidence backs
        # a True.
        "consistent": (
            all(v for v in comparisons.values() if v is not None)
            if checked
            else None
        ),
        "checked_count": checked,
        "runtime_sees_subset": subset,
        "match": comparisons,
        "lib": {
            "generations": lib_gens,
            "chip_count": len(chips),
            "coords": lib_coords,
            "hbm_bytes": chips[0].hbm_bytes if chips else 0,
            "num_hosts": topo.num_hosts if topo else None,
        },
        "runtime": {
            "device_kind": probe.device_kind,
            "generation": probe.generation,
            "num_devices": probe.num_devices,
            "coords": probe.coords,
            "hbm_bytes_limit": probe.hbm_bytes_limit,
            "process_count": probe.process_count,
        },
    }

"""TPU chip, host, and slice topology model.

The TPU-native replacement for the reference's NVML device model
(cmd/gpu-kubelet-plugin/nvlib.go:428-746).  Where a GPU is identified by UUID +
PCI bus ID, a TPU chip is additionally a *point in an ICI mesh*: its (x, y, z)
coordinates inside the slice determine which collectives ride ICI versus DCN,
so they are first-class device attributes (the analog of NVML fabric info's
clusterUUID/cliqueID, reference compute-domain-kubelet-plugin/nvlib.go:201-356).

Generations follow public Cloud TPU system architecture: chips per host, cores
per chip, HBM, and whether a chip's TensorCores can be partitioned and used as
independent accelerators (the MIG analog; v4/v5p have 2 TensorCores per chip,
v5e/v6e have 1 fused core).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Optional


@dataclass(frozen=True)
class TpuGenerationSpec:
    name: str  # "v4", "v5e", "v5p", "v6e"
    tensorcores_per_chip: int
    hbm_bytes: int
    chips_per_host: int
    # Default host footprint within the ICI mesh, x,y,z (v5p host owns a
    # 2x2x1 block; v5e host owns 2x4 of a 2D mesh).
    host_bounds: tuple[int, int, int]
    peak_bf16_tflops: float
    partitionable: bool  # can TensorCores be split into separate partitions


GENERATIONS: dict[str, TpuGenerationSpec] = {
    "v4": TpuGenerationSpec("v4", 2, 32 * 2**30, 4, (2, 2, 1), 275.0, True),
    "v5e": TpuGenerationSpec("v5e", 1, 16 * 2**30, 8, (2, 4, 1), 197.0, False),
    "v5p": TpuGenerationSpec("v5p", 2, 95 * 2**30, 4, (2, 2, 1), 459.0, True),
    "v6e": TpuGenerationSpec("v6e", 1, 32 * 2**30, 8, (2, 4, 1), 918.0, False),
}

# HBM is modeled in fixed slices for partition accounting (the analog of MIG
# memory slices): each chip's HBM divides into this many equal slices.
HBM_SLICES_PER_CHIP = 8


@dataclass(frozen=True)
class PartitionProfile:
    """A supported TensorCore partition shape (the MIG-profile analog).

    name examples (v5p): "1c.4hbm" = 1 TensorCore + 4/8 of HBM,
    "1c.8hbm" = 1 core with all HBM, "2c.8hbm" = whole chip as a partition.
    """

    tensorcores: int
    hbm_slices: int

    @property
    def name(self) -> str:
        return f"{self.tensorcores}c.{self.hbm_slices}hbm"

    def placements(self, spec: TpuGenerationSpec) -> list["PartitionPlacement"]:
        """All placements of this profile on one chip: core_start advances by
        the core count, hbm_start by the HBM-slice count (MIG placement
        analog, reference nvlib.go:1129-1209)."""
        out = []
        if self.tensorcores > spec.tensorcores_per_chip:
            return out
        if self.hbm_slices > HBM_SLICES_PER_CHIP:
            return out
        core_starts = range(0, spec.tensorcores_per_chip - self.tensorcores + 1, self.tensorcores)
        hbm_starts = range(0, HBM_SLICES_PER_CHIP - self.hbm_slices + 1, self.hbm_slices)
        # Placement = aligned (core block, hbm block) pairs; we pair the i-th
        # core block with the proportionally aligned HBM block to keep the
        # partition NUMA-local to its core's HBM stacks.
        for ci, cs in enumerate(core_starts):
            for hi, hs in enumerate(hbm_starts):
                if len(core_starts) > 1 and len(hbm_starts) > 1:
                    # Align: core block i owns HBM region i's slices only.
                    per_core = HBM_SLICES_PER_CHIP // spec.tensorcores_per_chip
                    lo = cs * per_core
                    hi_end = (cs + self.tensorcores) * per_core
                    if not (lo <= hs and hs + self.hbm_slices <= hi_end):
                        continue
                out.append(PartitionPlacement(self, cs, hs))
        return out


@dataclass(frozen=True)
class PartitionPlacement:
    profile: PartitionProfile
    core_start: int
    hbm_start: int


def partition_profiles(spec: TpuGenerationSpec) -> list[PartitionProfile]:
    """Supported profiles for a generation.  Non-partitionable generations
    (single fused core) support none."""
    if not spec.partitionable:
        return []
    profiles = []
    cores = spec.tensorcores_per_chip
    c = 1
    while c <= cores:
        h = HBM_SLICES_PER_CHIP // (cores // c)
        # Each core count supports its proportional HBM share and every
        # larger power-of-two share up to the full chip.
        while h <= HBM_SLICES_PER_CHIP:
            profiles.append(PartitionProfile(c, h))
            h *= 2
        c *= 2
    return profiles


@dataclass
class TpuChip:
    """One physical TPU chip on this host."""

    index: int  # host-local index; device node /dev/accel<index>
    uuid: str
    generation: str
    coords: tuple[int, int, int]  # ICI mesh coordinates within the slice
    pci_address: str
    # Fabric identity: "<slice_uuid>.<partition_id>" — chips that share it are
    # ICI-connected (the clusterUUID.cliqueID analog).
    clique_id: str
    hbm_bytes: int = 0
    tensorcores: int = 0

    @property
    def spec(self) -> TpuGenerationSpec:
        return GENERATIONS[self.generation]

    def dev_paths(self) -> list[str]:
        # Cloud TPU VMs expose both the accel and vfio-style nodes; the accel
        # node is the canonical one for libtpu.
        return [f"/dev/accel{self.index}"]


@dataclass
class SliceTopology:
    """The slice this host belongs to, as visible from the host."""

    slice_uuid: str
    partition_id: int
    mesh_shape: tuple[int, int, int]  # full slice mesh, e.g. v5p-16 = (2,2,2)
    host_index: int  # this host's index within the slice
    num_hosts: int

    @property
    def clique_id(self) -> str:
        return f"{self.slice_uuid}.{self.partition_id}"


@dataclass
class MockTopologyConfig:
    """Config for the mock backend (our hermetic-CI replacement for the fake
    NVML backend the reference never had; see SURVEY.md §4.3)."""

    generation: str = "v5p"
    num_chips: Optional[int] = None  # default: chips_per_host for generation
    slice_uuid: str = "mock-slice-0000"
    partition_id: int = 0
    mesh_shape: Optional[tuple[int, int, int]] = None
    host_index: int = 0
    num_hosts: int = 1
    # Pre-existing (static) partitions: list of (chip_index, profile_name,
    # core_start, hbm_start).
    static_partitions: list = field(default_factory=list)
    # Capability attestation (DeviceLib.partitions_supported): the mock is
    # a simulation backend, so True by default; tests flip it to model a
    # real-silicon node where no runtime API can mutate partitions.
    partitions_supported: bool = True

    @classmethod
    def from_json(cls, text: str) -> "MockTopologyConfig":
        data = json.loads(text)
        if "mesh_shape" in data and data["mesh_shape"] is not None:
            data["mesh_shape"] = tuple(data["mesh_shape"])
        data["static_partitions"] = [tuple(p) for p in data.get("static_partitions", [])]
        return cls(**data)

    def resolve(self) -> tuple[TpuGenerationSpec, int, tuple[int, int, int]]:
        spec = GENERATIONS[self.generation]
        num = self.num_chips if self.num_chips is not None else spec.chips_per_host
        if self.mesh_shape is not None:
            mesh = self.mesh_shape
        else:
            hb = spec.host_bounds
            mesh = (hb[0], hb[1], hb[2] * self.num_hosts)
        return spec, num, mesh


def host_origin(
    spec: TpuGenerationSpec, host_index: int
) -> tuple[int, int, int]:
    """Origin of one host's chip block within the slice mesh — the host's
    ICI *position*, as distinct from its index.  One definition shared by
    chip layout (:func:`chip_coords_for_host`) and the per-node grant env
    (``TPUDRA_HOST_COORDS``, cdplugin/libtpuenv.slice_env): a rank that
    knows its origin plus the slice mesh shape can place itself without
    enumerating any chip."""
    hb = spec.host_bounds
    return (0, 0, host_index * hb[2])


def chip_coords_for_host(
    spec: TpuGenerationSpec, host_index: int, num_chips: int
) -> list[tuple[int, int, int]]:
    """Lay this host's chips out in its block of the slice mesh.  Hosts stack
    along z (v5p) or y (2D generations)."""
    hb = spec.host_bounds
    if num_chips > hb[0] * hb[1] * hb[2]:
        # Overflowing the host's mesh block would collide with the next
        # host's coordinates; real hosts never exceed their block.
        raise ValueError(
            f"num_chips={num_chips} exceeds the {spec.name} host block "
            f"{hb[0]}x{hb[1]}x{hb[2]}"
        )
    coords = []
    base_z = host_origin(spec, host_index)[2]
    i = 0
    for z in range(hb[2]):
        for y in range(hb[1]):
            for x in range(hb[0]):
                if i >= num_chips:
                    return coords
                coords.append((x, y, base_z + z))
                i += 1
    return coords

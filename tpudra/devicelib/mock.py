"""Mock TPU device backend.

The hermetic-CI device backend the reference never had (its e2e suite requires
real GPU runners; SURVEY.md §4.3).  Topology comes from MockTopologyConfig
(inline, or JSON via the TPUDRA_MOCK_TOPOLOGY env var); partition state can be
persisted to a JSON file so driver restarts see pre-existing partitions — that
is what exercises the startup-reconciliation/rollback machinery
(DestroyUnknownPartitions) the same way real hardware would.

Health events are injected by tests through ``inject_health_event``.
"""

from __future__ import annotations

import json
import os
import queue
import threading
import uuid as uuidlib
from typing import Iterator, Optional

from tpudra import walwitness
from tpudra.devicelib.base import (
    DeviceLib,
    DeviceLibError,
    HealthEvent,
    LivePartition,
    PartitionSpec,
)
from tpudra.devicelib.topology import (
    GENERATIONS,
    HBM_SLICES_PER_CHIP,
    MockTopologyConfig,
    PartitionPlacement,
    PartitionProfile,
    SliceTopology,
    TpuChip,
    chip_coords_for_host,
    partition_profiles,
)

MOCK_TOPOLOGY_ENV = "TPUDRA_MOCK_TOPOLOGY"


class MockDeviceLib(DeviceLib):
    def __init__(
        self,
        config: Optional[MockTopologyConfig] = None,
        state_file: Optional[str] = None,
    ):
        if config is None:
            env = os.environ.get(MOCK_TOPOLOGY_ENV)
            if env:
                if env.strip().startswith("{"):
                    config = MockTopologyConfig.from_json(env)
                else:
                    with open(env) as f:
                        config = MockTopologyConfig.from_json(f.read())
            else:
                config = MockTopologyConfig()
        self._config = config
        self._state_file = state_file
        self._lock = threading.Lock()
        self._partitions: dict[str, LivePartition] = {}
        self._timeslice: dict[str, str] = {}
        self._exclusive: dict[str, bool] = {}
        self._health_queues: list[queue.Queue] = []
        self._injected_events: list[HealthEvent] = []

        spec, num_chips, mesh = config.resolve()
        coords = chip_coords_for_host(spec, config.host_index, num_chips)
        clique = f"{config.slice_uuid}.{config.partition_id}"
        self._chips = [
            TpuChip(
                index=i,
                uuid=f"tpu-{config.slice_uuid}-{config.host_index}-{i}",
                generation=spec.name,
                coords=coords[i],
                pci_address=f"0000:{0x10 + i:02x}:00.0",
                clique_id=clique,
                hbm_bytes=spec.hbm_bytes,
                tensorcores=spec.tensorcores_per_chip,
            )
            for i in range(num_chips)
        ]
        self._topology = SliceTopology(
            slice_uuid=config.slice_uuid,
            partition_id=config.partition_id,
            mesh_shape=mesh,
            host_index=config.host_index,
            num_hosts=config.num_hosts,
        )
        # Constructor-time loads take the registry lock too: nothing races
        # during __init__ itself, but the soak's fault injector creates and
        # deletes partitions concurrently with harness resets, and every
        # write to _partitions must share ONE guard for that to stay sound
        # (tpudra-racegraph pins the lockset).
        with self._lock:
            # tpudra-lint: disable=BLOCK-UNDER-LOCK-IP the state file IS the simulated silicon — load/create must be atomic with the in-memory registry, same as create_partition
            self._load_state()
            for part in config.static_partitions:
                chip_idx, profile, core_start, hbm_start = part
                spec_ = PartitionSpec(chip_idx, profile, core_start, hbm_start)
                if not any(p.spec == spec_ for p in self._partitions.values()):
                    # tpudra-lint: disable=BLOCK-UNDER-LOCK-IP the state file IS the simulated silicon — the static-partition create must be atomic with the registry, same as create_partition
                    self._create_unlocked(spec_, static=True)

    # -- state persistence --------------------------------------------------

    def _load_state(self) -> None:
        if not self._state_file or not os.path.exists(self._state_file):
            return
        with open(self._state_file) as f:
            data = json.load(f)
        for p in data.get("partitions", []):
            lp = LivePartition(
                spec=PartitionSpec(**p["spec"]),
                uuid=p["uuid"],
                parent_uuid=p["parent_uuid"],
                dev_paths=p["dev_paths"],
            )
            self._partitions[lp.uuid] = lp

    def _save_state(self) -> None:
        if not self._state_file:
            return
        data = {
            "partitions": [
                {
                    "spec": vars(p.spec),
                    "uuid": p.uuid,
                    "parent_uuid": p.parent_uuid,
                    "dev_paths": p.dev_paths,
                }
                for p in self._partitions.values()
            ]
        }
        tmp = self._state_file + ".tmp"
        with open(tmp, "w") as f:
            json.dump(data, f)
        os.replace(tmp, self._state_file)

    # -- enumeration --------------------------------------------------------

    def enumerate_chips(self) -> list[TpuChip]:
        return list(self._chips)

    def slice_topology(self) -> SliceTopology:
        return self._topology

    def chip_by_index(self, index: int) -> TpuChip:
        for chip in self._chips:
            if chip.index == index:
                return chip
        raise DeviceLibError(f"no chip with index {index}")

    def chip_by_uuid(self, uuid: str) -> TpuChip:
        for chip in self._chips:
            if chip.uuid == uuid:
                return chip
        raise DeviceLibError(f"no chip with uuid {uuid}")

    # -- partitions ---------------------------------------------------------

    def partitions_supported(self) -> bool:
        return self._config.partitions_supported

    def possible_placements(self, chip: TpuChip) -> list[PartitionPlacement]:
        out = []
        for profile in partition_profiles(chip.spec):
            out.extend(profile.placements(chip.spec))
        return out

    def _overlaps(self, a: PartitionSpec, b: PartitionSpec) -> bool:
        if a.parent_index != b.parent_index:
            return False

        def ranges(s: PartitionSpec):
            prof = _parse_profile(s.profile)
            return (
                (s.core_start, s.core_start + prof.tensorcores),
                (s.hbm_start, s.hbm_start + prof.hbm_slices),
            )

        (ac, ah), (bc, bh) = ranges(a), ranges(b)
        cores_overlap = ac[0] < bc[1] and bc[0] < ac[1]
        hbm_overlap = ah[0] < bh[1] and bh[0] < ah[1]
        return cores_overlap or hbm_overlap

    def _create_unlocked(self, spec: PartitionSpec, static: bool = False) -> LivePartition:
        chip = self.chip_by_index(spec.parent_index)
        prof = _parse_profile(spec.profile)
        gen = GENERATIONS[chip.generation]
        if prof.tensorcores + spec.core_start > gen.tensorcores_per_chip:
            raise DeviceLibError(f"placement {spec} exceeds chip cores")
        if prof.hbm_slices + spec.hbm_start > HBM_SLICES_PER_CHIP:
            raise DeviceLibError(f"placement {spec} exceeds chip HBM")
        if not gen.partitionable:
            raise DeviceLibError(f"generation {gen.name} is not partitionable")
        for live in self._partitions.values():
            if self._overlaps(live.spec, spec):
                raise DeviceLibError(
                    f"placement {spec} collides with existing partition {live.uuid}"
                )
        uuid = f"tpupart-{uuidlib.uuid4().hex[:12]}"
        live = LivePartition(
            spec=spec,
            uuid=uuid,
            parent_uuid=chip.uuid,
            dev_paths=[f"/dev/accel{chip.index}"],
        )
        self._partitions[uuid] = live
        self._save_state()
        return live

    def create_partition(self, spec: PartitionSpec) -> LivePartition:
        walwitness.note_effect("partition:create")
        with self._lock:
            # tpudra-lint: disable=BLOCK-UNDER-LOCK-IP the state file IS the simulated silicon — its write must be atomic with the in-memory registry, exactly like the hardware mutation it stands in for
            return self._create_unlocked(spec)

    def delete_partition(self, uuid: str) -> None:
        walwitness.note_effect("partition:destroy")
        with self._lock:
            if uuid not in self._partitions:
                raise DeviceLibError(f"no partition with uuid {uuid}")
            del self._partitions[uuid]
            # tpudra-lint: disable=BLOCK-UNDER-LOCK-IP the state file IS the simulated silicon — its write must be atomic with the registry drop
            self._save_state()

    def list_partitions(self) -> list[LivePartition]:
        with self._lock:
            return list(self._partitions.values())

    # -- sharing knobs ------------------------------------------------------

    def set_timeslice(self, chip_uuids: list[str], interval: str) -> None:
        walwitness.note_effect("timeslice:set")
        with self._lock:
            for u in chip_uuids:
                self.chip_by_uuid(u)  # existence check
                self._timeslice[u] = interval

    def set_exclusive(self, chip_uuids: list[str], exclusive: bool) -> None:
        with self._lock:
            for u in chip_uuids:
                self.chip_by_uuid(u)
                self._exclusive[u] = exclusive

    def get_timeslice(self, chip_uuid: str) -> Optional[str]:
        with self._lock:
            return self._timeslice.get(chip_uuid)

    def get_exclusive(self, chip_uuid: str) -> bool:
        with self._lock:
            return self._exclusive.get(chip_uuid, False)

    # -- health -------------------------------------------------------------

    def inject_health_event(self, event: HealthEvent) -> None:
        with self._lock:
            self._injected_events.append(event)
            for q in self._health_queues:
                q.put(event)

    def fault_chip(
        self, index: int, kind: str = "HbmEccError", detail: str = ""
    ) -> HealthEvent:
        """Inject a chip-scoped fault by index — the one-call injector the
        chaos soak's chip_fault and the multihost harness use (resolving
        the uuid here keeps every injector honest about which silicon it
        faulted).  Returns the injected event."""
        event = HealthEvent(
            kind=kind, chip_uuid=self.chip_by_index(index).uuid, detail=detail
        )
        self.inject_health_event(event)
        return event

    @property
    def injected_events(self) -> list[HealthEvent]:
        """Every event ever injected (introspection for harness
        invariants: 'which chips have been faulted on this node')."""
        with self._lock:
            return list(self._injected_events)

    def health_events(self, stop: threading.Event) -> Iterator[HealthEvent]:
        q: queue.Queue = queue.Queue()
        with self._lock:
            self._health_queues.append(q)
        try:
            while not stop.is_set():
                try:
                    yield q.get(timeout=0.05)
                except queue.Empty:
                    continue
        finally:
            with self._lock:
                if q in self._health_queues:
                    self._health_queues.remove(q)


def _parse_profile(name: str) -> PartitionProfile:
    try:
        cores_s, hbm_s = name.split(".")
        return PartitionProfile(int(cores_s.rstrip("c")), int(hbm_s.rstrip("hbm")))
    except (ValueError, AttributeError):
        raise DeviceLibError(f"invalid partition profile {name!r}") from None


def fake_sysfs_tree(root: str, chips) -> str:
    """Fabricate the PCI/IOMMU sysfs surface the vfio rebind path touches
    (tpudra/plugin/vfio.py), for the mock backend's chips: per-device dirs
    with an ``iommu_group`` file (group 7+index) and the two driver dirs.
    Shared by the unit tests and the bats harness so the layout cannot
    diverge from what VfioManager reads."""
    import os

    sysfs = os.path.join(root, "sys")
    os.makedirs(os.path.join(sysfs, "kernel", "iommu_groups", "7"), exist_ok=True)
    for chip in chips:
        d = os.path.join(sysfs, "bus", "pci", "devices", chip.pci_address)
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, "iommu_group"), "w") as f:
            f.write(str(7 + chip.index))
    for drv in ("tpu", "vfio-pci"):
        os.makedirs(os.path.join(sysfs, "bus", "pci", "drivers", drv), exist_ok=True)
    return sysfs

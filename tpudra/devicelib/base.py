"""Hardware-abstraction interface for TPU enumeration and control.

The analog of the reference's ``deviceLib`` (gpu-kubelet-plugin/nvlib.go:41):
everything the kubelet plugins need from the hardware lives behind this
interface so business logic runs identically on the mock backend (hermetic CI)
and the native backend (C++ libtpuinfo via ctypes, native/tpuinfo/).

Mapping to the reference:
- enumerate_chips / slice_topology  ↔ VisitDevices+getGpuInfo / fabric info
- partition create/delete/list     ↔ createMigDevice/deleteMigDevice (nvlib.go:860-1128)
- set_timeslice / set_exclusive    ↔ nvidia-smi timeslice/compute-mode shellouts
- health event stream              ↔ NVML XID/ECC event set (device_health.go)
"""

from __future__ import annotations

import abc
import threading
from dataclasses import dataclass
from typing import Iterator, Optional

from tpudra.devicelib.topology import (
    PartitionPlacement,
    SliceTopology,
    TpuChip,
)


@dataclass(frozen=True)
class PartitionSpec:
    """Identity of a possible partition: (parent chip index, profile name,
    core_start, hbm_start) — the analog of the reference's MigSpecTuple
    (parentMinor, profileID, placementStart), mig.go:35."""

    parent_index: int
    profile: str  # PartitionProfile.name, e.g. "1c.4hbm"
    core_start: int
    hbm_start: int


@dataclass
class LivePartition:
    """A partition that exists on the hardware right now (MigLiveTuple analog,
    mig.go:65)."""

    spec: PartitionSpec
    uuid: str
    parent_uuid: str
    dev_paths: list[str]


class HealthEventKind:
    # The XID-analog taxonomy for TPUs: hardware interrupt classes surfaced
    # by the driver (reference device_health.go:38-351 maps NVML XID/ECC).
    HBM_ECC_ERROR = "HbmEccError"
    ICI_LINK_DOWN = "IciLinkDown"
    CHIP_LOCKUP = "ChipLockup"
    THERMAL_TRIP = "ThermalTrip"
    FIRMWARE_FAULT = "FirmwareFault"

    ALL = (HBM_ECC_ERROR, ICI_LINK_DOWN, CHIP_LOCKUP, THERMAL_TRIP, FIRMWARE_FAULT)

    # Events that do not indicate the chip itself is unusable — the analog of
    # the reference's default-ignored XIDs (device_health.go:329: app-caused
    # XIDs 13,31,43,45,...).  ICI link flaps degrade the fabric but the chip
    # still computes; the ComputeDomain layer owns fabric health.
    DEFAULT_IGNORED = (ICI_LINK_DOWN,)


@dataclass(frozen=True)
class HealthEvent:
    kind: str
    chip_uuid: str
    partition_uuid: Optional[str] = None  # set when scoped to a partition
    detail: str = ""

    def to_line(self) -> str:
        """The native backend's event-file wire form: one event per line,
        ``<kind> <chipUUID> <partUUID|-> <detail>``.  Shared by whatever
        writes the fifo (tests, the chaos soak's chip_fault injector, an
        operator's manual fault injection) so the injector and the parser
        cannot drift."""
        return " ".join(
            (self.kind, self.chip_uuid, self.partition_uuid or "-", self.detail)
        ).rstrip()

    @classmethod
    def from_line(cls, line: str) -> Optional["HealthEvent"]:
        """Parse one event-file line; None for blank/short lines (the
        native stream skips them rather than dying on a torn write)."""
        parts = line.split(None, 3)
        if len(parts) < 2:
            return None
        return cls(
            kind=parts[0],
            chip_uuid=parts[1],
            partition_uuid=parts[2] if len(parts) > 2 and parts[2] != "-" else None,
            detail=parts[3].strip() if len(parts) > 3 else "",
        )


class DeviceLibError(Exception):
    pass


class DeviceLib(abc.ABC):
    """Abstract TPU device library."""

    # -- enumeration --------------------------------------------------------

    @abc.abstractmethod
    def enumerate_chips(self) -> list[TpuChip]:
        """All chips on this host, stable order by index."""

    @abc.abstractmethod
    def slice_topology(self) -> SliceTopology:
        """This host's slice membership / fabric identity."""

    # -- partitions (MIG analog) -------------------------------------------

    def partitions_supported(self) -> bool:
        """Capability attestation: can this backend actually mutate
        sub-chip partitions?  The plugin only advertises dynamic-partition
        devices when this is True (the MIG-capability gating analog,
        reference nvlib.go:269-301) — advertising partitions the hardware
        cannot enforce would hand the scheduler phantom devices.  Default
        True for simulation backends; the native library attests per
        handle (no public TPU runtime API exposes partition mutation, so
        real silicon reports False unless simulation is opted in)."""
        return True

    def multiprocess_mode(self) -> str:
        """Platform attestation for multi-process chip sharing (the
        MPS-enforcement-truth analog, reference sharing.go:123-445):

        - ``"concurrent"``: a second process CAN open the chip while a
          first holds it — processes can share; broker limits stay
          cooperative (nothing enforces percentages in hardware).
        - ``"exclusive"``: a second open is refused (EBUSY) — concurrent
          process sharing is impossible and the MP broker can only
          time-multiplex attachment.
        - ``"unknown"``: no device node to probe (remote tunnel, config
          mode).

        Published as a chip attribute and surfaced by the MP control
        daemon's STATUS so operators see the truth, not the aspiration.
        Default reflects simulation backends: pods are plain processes
        sharing a CPU device, so concurrent."""
        return "concurrent"

    @abc.abstractmethod
    def possible_placements(self, chip: TpuChip) -> list[PartitionPlacement]:
        """All (profile, placement) pairs the chip supports."""

    @abc.abstractmethod
    def create_partition(self, spec: PartitionSpec) -> LivePartition:
        """Carve a TensorCore partition out of a chip.  Idempotence is the
        caller's job (checkpoint state machine); colliding placements raise."""

    @abc.abstractmethod
    def delete_partition(self, uuid: str) -> None:
        """Destroy a live partition by uuid; unknown uuid raises."""

    @abc.abstractmethod
    def list_partitions(self) -> list[LivePartition]:
        """Partitions that exist right now (startup reconciliation input for
        DestroyUnknownPartitions, reference device_state.go:337)."""

    # -- sharing knobs ------------------------------------------------------

    @abc.abstractmethod
    def set_timeslice(self, chip_uuids: list[str], interval: str) -> None:
        """Record the cooperative time-slice hint for the chips."""

    @abc.abstractmethod
    def set_exclusive(self, chip_uuids: list[str], exclusive: bool) -> None:
        """Single-client vs multi-client chip access (compute-mode analog)."""

    # -- health -------------------------------------------------------------

    @abc.abstractmethod
    def health_events(self, stop: threading.Event) -> Iterator[HealthEvent]:
        """Blocking stream of health events until ``stop`` is set."""

    # -- lifecycle ----------------------------------------------------------

    def close(self) -> None:
        return None


def make_device_lib(backend: str = "mock", **kwargs) -> DeviceLib:
    """Factory: backend is "mock" (hermetic) or "native" (C++ libtpuinfo)."""
    if backend == "mock":
        from tpudra.devicelib.mock import MockDeviceLib

        return MockDeviceLib(**kwargs)
    if backend == "native":
        from tpudra.devicelib.native import NativeDeviceLib

        return NativeDeviceLib(**kwargs)
    raise DeviceLibError(f"unknown device-lib backend {backend!r}")

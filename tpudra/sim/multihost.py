"""Multi-host launch harness: ComputeDomain claim → gang → real psum.

PAPER.md's end state, hermetically: a ComputeDomain claim for an N-node
slice is gang-reserved (controller/gang.py) through N real CD plugin
drivers, and the resulting grants launch **one real OS process per
simulated node** that joins ``jax.distributed`` from the grant env alone
and runs a cross-process psum — the first harness that exercises the
cluster *vertically* (claim → allocation → grant env → mesh formation →
collective) instead of node-locally.

What is real here:

- the CD plugin bind path per node (checkpointed prepare, channel
  conflict detection, CDI spec write, node label) — the same code kubelet
  drives in production;
- the gang reservation state machine and its WAL journaling;
- the grant env: each rank process receives EXACTLY the env the claim's
  CDI spec carries (plus the sim's platform shims below) — coordinator
  address, process count, mesh shape, host coords, the libtpu
  worker-bootstrap contract;
- the DCN rendezvous relay: host 0 binds its coordinator locally and
  registers it in the per-domain dir; peers dial the REAL
  ``cddaemon.coordproxy.CoordinatorProxy`` which forwards to the
  registration — the production path minus only the stable DNS name
  (both "hosts" are this machine, so the name is swapped for loopback);
- the collective: ``jax.distributed.initialize`` + a jitted psum across
  all ranks (gloo CPU collectives — the multiprocess CPU shim
  ``workload/envspec._enable_cpu_collectives`` enables for simulations).

Sim shims, each one env-visible: ``JAX_PLATFORMS=cpu`` (no TPU in CI),
``XLA_FLAGS=--xla_force_host_platform_device_count=<chips/host>`` (each
rank fields as many "chips" as its granted host block, so
``jax.devices()`` must equal the granted slice's chip count), and
``TPUDRA_SIM_COORDINATOR`` (loopback for the stable daemon DNS name).

Entry points: ``make e2e-multihost`` (tests/test_multihost.py, the
``multihost`` marker lane) and ``python -m tpudra.sim.multihost`` (the
demo CLI; ``--kill-rank K`` exercises the failure path: a dead rank fails
the launch, and release/rollback must leave zero bound claims and zero
CDI spec files on every node).
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time
from dataclasses import dataclass, field
from typing import Optional

from tpudra import COMPUTE_DOMAIN_DRIVER_NAME, trace
from tpudra.controller.gang import (
    GangBindError,
    GangMember,
    GangReservationManager,
)
from tpudra.kube import gvr
from tpudra.kube.fake import FakeKube
from tpudra.plugin.checkpoint import CheckpointManager

logger = logging.getLogger(__name__)

CD_API_V = "resource.tpu.google.com/v1beta1"
REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_channel_claim(
    uid: str,
    node: str,
    domain_uid: str,
    channel_id: int = 0,
    namespace: str = "default",
) -> dict:
    """An allocated ComputeDomain channel claim bound to ``node``'s pool —
    what the scheduler's allocator writes for one member of the gang."""
    return {
        "metadata": {"uid": uid, "namespace": namespace, "name": uid},
        "status": {
            "allocation": {
                "devices": {
                    "results": [
                        {
                            "request": "channel",
                            "driver": COMPUTE_DOMAIN_DRIVER_NAME,
                            "pool": node,
                            "device": f"channel-{channel_id}",
                        }
                    ],
                    "config": [
                        {
                            "source": "FromClaim",
                            "requests": [],
                            "opaque": {
                                "driver": COMPUTE_DOMAIN_DRIVER_NAME,
                                "parameters": {
                                    "apiVersion": CD_API_V,
                                    "kind": "ComputeDomainChannelConfig",
                                    "domainID": domain_uid,
                                    "allocationMode": "Single",
                                },
                            },
                        }
                    ],
                }
            }
        },
    }


def make_compute_domain(
    name: str,
    uid: str,
    nodes: list[str],
    namespace: str = "default",
    ready: bool = True,
) -> dict:
    """A ComputeDomain object for ``nodes``.  ``ready=True`` stamps the
    aggregated Ready status directly (harness/bench contexts with no live
    controller); ``ready=False`` leaves status to a running controller's
    clique aggregation (the chaos soak's cd-wave)."""
    cd = {
        "apiVersion": CD_API_V,
        "kind": "ComputeDomain",
        "metadata": {"name": name, "namespace": namespace, "uid": uid},
        "spec": {"numNodes": len(nodes)},
    }
    if ready:
        cd["status"] = {
            "status": "Ready",
            "nodes": [{"name": n, "status": "Ready"} for n in nodes],
        }
    return cd


def build_cd_stack(
    kube,
    node_names: list[str],
    base: str,
    num_hosts: Optional[int] = None,
    generation: str = "v5p",
    slice_uuid: Optional[str] = None,
    prefix: str = "cd",
    host_indices: Optional[list[int]] = None,
) -> dict[str, object]:
    """Per-node CD plugin drivers over persistent dirs under ``base`` —
    the one construction shared by this harness, the chaos soak's cd-wave
    stack, and ``bench.py --gang`` (node ``i`` is host ``i`` of an
    ``num_hosts``-host slice).  ``host_indices`` overrides the default
    identity mapping — how a HOT SPARE node is cabled at the slot it can
    replace (its grants must carry the displaced host's mesh position)."""
    from tpudra.cdplugin.driver import CDDriver, CDDriverConfig
    from tpudra.devicelib.mock import MockDeviceLib
    from tpudra.devicelib.topology import MockTopologyConfig

    n = num_hosts if num_hosts is not None else len(node_names)
    drivers: dict[str, object] = {}
    for i, name in enumerate(node_names):
        host_index = host_indices[i] if host_indices is not None else i
        topo_kwargs = dict(
            generation=generation, num_hosts=n, host_index=host_index
        )
        if slice_uuid is not None:
            topo_kwargs["slice_uuid"] = slice_uuid
        lib = MockDeviceLib(
            config=MockTopologyConfig(**topo_kwargs),
            state_file=os.path.join(base, f"{prefix}-hw{i}.json"),
        )
        drivers[name] = CDDriver(
            CDDriverConfig(
                node_name=name,
                plugin_dir=os.path.join(base, f"{prefix}-p{i}"),
                registry_dir=os.path.join(base, f"{prefix}-r{i}"),
                cdi_root=os.path.join(base, f"{prefix}-c{i}"),
            ),
            kube,
            lib,
        )
    return drivers


def close_cd_stack(drivers: dict[str, object]) -> None:
    """Teardown counterpart of :func:`build_cd_stack`: every driver's
    checkpoint gets its clean-shutdown close (the journal compaction the
    plugins wire into stop() — the WAL downgrade gate)."""
    for d in drivers.values():
        try:
            d._checkpoints.close()
        except Exception:  # noqa: BLE001 — teardown must visit every node
            logger.exception("cd driver checkpoint close failed")


class DriverGangBinder:
    """GangBinder over in-process CD plugin drivers — the harness (like
    the cluster sim's churn) plays kubelet: bind = the node's real
    checkpointed prepare, unbind = its real unprepare.  Used by the
    multi-host harness, the chaos soak's cd-wave, and ``bench.py --gang``.
    """

    def __init__(self, drivers: dict[str, object]):
        self._drivers = drivers  # node name -> CDDriver

    def bind(self, member: GangMember, claim: dict) -> None:
        driver = self._drivers[member.node]
        resp = driver.prepare_resource_claims([claim])
        entry = resp["claims"].get(member.claim_uid, {})
        err = entry.get("error")
        if err:
            raise GangBindError(
                f"prepare on {member.node}: {err}"
                + (" (permanent)" if entry.get("permanent") else "")
            )

    def unbind(self, member: GangMember) -> None:
        driver = self._drivers[member.node]
        resp = driver.unprepare_resource_claims([{"uid": member.claim_uid}])
        err = resp["claims"].get(member.claim_uid, {}).get("error")
        if err:
            raise RuntimeError(f"unprepare on {member.node}: {err}")


@dataclass
class RankResult:
    rank: int
    returncode: Optional[int]
    output: str

    @property
    def ok(self) -> bool:
        return self.returncode == 0


@dataclass
class MultiHostConfig:
    num_hosts: int = 4
    generation: str = "v5p"
    namespace: str = "default"
    domain_name: str = "gang-e2e"
    base_dir: Optional[str] = None
    #: Wall deadline for the rank processes (jax.distributed's own
    #: initialization timeout is 300 s; a harness must fail faster).
    launch_deadline_s: float = 120.0
    extra_env: dict = field(default_factory=dict)
    #: Hot-standby nodes: each listed slot k gets a spare node
    #: (``mh-spare-k``) cabled at host position k — a CD driver whose
    #: grants carry slot k's mesh coordinates, so a chip fault on member k
    #: can remediate onto it without changing the slice geometry.  Spares
    #: (and members) then also get per-node TPU health drivers publishing
    #: real ResourceSlices, because remediation's member selection filters
    #: on PUBLISHED slice health (controller/gang.select_healthy_spares).
    spare_slots: tuple = ()


class MultiHostGang:
    """N simulated TPU hosts, one gang, one launch.

    Lifecycle: ``up()`` → ``reserve()`` → ``launch()`` → ``release()`` →
    ``close()`` (or use as a context manager for up/close)."""

    def __init__(self, config: MultiHostConfig | None = None):
        self.config = config or MultiHostConfig()
        self.kube = FakeKube()
        self.domain_uid = f"{self.config.domain_name}-uid"
        self.node_names = [
            f"mh-node-{i}" for i in range(self.config.num_hosts)
        ]
        self._tmp: Optional[tempfile.TemporaryDirectory] = None
        self.drivers: dict[str, object] = {}
        #: Per-node TPU plugin drivers (health + slice publication), built
        #: only when spare_slots asks for the remediation machinery.
        self.tpu_drivers: dict[str, object] = {}
        #: Spare node name → the host slot it can replace.
        self.spare_slot: dict[str, int] = {}
        self.gangs: Optional[GangReservationManager] = None
        self._gang_cp: Optional[CheckpointManager] = None
        self.grant: Optional[object] = None
        self._members: list[GangMember] = []
        self._proxy = None
        self._procs: list[subprocess.Popen] = []

    # ------------------------------------------------------------ lifecycle

    def up(self) -> "MultiHostGang":
        cfg = self.config
        self._tmp = tempfile.TemporaryDirectory(prefix="tpudra-multihost-")
        base = self._tmp.name
        self.spare_slot = {
            f"mh-spare-{slot}": slot for slot in cfg.spare_slots
        }
        all_nodes = self.node_names + sorted(self.spare_slot)
        for name in all_nodes:
            self.kube.create(gvr.NODES, {"metadata": {"name": name}, "spec": {}})
        # The ComputeDomain object, already Ready on every member AND
        # spare node (daemons run on spares too — that is what makes them
        # spares): the harness plays the controller's status-aggregation
        # role (the bats suite exercises the real daemon/clique path; this
        # harness exercises the gang + launch path).
        self.kube.create(
            gvr.COMPUTE_DOMAINS,
            make_compute_domain(
                cfg.domain_name,
                self.domain_uid,
                all_nodes,
                namespace=cfg.namespace,
            ),
            cfg.namespace,
        )
        self.drivers = build_cd_stack(
            self.kube,
            all_nodes,
            base,
            num_hosts=cfg.num_hosts,
            generation=cfg.generation,
            slice_uuid=f"{cfg.domain_name}-slice",
            # Members take their own slot; each spare is cabled at the
            # slot it replaces.
            host_indices=[
                self.spare_slot.get(name, i if i < cfg.num_hosts else 0)
                for i, name in enumerate(all_nodes)
            ],
        )
        if self.spare_slot:
            self._build_tpu_health_drivers(base, all_nodes)
        self._gang_cp = CheckpointManager(os.path.join(base, "controller"))
        self.gangs = GangReservationManager(
            self._gang_cp, DriverGangBinder(self.drivers)
        )
        return self

    def _build_tpu_health_drivers(self, base: str, nodes: list[str]) -> None:
        """One TPU plugin Driver per node, publishing real ResourceSlices
        into the shared fake — the published-slice-health substrate the
        remediation's spare selection reads.  Never start()ed: publication
        runs inline and health events are delivered straight to the
        handler (the health loop's body)."""
        from tpudra.devicelib.mock import MockDeviceLib
        from tpudra.devicelib.topology import MockTopologyConfig
        from tpudra.plugin.driver import Driver, DriverConfig

        for i, name in enumerate(nodes):
            lib = MockDeviceLib(
                config=MockTopologyConfig(num_chips=4),
                state_file=os.path.join(base, f"tpu-hw{i}.json"),
            )
            driver = Driver(
                DriverConfig(
                    node_name=name,
                    plugin_dir=os.path.join(base, f"tpu-p{i}"),
                    registry_dir=os.path.join(base, f"tpu-r{i}"),
                    cdi_root=os.path.join(base, f"tpu-c{i}"),
                    claim_cache=False,
                    initial_pool_generation=1,
                ),
                self.kube,
                lib,
            )
            driver.publish_resources()
            self.tpu_drivers[name] = driver

    def close(self) -> None:
        self._kill_procs()
        if self._proxy is not None:
            self._proxy.stop()
            self._proxy = None
        close_cd_stack(self.drivers)
        for d in self.tpu_drivers.values():
            try:
                d._checkpoints.close()
            except Exception:  # noqa: BLE001 — teardown must visit every node
                logger.exception("tpu health driver checkpoint close failed")
        if self._gang_cp is not None:
            try:
                self._gang_cp.close()
            except Exception:  # noqa: BLE001 — teardown continues
                logger.exception("gang checkpoint close failed")
            self._gang_cp = None
        if self._tmp is not None:
            self._tmp.cleanup()
            self._tmp = None

    def __enter__(self) -> "MultiHostGang":
        return self.up()

    def __exit__(self, *exc) -> None:
        self.close()

    # ---------------------------------------------------------- reservation

    def members(self) -> list[GangMember]:
        return [
            GangMember(node=n, claim_uid=f"{self.domain_uid}-m{i}",
                       namespace=self.config.namespace)
            for i, n in enumerate(self.node_names)
        ]

    def reserve(self) -> object:
        """Gang-reserve one channel claim per node; returns the
        GangStatus.  Raises GangBindError (rolled back) on any member
        failure."""
        self._members = self.members()
        claims = {
            m.claim_uid: make_channel_claim(
                m.claim_uid,
                m.node,
                self.domain_uid,
                namespace=self.config.namespace,
            )
            for m in self._members
        }
        for claim in claims.values():
            self.kube.create(gvr.RESOURCE_CLAIMS, claim, self.config.namespace)
        self.grant = self.gangs.reserve(
            self.config.domain_name, self._members, claims
        )
        return self.grant

    def release(self) -> None:
        self.gangs.release(self.config.domain_name)
        self.grant = None

    # --------------------------------------------------------- remediation

    def fault_chip(self, member_index: int, chip_index: int = 0):
        """Fault a chip on a bound member's node, through the TPU driver's
        real health handler: the chip leaves the published ResourceSlices
        (with the unhealthy-count annotation bumped) and any bound TPU
        claim holding it gets the status-condition escalation.  Returns
        the injected HealthEvent."""
        if not self.tpu_drivers:
            raise RuntimeError("fault_chip needs spare_slots (health drivers)")
        from tpudra.devicelib import HealthEvent, HealthEventKind

        node = self.node_names[member_index]
        driver = self.tpu_drivers[node]
        event = HealthEvent(
            kind=HealthEventKind.HBM_ECC_ERROR,
            chip_uuid=driver._lib.chip_by_index(chip_index).uuid,
            detail=f"harness fault on {node}",
        )
        driver._handle_health_event(event)
        return event

    def remediate_unhealthy(self):
        """The controller's remediation-loop role, one pass: find gang
        members whose nodes' PUBLISHED slices report unhealthy silicon,
        mark the gang degraded, pick spares (filtered on published slice
        health, matched by the slot they are cabled at), and run the
        coordinated remediation.  Returns the new GangStatus; updates the
        member list `launch()` uses."""
        from tpudra.controller.gang import (
            GangMember,
            published_slice_health,
            select_healthy_spares,
        )

        members = self._members or self.members()
        health = published_slice_health(self.kube)
        sick = [
            m for m in members
            if m.node in health and not health[m.node].healthy
        ]
        if not sick:
            raise RuntimeError("no member node reports unhealthy slices")
        self.gangs.mark_degraded(
            self.config.domain_name,
            [m.claim_uid for m in sick],
            reason="published-slice-health",
        )
        member_nodes = {m.node for m in members}
        healthy_spares = set(
            select_healthy_spares(
                self.kube, sorted(self.spare_slot), exclude=member_nodes
            )
        )
        replacements: dict[str, GangMember] = {}
        claims: dict[str, dict] = {}
        for m in sick:
            slot = members.index(m)
            spare = next(
                (
                    name
                    for name, s in sorted(self.spare_slot.items())
                    if s == slot and name in healthy_spares
                ),
                None,
            )
            if spare is None:
                raise RuntimeError(
                    f"no healthy spare cabled at slot {slot} for {m.node}"
                )
            replacement = GangMember(
                node=spare,
                claim_uid=f"{self.domain_uid}-r{slot}",
                namespace=self.config.namespace,
            )
            replacements[m.claim_uid] = replacement
        target = [replacements.get(m.claim_uid, m) for m in members]
        new_uids = {r.claim_uid for r in replacements.values()}
        for m in target:
            claims[m.claim_uid] = make_channel_claim(
                m.claim_uid, m.node, self.domain_uid,
                namespace=self.config.namespace,
            )
            if m.claim_uid in new_uids:
                # Replacement claims are new API objects; the surviving
                # members' claims were created at reserve().
                self.kube.create(
                    gvr.RESOURCE_CLAIMS,
                    claims[m.claim_uid],
                    self.config.namespace,
                )
        status = self.gangs.remediate(
            self.config.domain_name, replacements, claims
        )
        self._members = list(status.members)
        self.grant = status
        return status

    # -------------------------------------------------------------- probes

    def bound_claim_count(self) -> int:
        """Gang-member claims currently bound across every node's plugin
        checkpoint — the rollback assertions' "zero bound claims"."""
        uids = {m.claim_uid for m in (self._members or self.members())}
        n = 0
        for d in self.drivers.values():
            n += sum(1 for uid in d.state.prepared_claim_uids() if uid in uids)
        return n

    def cdi_leak_count(self) -> int:
        """Claim CDI spec files present across every node — zero after a
        rollback/release (the "zero CDI leaks" assertion)."""
        return sum(
            len(d.state._cdi.list_claim_uids()) for d in self.drivers.values()
        )

    # --------------------------------------------------------------- launch

    def _grant_env(self, node: str, claim_uid: str) -> dict[str, str]:
        """The env a container consuming this claim would see: the CDI
        spec's claim-wide containerEdits env, with mount containerPaths
        rewritten to their hostPaths (what the runtime's bind mount does)."""
        driver = self.drivers[node]
        spec = driver.state._cdi.read_claim_spec(claim_uid)
        if spec is None:
            raise RuntimeError(f"no CDI spec for {claim_uid} on {node}")
        edits = spec.get("containerEdits", {})
        mount_map = {
            m["containerPath"]: m["hostPath"] for m in edits.get("mounts", [])
        }
        env: dict[str, str] = {}
        for kv in edits.get("env", []):
            k, _, v = kv.partition("=")
            env[k] = mount_map.get(v, v)
        return env

    def launch(
        self,
        kill_rank: Optional[int] = None,
        deadline_s: Optional[float] = None,
    ) -> list[RankResult]:
        """One OS process per granted rank; returns per-rank results.

        ``kill_rank`` SIGKILLs that rank shortly after spawn (the
        dead-worker failure path); survivors blocked in the gang barrier
        are killed at the deadline and report nonzero."""
        if self.grant is None:
            raise RuntimeError("reserve() before launch()")
        from tpudra.cddaemon.coordproxy import CoordinatorProxy

        deadline_s = deadline_s or self.config.launch_deadline_s
        host0 = self._members[0]
        domain_dir = self.drivers[host0.node].cd_manager.domain_dir(
            self.domain_uid
        )
        coord_port = _free_port()
        # Peers dial the REAL daemon coordinator proxy; it forwards to the
        # registration host 0 writes into the shared domain dir.
        self._proxy = CoordinatorProxy(
            port=0, registration_dir=domain_dir, host="127.0.0.1"
        )
        self._proxy.start()

        self._procs = []
        logs: list[str] = []
        for rank, member in enumerate(self._members):
            env = self._grant_env(member.node, member.claim_uid)
            chips_block = 1
            for v in env.get("TPU_CHIPS_PER_HOST_BOUNDS", "1").split(","):
                chips_block *= int(v)
            sim_coord = (
                f"127.0.0.1:{coord_port}"
                if rank == 0
                else f"127.0.0.1:{self._proxy.bound_port}"
            )
            full_env = {
                # The grant is the contract; the process env starts from it.
                **env,
                # Sim platform shims (module docstring).
                "JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": (
                    f"--xla_force_host_platform_device_count={chips_block}"
                ),
                "TPUDRA_SIM_COORDINATOR": sim_coord,
                # Process plumbing.
                "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
                "PATH": os.environ.get("PATH", ""),
                "HOME": os.environ.get("HOME", "/root"),
                **self.config.extra_env,
            }
            if trace.enabled():
                # The rank process appends its spans to the SAME trace log
                # (a shared-log absolute path), parented on the grant env's
                # TPUDRA_TRACEPARENT — the process-boundary half of the
                # controller→plugin→rank chain.
                full_env.setdefault(trace.ENV_TRACE, "1")
                full_env.setdefault(
                    trace.ENV_TRACE_LOG, os.path.abspath(trace.log_path())
                )
            log_path = os.path.join(self._tmp.name, f"rank-{rank}.log")
            logs.append(log_path)
            with open(log_path, "w") as out:
                self._procs.append(
                    subprocess.Popen(
                        [sys.executable, "-m", "tpudra.sim.multihost", "--worker"],
                        env=full_env,
                        stdout=out,
                        stderr=subprocess.STDOUT,
                        text=True,
                    )
                )
        if kill_rank is not None:
            # Mid-gang death: the victim dies while the gang is forming
            # (well inside rendezvous — a full healthy run takes seconds).
            time.sleep(0.3)
            self._procs[kill_rank].send_signal(signal.SIGKILL)

        deadline = time.monotonic() + deadline_s
        for proc in self._procs:
            remaining = max(0.1, deadline - time.monotonic())
            try:
                proc.wait(timeout=remaining)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=10)
        results = []
        for rank, (proc, log_path) in enumerate(zip(self._procs, logs)):
            try:
                with open(log_path) as f:
                    output = f.read()
            except OSError:
                output = ""
            results.append(
                RankResult(rank=rank, returncode=proc.returncode, output=output)
            )
        self._procs = []
        self._proxy.stop()
        self._proxy = None
        return results

    def _kill_procs(self) -> None:
        for proc in self._procs:
            if proc.poll() is None:
                proc.kill()
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    pass
        self._procs = []


def _free_port() -> int:
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ------------------------------------------------------------------ worker


def _worker_main() -> int:
    """One rank of the gang, driven by the grant env alone."""
    from tpudra.workload.envspec import ClaimEnv

    env = ClaimEnv.from_environ()
    # The libtpu worker-bootstrap contract must be complete BEFORE jax
    # loads (libtpu reads the real process env) — assert, then re-export.
    assert env.num_hosts > 1, f"not a multi-host grant: {env.num_hosts}"
    assert env.worker_id == env.host_index, (env.worker_id, env.host_index)
    assert len(env.worker_hostnames) == env.num_hosts, env.worker_hostnames
    assert env.skip_mds_query, "grant did not set TPU_SKIP_MDS_QUERY"
    assert env.host_bounds and env.chips_per_host_bounds, "no host bounds"
    assert env.mesh_shape, "grant carried no TPUDRA_MESH_SHAPE"
    assert env.host_coords, "grant carried no TPUDRA_HOST_COORDS"
    assert all(
        c < m for c, m in zip(env.host_coords, env.mesh_shape)
    ), (env.host_coords, env.mesh_shape)
    assert env.coordinator, "grant injected no coordinator"
    env.apply_libtpu_env()
    # Sim-only address override (the stable daemon DNS name does not
    # resolve on one machine); the relay itself stays real — peers reach
    # host 0 through the daemon's coordinator proxy.
    env.coordinator = os.environ.get("TPUDRA_SIM_COORDINATOR") or env.coordinator
    # The rank's span parents on the grant env's traceparent: the claim's
    # CDI environment alone connects this process to the member bind that
    # granted it (the last hop of the controller→plugin→rank chain).
    with trace.start_span(
        "rank.worker",
        parent=env.traceparent or None,
        attrs={"host": env.host_index, "num_hosts": env.num_hosts},
    ):
        return _worker_body(env)


def _worker_body(env) -> int:
    env.initialize_distributed()

    import jax

    n_slice = env.slice_device_count
    devices = jax.devices()
    local = jax.local_devices()
    assert jax.process_count() == env.num_hosts, jax.process_count()
    # THE topology assertion: the runtime sees exactly the granted slice —
    # every chip of the mesh, this host fielding exactly its chip block.
    assert len(devices) == n_slice, (len(devices), n_slice)
    chips_block = 1
    for v in env.chips_per_host_bounds.split(","):
        chips_block *= int(v)
    assert len(local) == chips_block, (len(local), chips_block)

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from jax.experimental import multihost_utils

    mesh = Mesh(np.asarray(devices).reshape(-1), ("dp",))
    cols = 8
    block = jnp.ones((len(local), cols), jnp.float32) * (env.host_index + 1)
    garr = multihost_utils.host_local_array_to_global_array(
        block, mesh, P("dp", None)
    )
    total = jax.jit(lambda a: a.sum(), out_shardings=NamedSharding(mesh, P()))(
        garr
    )
    val = float(total.addressable_data(0))
    expect = cols * chips_block * sum(
        r + 1 for r in range(env.num_hosts)
    )
    assert val == expect, (val, expect)
    print(
        f"RESULT gang-psum: {val} host {env.host_index} "
        f"devices {len(devices)} mesh {','.join(map(str, env.mesh_shape))}",
        flush=True,
    )
    return 0


# --------------------------------------------------------------------- CLI


def run_e2e(
    num_hosts: int, kill_rank: Optional[int] = None, deadline_s: float = 120.0
) -> dict:
    """The whole loop as one call (the `make e2e-multihost` CLI body and
    tests/test_multihost.py's engine).  Returns a JSON-able summary."""
    cfg = MultiHostConfig(num_hosts=num_hosts, launch_deadline_s=deadline_s)
    out: dict = {"num_hosts": num_hosts, "kill_rank": kill_rank}
    with MultiHostGang(cfg) as gang:
        t0 = time.perf_counter()
        gang.reserve()
        out["gang_bind_ms"] = round((time.perf_counter() - t0) * 1000.0, 2)
        out["bound_claims"] = gang.bound_claim_count()
        results = gang.launch(kill_rank=kill_rank)
        out["ranks"] = [
            {"rank": r.rank, "rc": r.returncode, "tail": r.output[-400:]}
            for r in results
        ]
        out["launch_ok"] = all(r.ok for r in results)
        gang.release()
        out["bound_claims_after_release"] = gang.bound_claim_count()
        out["cdi_leaks_after_release"] = gang.cdi_leak_count()
    if kill_rank is None:
        out["ok"] = (
            out["launch_ok"]
            and out["bound_claims"] == num_hosts
            and out["bound_claims_after_release"] == 0
            and out["cdi_leaks_after_release"] == 0
        )
    else:
        out["ok"] = (
            not out["launch_ok"]
            and out["bound_claims_after_release"] == 0
            and out["cdi_leaks_after_release"] == 0
        )
    return out


def main(argv: Optional[list[str]] = None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "--worker":
        return _worker_main()
    parser = argparse.ArgumentParser(
        description="Multi-host gang harness: ComputeDomain claim → gang "
        "reservation → one OS process per node → jax.distributed psum "
        "(docs/multi-host.md)."
    )
    parser.add_argument("--hosts", type=int, default=4)
    parser.add_argument(
        "--kill-rank",
        type=int,
        default=None,
        help="kill this rank mid-gang and assert rollback instead",
    )
    parser.add_argument("--deadline-s", type=float, default=120.0)
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    out = run_e2e(args.hosts, kill_rank=args.kill_rank, deadline_s=args.deadline_s)
    print(json.dumps(out, indent=2))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())

"""Scheduler + kubelet + pod-runtime simulation for the hermetic cluster.

One ClusterSim process stands in for everything between the apiserver and
the driver that a real cluster provides (SURVEY.md §4.3's "kind + mock"
target):

- a DRA-aware scheduler (tpudra/sim/sched.py) that instantiates
  ResourceClaims from ResourceClaimTemplates, performs the
  extendedResourceName translation, and picks a node where every claim fits;
- per-node kubelet behavior: NodePrepareResources/NodeUnprepareResources
  over the driver's real gRPC unix socket (retrying retryable errors the way
  kubelet holds a pod in ContainerCreating — reference device_state.go:499);
- a container runtime: containers run as local processes with the CDI
  spec's environment applied (what containerd's CDI support does with the
  transient spec files, reference cdi.go:194-304), logs captured to pod
  annotations, exec readiness probes honored;
- minimal DaemonSet/Deployment controllers so the pods the ComputeDomain
  controller and the sharing managers stamp out actually run.

Known binary names map to ``python -m`` module invocations, so the pods the
controller renders ("compute-domain-daemon run") execute the real binaries.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from tpudra import lockwitness
from tpudra.kube import gvr
from tpudra.kube.errors import ApiError, Conflict, NotFound
from tpudra.sim.sched import (
    EXTENDED_RESOURCE_CLASSES,
    InsufficientResources,
    Scheduler,
)

logger = logging.getLogger(__name__)

LOG_ANNOTATION_PREFIX = "sim.tpu.google.com/log-"
EVENT_ANNOTATION = "sim.tpu.google.com/event"
DEVICE_NODES_ENV = "SIM_CDI_DEVICE_NODES"

# Console-script name -> python module (the image's entry points).
BINARY_MODULES = {
    "tpu-kubelet-plugin": "tpudra.plugin.main",
    "compute-domain-kubelet-plugin": "tpudra.cdplugin.main",
    "compute-domain-controller": "tpudra.controller.main",
    "compute-domain-daemon": "tpudra.cddaemon.main",
    "tpudra-webhook": "tpudra.webhook.main",
    "tpu-mp-control-daemon": "tpudra.mpdaemon",
}

LOG_CAP = 8192


@dataclass
class NodeConfig:
    """One simulated node: where its driver sockets and CDI roots live, and
    the node-level environment injected into every container it runs (the
    analog of node-scoped config like /etc/hosts and the TPU metadata
    server)."""

    name: str
    drivers: dict[str, str] = field(default_factory=dict)  # driver -> socket
    cdi_roots: list[str] = field(default_factory=list)
    env: dict[str, str] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, d: dict) -> "NodeConfig":
        return cls(
            name=d["name"],
            drivers=dict(d.get("drivers", {})),
            cdi_roots=list(d.get("cdi_roots", [])),
            env={k: str(v) for k, v in d.get("env", {}).items()},
        )


class _Container:
    def __init__(self, spec: dict, env: dict, workdir: str):
        self.spec = spec
        self.name = spec["name"]
        self.env = env
        self.workdir = workdir
        self.proc: Optional[subprocess.Popen] = None
        self.log_path = os.path.join(workdir, f"{self.name}.log")
        self.ready = False
        self.restarts = 0
        self.last_exit: Optional[int] = None
        self.next_start = 0.0  # restart backoff deadline
        self.next_probe = 0.0
        self.next_log_sync = 0.0
        self.synced_len = -1

    def running(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def log_tail(self) -> str:
        try:
            with open(self.log_path) as f:
                data = f.read()
            return data[-LOG_CAP:]
        except OSError:
            return ""


class _PodRun:
    def __init__(self, pod: dict, node: NodeConfig):
        self.uid = pod["metadata"]["uid"]
        self.namespace = pod["metadata"]["namespace"]
        self.name = pod["metadata"]["name"]
        self.pod = pod
        self.node = node
        self.claims: list[dict] = []  # resolved ResourceClaim objects
        self.generated_claims: list[tuple[str, str]] = []  # (ns, name) we created
        self.prepared = False
        self.containers: list[_Container] = []
        self.workdir = tempfile.mkdtemp(prefix=f"pod-{self.name}-")
        self.next_prepare = 0.0
        self.last_status: Optional[tuple] = None
        # Async prepare: the gRPC call can legitimately block on work only
        # this sim performs (e.g. the MP control-daemon Deployment the
        # plugin stamps and then waits on), so it must not run on the
        # reconcile loop's thread.
        self.prepare_thread: Optional[threading.Thread] = None
        # ("ok", "", prepared_uids) | ("err", message, prepared_uids) —
        # prepared_uids always lists the claims the attempt did prepare.
        self.prepare_result: Optional[tuple] = None


def _resolve_field_ref(path: str, pod: dict) -> str:
    md = pod["metadata"]
    return {
        "metadata.name": md["name"],
        "metadata.namespace": md["namespace"],
        "metadata.uid": md.get("uid", ""),
        "spec.nodeName": pod["spec"].get("nodeName", ""),
        "status.podIP": "127.0.0.1",
    }.get(path, "")


def _container_env(container: dict, pod: dict) -> dict:
    env = {}
    for e in container.get("env", []):
        if "value" in e:
            env[e["name"]] = str(e["value"])
        elif "valueFrom" in e and "fieldRef" in e["valueFrom"]:
            env[e["name"]] = _resolve_field_ref(
                e["valueFrom"]["fieldRef"].get("fieldPath", ""), pod
            )
    return env


def rewrite_command(argv: list[str]) -> list[str]:
    """Map console-script names to `python -m` (the hermetic image)."""
    if not argv:
        return argv
    head, rest = argv[0], argv[1:]
    if head in BINARY_MODULES:
        return [sys.executable, "-m", BINARY_MODULES[head], *rest]
    if os.path.basename(head) in ("python", "python3"):
        return [sys.executable, *rest]
    return argv


class ClusterSim:
    """The reconcile loop tying scheduler, kubelet, and pod runtime together."""

    def __init__(self, kube, nodes: list[NodeConfig], base_env: Optional[dict] = None):
        self._kube = kube
        self._nodes = {n.name: n for n in nodes}
        self._base_env = dict(base_env or {})
        self._sched = Scheduler(kube)
        self._pods: dict[str, _PodRun] = {}
        # claim uid -> set of pod uids that required it (shared-claim refcount)
        self._claim_users: dict[str, set[str]] = {}
        self._prepared_claims: set[str] = set()
        self._dra_clients: dict[tuple[str, str], object] = {}
        # Pod prepare/unprepare threads share the client cache with the
        # main loop; the get-or-create below is a classic TOCTOU without
        # a guard (tpudra-racegraph pins the lockset).
        self._dra_lock = lockwitness.make_lock("kubelet.dra_clients")
        self._stop = threading.Event()

    # ----------------------------------------------------------- plumbing

    def _dra(self, node: NodeConfig, driver: str):
        from tpudra.plugin.grpcserver import DRAClient

        key = (node.name, driver)
        cli = self._dra_clients.get(key)
        if cli is None:
            sock = node.drivers.get(driver)
            if not sock:
                raise RuntimeError(f"node {node.name} has no driver {driver}")
            # Construct outside the lock (the client may dial its socket);
            # setdefault under it keeps one canonical client per key when
            # two pod threads race the miss.
            cli = DRAClient(sock)
            with self._dra_lock:
                cli = self._dra_clients.setdefault(key, cli)
        return cli

    def _annotate(self, pod_run: _PodRun, annotations: dict) -> None:
        try:
            self._kube.patch(
                gvr.PODS,
                pod_run.name,
                {"metadata": {"annotations": annotations}},
                pod_run.namespace,
            )
        except (NotFound, ApiError):
            pass

    # --------------------------------------------------------------- run

    def run(self, stop: Optional[threading.Event] = None, tick: float = 0.15) -> None:
        stop = stop or self._stop
        self._adopt_existing()
        while not stop.is_set():
            try:
                self.step()
            except Exception:  # noqa: BLE001 — the loop must survive
                logger.exception("sim tick failed")
            stop.wait(tick)
        self._teardown()

    def stop(self) -> None:
        self._stop.set()

    def step(self) -> None:
        node_labels = self._node_labels()
        self._sync_daemonsets(node_labels)
        self._sync_deployments()
        pods = self._kube.list(gvr.PODS).get("items", [])
        by_uid = {p["metadata"]["uid"]: p for p in pods}
        self._schedule(pods, node_labels)
        self._kubelet(pods)
        self._reap(by_uid)

    def _adopt_existing(self) -> None:
        """Rebuild the allocation ledger from claims already in the
        apiserver (sim restart; the analog of scheduler cache rebuild)."""
        for claim in self._kube.list(gvr.RESOURCE_CLAIMS).get("items", []):
            results = (
                claim.get("status", {})
                .get("allocation", {})
                .get("devices", {})
                .get("results", [])
            )
            if results:
                self._sched.adopt(claim)

    # -------------------------------------------- DaemonSet / Deployment

    def _node_labels(self) -> dict[str, dict]:
        labels = {}
        for n in self._kube.list(gvr.NODES).get("items", []):
            labels[n["metadata"]["name"]] = n["metadata"].get("labels", {})
        return labels

    def _ensure_pod(self, name: str, namespace: str, template: dict,
                    node_name: str, owner: dict) -> None:
        spec = json.loads(json.dumps(template.get("spec", {})))
        spec["nodeName"] = node_name
        pod = {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": name,
                "namespace": namespace,
                "labels": dict(template.get("metadata", {}).get("labels", {})),
                "ownerReferences": [owner],
            },
            "spec": spec,
        }
        try:
            self._kube.create(gvr.PODS, pod, namespace)
        except (Conflict, ApiError) as e:
            if "exists" not in str(e).lower():
                raise

    def _owned_pods(self, owner_uid: str) -> list[dict]:
        return [
            p
            for p in self._kube.list(gvr.PODS).get("items", [])
            if any(
                o.get("uid") == owner_uid
                for o in p["metadata"].get("ownerReferences", [])
            )
        ]

    @staticmethod
    def _node_matches(node_labels: dict, node: str, selector: dict) -> bool:
        return all(
            node_labels.get(node, {}).get(k) == v for k, v in selector.items()
        )

    def _sync_daemonsets(self, node_labels: dict) -> None:
        for ds in self._kube.list(gvr.DAEMONSETS).get("items", []):
            md, tmpl = ds["metadata"], ds["spec"]["template"]
            selector = tmpl["spec"].get("nodeSelector", {})
            want_nodes = {
                n
                for n in self._nodes
                if self._node_matches(node_labels, n, selector)
            }
            owner = {
                "apiVersion": "apps/v1", "kind": "DaemonSet",
                "name": md["name"], "uid": md["uid"],
            }
            have = {p["spec"].get("nodeName"): p for p in self._owned_pods(md["uid"])}
            for n in want_nodes - set(have):
                self._ensure_pod(
                    f"{md['name']}-{n}", md["namespace"], tmpl, n, owner
                )
            for n, pod in have.items():
                if n not in want_nodes:
                    self._delete_pod(pod)
            # numberReady lets kubectl-level waits observe rollout state.
            ready = sum(
                1
                for p in have.values()
                if any(
                    c.get("type") == "Ready" and c.get("status") == "True"
                    for c in p.get("status", {}).get("conditions", [])
                )
            )
            status = {
                "desiredNumberScheduled": len(want_nodes),
                "numberReady": ready,
            }
            if ds.get("status", {}) != status:
                ds = dict(ds, status=status)
                try:
                    self._kube.update_status(gvr.DAEMONSETS, ds, md["namespace"])
                except (Conflict, NotFound):
                    pass

    def _sync_deployments(self) -> None:
        for dep in self._kube.list(gvr.DEPLOYMENTS).get("items", []):
            md, tmpl = dep["metadata"], dep["spec"]["template"]
            node_name = tmpl["spec"].get("nodeName", "")
            if node_name not in self._nodes:
                continue
            owner = {
                "apiVersion": "apps/v1", "kind": "Deployment",
                "name": md["name"], "uid": md["uid"],
            }
            have = self._owned_pods(md["uid"])
            replicas = int(dep["spec"].get("replicas", 1))
            have_names = {p["metadata"]["name"] for p in have}
            want_names = {f"{md['name']}-{i}" for i in range(replicas)}
            for name in sorted(want_names - have_names):
                self._ensure_pod(name, md["namespace"], tmpl, node_name, owner)
            for p in have:
                if p["metadata"]["name"] not in want_names:  # scale-down
                    self._delete_pod(p)
            ready = sum(
                1
                for p in have
                if any(
                    c.get("type") == "Ready" and c.get("status") == "True"
                    for c in p.get("status", {}).get("conditions", [])
                )
            )
            status = {"replicas": len(have), "readyReplicas": ready}
            if dep.get("status", {}) != status:
                dep = dict(dep, status=status)
                try:
                    self._kube.update_status(gvr.DEPLOYMENTS, dep, md["namespace"])
                except (Conflict, NotFound):
                    pass

    # ---------------------------------------------------------- scheduler

    def _claim_entries(self, pod: dict) -> list[dict]:
        return pod["spec"].get("resourceClaims", [])

    def _extended_limits(self, pod: dict) -> dict[str, int]:
        limits: dict[str, int] = {}
        for c in pod["spec"].get("containers", []):
            for k, v in c.get("resources", {}).get("limits", {}).items():
                if k in EXTENDED_RESOURCE_CLASSES:
                    limits[k] = limits.get(k, 0) + int(v)
        return limits

    def _resolve_claims(self, pod: dict, node: str) -> Optional[list[dict]]:
        """Ensure every claim the pod references exists and is allocated on
        ``node``.  Returns the claim objects, or None when allocation cannot
        be satisfied (caller tries another node / retries).  Rolls back
        claims allocated in this call on failure."""
        md = pod["metadata"]
        ns, owner = md["namespace"], {
            "apiVersion": "v1", "kind": "Pod", "name": md["name"], "uid": md["uid"],
        }
        resolved: list[dict] = []
        fresh: list[dict] = []  # claims this attempt created (safe to delete)
        fresh_status: list[dict] = []  # user claims this attempt allocated
        try:
            for entry in self._claim_entries(pod):
                if entry.get("resourceClaimName"):
                    claim = self._kube.get(
                        gvr.RESOURCE_CLAIMS, entry["resourceClaimName"], ns
                    )
                    results = (
                        claim.get("status", {})
                        .get("allocation", {})
                        .get("devices", {})
                        .get("results", [])
                    )
                    if not results:
                        # Allocate a user-authored standalone claim in place.
                        rct_shape = {"spec": {"spec": claim["spec"]}}
                        alloc = self._sched.allocate(
                            rct_shape, claim["metadata"]["uid"], ns,
                            claim["metadata"]["name"], create=False, node=node,
                        )
                        claim["status"] = alloc["status"]
                        claim = self._kube.update_status(gvr.RESOURCE_CLAIMS, claim, ns)
                        fresh_status.append(claim)
                    resolved.append(claim)
                elif entry.get("resourceClaimTemplateName"):
                    cname = f"{md['name']}-{entry['name']}"
                    try:
                        claim = self._kube.get(gvr.RESOURCE_CLAIMS, cname, ns)
                    except NotFound:
                        rct = self._kube.get(
                            gvr.RESOURCE_CLAIM_TEMPLATES,
                            entry["resourceClaimTemplateName"],
                            ns,
                        )
                        claim = self._sched.allocate(
                            rct, f"{md['uid']}-{entry['name']}", ns, cname,
                            node=node, owner=owner,
                        )
                        fresh.append(claim)
                    resolved.append(claim)
            limits = self._extended_limits(pod)
            if limits:
                cname = f"{md['name']}-extended-resources"
                try:
                    claim = self._kube.get(gvr.RESOURCE_CLAIMS, cname, ns)
                except NotFound:
                    claim = self._sched.allocate_extended(
                        limits, f"{md['uid']}-extres", ns, md["name"],
                        node=node, owner=owner,
                    )
                    fresh.append(claim)
                resolved.append(claim)
        except (InsufficientResources, NotFound) as e:
            # Claims this attempt created are deleted; a user-authored
            # standalone claim only has the status this attempt wrote
            # cleared — the object is the user's, not ours.
            for claim in fresh:
                self._sched.release(claim)
                try:
                    self._kube.delete(
                        gvr.RESOURCE_CLAIMS, claim["metadata"]["name"], ns
                    )
                except NotFound:
                    pass
            for claim in fresh_status:
                self._sched.release(claim)
                claim["status"] = {}
                try:
                    self._kube.update_status(gvr.RESOURCE_CLAIMS, claim, ns)
                except (Conflict, NotFound):
                    pass
            logger.debug("pod %s/%s does not fit on %s: %s", ns, md["name"], node, e)
            return None
        # Reserve every resolved claim for this pod (status.reservedFor),
        # as the real scheduler's claim controller does on allocation — the
        # CD plugin's worker-hostnames policy resolves the consuming pod
        # through it (cdplugin/state.py:_consuming_pod).
        pod_ref = {"resource": "pods", "name": md["name"], "uid": md["uid"]}
        for i, claim in enumerate(resolved):
            reserved = claim.setdefault("status", {}).setdefault("reservedFor", [])
            if not any(r.get("uid") == md["uid"] for r in reserved):
                reserved.append(pod_ref)
                try:
                    resolved[i] = self._kube.update_status(
                        gvr.RESOURCE_CLAIMS, claim, ns
                    )
                except (Conflict, NotFound):
                    pass  # concurrent writer/deleter; reservation is best-effort
        return resolved

    def _schedule(self, pods: list[dict], node_labels: dict) -> None:
        for pod in pods:
            md = pod["metadata"]
            if md.get("deletionTimestamp") or pod["spec"].get("nodeName"):
                continue
            if pod.get("status", {}).get("phase") in ("Succeeded", "Failed"):
                continue
            selector = pod["spec"].get("nodeSelector", {})
            for node in self._nodes:
                if not self._node_matches(node_labels, node, selector):
                    continue
                claims = self._resolve_claims(pod, node)
                if claims is None:
                    continue
                pod["spec"]["nodeName"] = node
                try:
                    self._kube.update(gvr.PODS, pod, md["namespace"])
                except (Conflict, NotFound):
                    # Racing update: the claims persist in the apiserver and
                    # stay in the ledger; the next tick re-resolves them by
                    # name, so nothing is released here.
                    pass
                break

    # ------------------------------------------------------------ kubelet

    def _kubelet(self, pods: list[dict]) -> None:
        for pod in pods:
            md = pod["metadata"]
            node = self._nodes.get(pod["spec"].get("nodeName", ""))
            if node is None:
                continue
            run = self._pods.get(md["uid"])
            if run is None:
                if md.get("deletionTimestamp"):
                    continue
                run = _PodRun(pod, node)
                self._pods[md["uid"]] = run
            run.pod = pod
            if md.get("deletionTimestamp"):
                self._shutdown_pod(run)
                continue
            if not run.prepared:
                self._prepare_pod(run)
            if run.prepared:
                self._run_containers(run)
            self._report_status(run)

    def _prepare_pod(self, run: _PodRun) -> None:
        now = time.monotonic()
        if now < run.next_prepare:
            return
        run.next_prepare = now + 1.0
        if not run.claims:
            claims = self._resolve_claims(run.pod, run.node.name)
            if claims is None:
                return
            run.claims = claims
            run.generated_claims = [
                (c["metadata"]["namespace"], c["metadata"]["name"])
                for c in claims
                if any(
                    o.get("uid") == run.uid
                    for o in c["metadata"].get("ownerReferences", [])
                )
            ]
        for claim in run.claims:
            uid = claim["metadata"]["uid"]
            self._claim_users.setdefault(uid, set()).add(run.uid)

        # Harvest a finished async prepare.
        if run.prepare_thread is not None and not run.prepare_thread.is_alive():
            run.prepare_thread = None
            kind, msg, done = run.prepare_result
            run.prepare_result = None
            # Claims prepared before any failure stay prepared (the driver
            # is idempotent); only the pod-level gate retries.
            self._prepared_claims.update(done)
            if kind == "ok":
                run.prepared = True
                self._annotate(run, {EVENT_ANNOTATION: "prepared"})
            else:
                logger.info("prepare pending for pod %s: %s", run.name, msg[:200])
                self._annotate(run, {EVENT_ANNOTATION: f"prepare: {msg[:500]}"})
            return
        if run.prepare_thread is not None:
            return

        pending = [
            c for c in run.claims
            if c["metadata"]["uid"] not in self._prepared_claims
        ]
        if not pending:
            run.prepared = True
            self._annotate(run, {EVENT_ANNOTATION: "prepared"})
            return

        def do_prepare() -> None:
            # Any retryable failure keeps the pod unprepared (kubelet's
            # ContainerCreating retry loop).
            done: list[str] = []
            try:
                for claim in pending:
                    uid = claim["metadata"]["uid"]
                    drivers = {
                        r["driver"]
                        for r in claim["status"]["allocation"]["devices"]["results"]
                    }
                    for driver in drivers:
                        resp = self._dra(run.node, driver).prepare([claim])
                        result = resp["claims"].get(uid, {})
                        if result.get("error"):
                            raise RuntimeError(result["error"])
                    done.append(uid)
            except Exception as e:  # noqa: BLE001 — retried next tick
                run.prepare_result = ("err", str(e), done)
                return
            run.prepare_result = ("ok", "", done)

        run.prepare_thread = threading.Thread(target=do_prepare, daemon=True)
        run.prepare_thread.start()

    def _cdi_env(self, run: _PodRun) -> dict:
        """Apply the transient CDI specs of the pod's claims: merge every
        env edit and surface injected device nodes for assertions.

        Mount translation: pod "containers" here are host processes, so a
        bind mount is an identity map — any env value naming a mounted
        containerPath is rewritten to its hostPath (e.g. TPUDRA_CD_DIR →
        the per-domain dir the plugin created), exactly what the runtime's
        real bind mount would make true inside the container."""
        env: dict[str, str] = {}
        dev_nodes: list[str] = []
        mounts: dict[str, str] = {}  # containerPath -> hostPath
        uids = {c["metadata"]["uid"] for c in run.claims}
        for root in run.node.cdi_roots:
            try:
                files = os.listdir(root)
            except OSError:
                continue
            for fn in files:
                if not any(uid in fn for uid in uids):
                    continue
                try:
                    with open(os.path.join(root, fn)) as f:
                        spec = json.load(f)
                except (OSError, ValueError):
                    continue
                all_edits = [spec.get("containerEdits", {})] + [
                    dev.get("containerEdits", {}) for dev in spec.get("devices", [])
                ]
                for edits in all_edits:
                    for e in edits.get("env", []):
                        k, _, v = e.partition("=")
                        env[k] = v
                    for n in edits.get("deviceNodes", []):
                        dev_nodes.append(n["path"])
                    for mt in edits.get("mounts", []):
                        mounts[mt["containerPath"]] = mt["hostPath"]
        for k, v in env.items():
            for cpath, hpath in mounts.items():
                if v == cpath or v.startswith(cpath + "/"):
                    env[k] = hpath + v[len(cpath):]
                    break
        if dev_nodes:
            env[DEVICE_NODES_ENV] = ",".join(sorted(dev_nodes))
        return env

    @staticmethod
    def _mock_jax_env(env: dict) -> dict:
        """With TPUDRA_SIM_JAX_CPU=1 (node env), a claimed pod's jax sees
        exactly its granted chips as CPU devices — the in-pod observable
        the reference asserts with nvidia-smi, minus the silicon.  The
        device count flows from the CDI-injected TPU_VISIBLE_DEVICES, so a
        wrong grant fails the pod's own assertion."""
        if env.get("TPUDRA_SIM_JAX_CPU") != "1":
            return {}
        visible = env.get("TPU_VISIBLE_DEVICES", "")
        if not visible:
            return {}
        n = len(visible.split(","))
        return {
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": f"--xla_force_host_platform_device_count={n}",
        }

    def _start_container(self, run: _PodRun, c: _Container) -> None:
        argv = rewrite_command(
            list(c.spec.get("command", [])) + list(c.spec.get("args", []))
        )
        if not argv:
            argv = [sys.executable, "-c", "pass"]
        with open(c.log_path, "a") as out:
            c.proc = subprocess.Popen(
                argv, env=c.env, cwd=run.workdir,
                stdout=out, stderr=subprocess.STDOUT,
                start_new_session=True,
            )

    def _run_containers(self, run: _PodRun) -> None:
        if not run.containers:
            cdi_env = self._cdi_env(run)
            for cspec in run.pod["spec"].get("containers", []):
                env = {
                    "PATH": os.environ.get("PATH", "/usr/bin:/bin"),
                    "HOME": run.workdir,
                    "PYTHONPATH": os.environ.get("PYTHONPATH", ""),
                    "PYTHONUNBUFFERED": "1",
                }
                env.update(self._base_env)
                env.update(run.node.env)
                env.update(cdi_env)
                env.update(self._mock_jax_env(env))
                env.update(_container_env(cspec, run.pod))
                c = _Container(cspec, env, run.workdir)
                run.containers.append(c)
                self._start_container(run, c)
        restart_always = run.pod["spec"].get("restartPolicy", "Always") == "Always"
        now = time.monotonic()
        for c in run.containers:
            if c.running() and now >= c.next_log_sync:
                # Running containers sync logs periodically so `kubectl
                # logs` works mid-run (exited ones sync below).  Track the
                # uncapped file size: the capped tail's length pins at
                # LOG_CAP and would freeze the sync.
                c.next_log_sync = now + 2.0
                try:
                    size = os.path.getsize(c.log_path)
                except OSError:
                    size = 0
                if size != c.synced_len:
                    c.synced_len = size
                    tail = c.log_tail()
                    if tail:
                        self._annotate(
                            run, {LOG_ANNOTATION_PREFIX + c.name: tail}
                        )
            if not c.running() and c.proc is not None:
                rc = c.proc.poll()
                if c.last_exit is None or c.last_exit != rc:
                    c.last_exit = rc
                    self._annotate(
                        run,
                        {LOG_ANNOTATION_PREFIX + c.name: c.log_tail() or "(empty)"},
                    )
                if restart_always and rc is not None:
                    if c.next_start == 0.0:
                        c.next_start = now + 1.0
                    elif now >= c.next_start:
                        c.restarts += 1
                        c.next_start = 0.0
                        c.last_exit = None
                        self._start_container(run, c)
            self._probe(c, now)

    def _probe(self, c: _Container, now: float) -> None:
        probe = c.spec.get("readinessProbe", {})
        exec_cmd = probe.get("exec", {}).get("command")
        if not c.running():
            # A completed (rc 0) container counts ready for Succeeded pods.
            c.ready = c.proc is not None and c.proc.poll() == 0
            return
        if not exec_cmd:
            c.ready = True
            return
        if now < c.next_probe:
            return
        c.next_probe = now + max(1.0, float(probe.get("periodSeconds", 5)))
        try:
            rc = subprocess.run(
                rewrite_command(list(exec_cmd)),
                env=c.env, capture_output=True, timeout=10,
            ).returncode
        except (OSError, subprocess.TimeoutExpired):
            rc = 1
        c.ready = rc == 0

    def _report_status(self, run: _PodRun) -> None:
        if not run.prepared:
            phase, ready = "Pending", False
        else:
            states = [(c.running(), c.proc.poll() if c.proc else None)
                      for c in run.containers]
            if not states:
                phase, ready = "Pending", False
            elif any(r for r, _ in states):
                phase, ready = "Running", all(c.ready for c in run.containers)
            elif all(rc == 0 for _, rc in states):
                phase, ready = "Succeeded", True
            elif run.pod["spec"].get("restartPolicy", "Always") == "Always":
                phase, ready = "Running", False  # crash-looping
            else:
                phase, ready = "Failed", False
        statuses = [
            {
                "name": c.name,
                "ready": c.ready,
                "restartCount": c.restarts,
                "state": (
                    {"running": {}}
                    if c.running()
                    else {"terminated": {"exitCode": c.proc.poll() if c.proc else -1}}
                ),
            }
            for c in run.containers
        ]
        key = (phase, ready, json.dumps(statuses, sort_keys=True))
        if key == run.last_status:
            return
        run.last_status = key
        pod = dict(run.pod)
        pod["status"] = {
            "phase": phase,
            "podIP": "127.0.0.1",
            "conditions": [
                {"type": "Ready", "status": "True" if ready else "False"}
            ],
            "containerStatuses": statuses,
        }
        try:
            self._kube.update_status(gvr.PODS, pod, run.namespace)
        except (Conflict, NotFound):
            run.last_status = None

    # ------------------------------------------------------------ teardown

    def _delete_pod(self, pod: dict) -> None:
        try:
            self._kube.delete(
                gvr.PODS, pod["metadata"]["name"], pod["metadata"]["namespace"]
            )
        except NotFound:
            pass

    def _shutdown_pod(self, run: _PodRun) -> bool:
        """Kill containers, unprepare claims whose last user left, release
        allocations, and delete generated claims — then drop the pod.
        Returns False when shutdown must be deferred because a prepare is
        still in flight: joining here would stall the reconcile loop (and
        deadlock an MP prepare that waits on this loop's Deployment sync),
        so _reap retries next tick until the RPCs self-bound."""
        if run.prepare_thread is not None and run.prepare_thread.is_alive():
            return False
        run.prepare_thread = None
        if run.prepare_result:
            self._prepared_claims.update(run.prepare_result[2])
            run.prepare_result = None
        for c in run.containers:
            if c.running():
                try:
                    os.killpg(os.getpgid(c.proc.pid), signal.SIGTERM)
                except (OSError, ProcessLookupError):
                    pass
        deadline = time.monotonic() + 5
        for c in run.containers:
            if c.proc is None:
                continue
            while c.proc.poll() is None and time.monotonic() < deadline:
                time.sleep(0.05)
            if c.proc.poll() is None:
                try:
                    os.killpg(os.getpgid(c.proc.pid), signal.SIGKILL)
                except (OSError, ProcessLookupError):
                    pass
                c.proc.wait()
        for claim in run.claims:
            uid = claim["metadata"]["uid"]
            users = self._claim_users.get(uid, set())
            users.discard(run.uid)
            if users:
                continue
            self._claim_users.pop(uid, None)
            if uid in self._prepared_claims:
                drivers = {
                    r["driver"]
                    for r in claim["status"]["allocation"]["devices"]["results"]
                }
                for driver in drivers:
                    try:
                        self._dra(run.node, driver).unprepare([claim])
                    except Exception:  # noqa: BLE001
                        logger.exception(
                            "unprepare failed for claim %s", claim["metadata"]["name"]
                        )
                self._prepared_claims.discard(uid)
            self._sched.release(claim)
        for ns, name in run.generated_claims:
            try:
                self._kube.delete(gvr.RESOURCE_CLAIMS, name, ns)
            except NotFound:
                pass
        self._pods.pop(run.uid, None)
        return True

    def _reap(self, live_by_uid: dict[str, dict]) -> None:
        for uid in list(self._pods):
            if uid not in live_by_uid:
                self._shutdown_pod(self._pods[uid])

    def _teardown(self) -> None:
        # Bounded wait for in-flight prepares (each RPC self-bounds at the
        # client timeout); anything still live after that is abandoned.
        deadline = time.monotonic() + 40
        while self._pods and time.monotonic() < deadline:
            for run in list(self._pods.values()):
                self._shutdown_pod(run)
            if self._pods:
                time.sleep(0.2)
        for cli in self._dra_clients.values():
            try:
                cli.close()
            except Exception:  # noqa: BLE001
                # Teardown must visit every client even when one close
                # fails, but a failure is still worth a line: a wedged
                # channel here has masked real plugin shutdown bugs.
                logger.warning("closing DRA client failed", exc_info=True)


def parse_config(path: str) -> tuple[str, list[NodeConfig], dict]:
    with open(path) as f:
        cfg = json.load(f)
    nodes = [NodeConfig.from_dict(d) for d in cfg.get("nodes", [])]
    return cfg.get("server", ""), nodes, {
        k: str(v) for k, v in cfg.get("env", {}).items()
    }
